"""Lint rules over jaxprs, compiled HLO, and partition metadata.

Each lint encodes one invariant the repo's performance/correctness story
depends on but that nothing used to CHECK mechanically:

- ``host-transfer``      — no host callbacks / infeed / outfeed inside a
                           jitted hot path (a `jax.debug.print` left in a
                           train step serializes every device step
                           through Python).
- ``missing-donation``   — a hot-loop step that re-binds its state every
                           iteration must donate the old buffers, or
                           peak memory doubles silently.
- ``compress-wire``      — under `comm.compress`, every wide collective
                           operand must ride the 1-byte (or configured)
                           wire; a 4-byte gradient payload collective
                           means the compressed path silently fell back
                           to exact sync.
- ``dead-rule``          — a USER partition rule matching zero leaves is
                           a typo'd pattern whose layer silently fell
                           through to the built-ins.
- ``replicated-fallthrough`` — under a model-sharded (tp) rule set, a
                           large leaf that only the catch-all matched
                           and that ended up replicated: the rule
                           vocabulary doesn't know this parameter.
- ``replicated-residency`` — under fsdp (params+opt) / zero1 (opt) rule
                           sets, a large shardable leaf living fully
                           replicated defeats the memory story the rule
                           set exists for.
- ``unplanned-reshard``  — a major collective whose (kind, axes)
                           signature is not derivable from the rule
                           set's data/model axis roles: a fall-through
                           or user rule forcing a replication
                           round-trip (all-gather + re-slice) inside
                           the step.
- ``reused-prng-key``    — the same PRNG key consumed by two samplers in
                           one traced fn produces correlated "random"
                           numbers; keys must be `split`/`fold_in`-
                           derived per use.

`run_lints(program)` runs every applicable lint over one
`programs.AnalysisProgram`; each lint is also usable standalone on raw
(fn, args) pairs via the jaxpr/HLO helpers.  Findings are data
(`Finding`), so tests can seed one violation per lint and assert exactly
that finding fires — and the CLI can gate CI on an empty list.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from tpu_dist.analysis.plan import KIND_CLASS, MINOR_ELEMS, itemsize

# Leaves below this many elements never trigger the residency /
# fallthrough lints — biases and norm scales are replicated by design.
BIG_LEAF_ELEMS = 4096

# jaxpr primitives that CONSUME a PRNG key (draw bits from it), vs the
# DERIVATION primitives that mint new keys and are safe to call many
# times on one parent key.
_SAMPLERS = frozenset({"random_bits", "random_gamma"})
_DERIVERS = frozenset({
    "random_split", "random_fold_in", "random_clone", "random_unwrap",
})

# jaxpr primitives that round-trip through the host.
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call", "debug_print",
})

# HLO ops / custom-call targets that stage through the host.
HOST_OPS = ("infeed", "outfeed", "copy-to-host", "copy-from-host")
_CALLBACK_TARGETS = ("callback", "host")


@dataclass(frozen=True)
class Finding:
    """One lint hit.  ``severity`` is 'error' (CI-gating) or 'warning'."""

    lint: str
    program: str
    message: str
    severity: str = "error"
    detail: dict = field(default_factory=dict, compare=False)

    def __str__(self) -> str:
        return f"[{self.lint}] {self.program}: {self.message}"


# ------------------------------------------------------- jaxpr traversal


def _subjaxprs(eqn):
    """(closed) jaxprs hiding in an eqn's params, with a best-effort map
    of eqn operand positions -> subjaxpr invar positions."""
    prim = eqn.primitive.name
    found = []
    for value in eqn.params.values():
        vals = value if isinstance(value, (tuple, list)) else (value,)
        for v in vals:
            jx = getattr(v, "jaxpr", None)
            if jx is None and hasattr(v, "eqns"):
                jx = v
            if jx is not None and hasattr(jx, "eqns"):
                found.append(jx)
    maps = []
    for jx in found:
        n_in = len(jx.invars)
        if prim in ("cond", "switch"):
            # first eqn operand is the branch index
            offsets = list(range(1, 1 + n_in))
        else:
            # pjit / closed_call / scan / while / custom_* bind their
            # operands 1:1 (tail-aligned when lengths differ)
            offsets = list(range(len(eqn.invars) - n_in, len(eqn.invars)))
        maps.append((jx, offsets))
    return maps


def _walk_jaxprs(jaxpr, visit, scope=()):
    """Depth-first over a jaxpr and every subjaxpr; ``visit(jaxpr,
    scope)`` per jaxpr, scope = tuple of enclosing call names."""
    visit(jaxpr, scope)
    for eqn in jaxpr.eqns:
        name = eqn.params.get("name") or eqn.primitive.name
        for sub, _ in _subjaxprs(eqn):
            _walk_jaxprs(sub, visit, scope + (str(name),))


def _is_key_var(v) -> bool:
    try:
        import jax

        return jax.dtypes.issubdtype(v.aval.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def _key_consumption(jaxpr, reused: list, scope=()):
    """Per-invar consumption counts for one jaxpr, recursing through
    call-like primitives; appends (scope, var, count) to ``reused`` for
    every var consumed more than once WITHIN one scope."""
    counts: dict[Any, int] = {}
    alias: dict[Any, Any] = {}

    def root(v):
        while v in alias:
            v = alias[v]
        return v

    def bump(v, n=1):
        v = root(v)
        counts[v] = counts.get(v, 0) + n

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in _SAMPLERS:
            for v in eqn.invars:
                if hasattr(v, "aval") and (
                    _is_key_var(v) or root(v) is not v
                ):
                    bump(v)
        elif prim == "random_wrap":
            # u32 raw key -> typed key: consumption of the wrapped key
            # attributes back to the raw operand
            if eqn.invars and eqn.outvars:
                alias[eqn.outvars[0]] = eqn.invars[0]
        elif prim in _DERIVERS:
            pass  # deriving new keys is the SAFE way to reuse a parent
        else:
            subs = _subjaxprs(eqn)
            name = str(eqn.params.get("name") or prim)
            for sub, offsets in subs:
                inner = _key_consumption(sub, reused, scope + (name,))
                for pos, n in inner.items():
                    if n and 0 <= offsets[pos] < len(eqn.invars):
                        v = eqn.invars[offsets[pos]]
                        if hasattr(v, "aval"):
                            bump(v, n)
    invar_counts = {}
    for i, v in enumerate(jaxpr.invars):
        invar_counts[i] = counts.pop(root(v), 0)
    for v, n in counts.items():
        if n > 1:
            reused.append((scope, str(v), n))
    # an invar consumed >1 time inside THIS jaxpr is reported by the
    # caller (it owns the var's name) — unless this is the top level
    for i, n in invar_counts.items():
        if n > 1 and scope == ():
            reused.append((scope, f"arg{i}", n))
    return invar_counts


def find_reused_keys(fn, args) -> list[dict]:
    """Key-reuse sites of a traceable fn on example args: the same PRNG
    key var feeding ≥2 sampling primitives within one traced scope
    (derivation via split/fold_in does not count)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    reused: list = []
    _key_consumption(jaxpr, reused)
    return [
        {"scope": "/".join(scope) or "<top>", "var": var, "uses": n}
        for scope, var, n in reused
    ]


def find_callbacks(fn, args) -> list[str]:
    """Host-callback primitives anywhere in the traced jaxpr."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    hits: list[str] = []

    def visit(jx, scope):
        for eqn in jx.eqns:
            if eqn.primitive.name in _CALLBACK_PRIMS:
                hits.append(
                    ("/".join(scope) or "<top>") + ":" + eqn.primitive.name
                )

    _walk_jaxprs(jaxpr, visit)
    return hits


# ---------------------------------------------------------------- lints


def lint_host_transfer(prog) -> list[Finding]:
    """No host round-trips inside the compiled hot path: callback
    primitives in the jaxpr, host ops / callback custom-calls in the
    HLO."""
    findings = []
    for hit in find_callbacks(prog.fn, prog.args):
        findings.append(
            Finding(
                lint="host-transfer",
                program=prog.name,
                message=f"host callback in traced fn: {hit}",
            )
        )
    txt = prog.hlo_text
    for op in HOST_OPS:
        n = len([
            line for line in txt.splitlines()
            if f" {op}(" in line or f" {op}-start(" in line
        ])
        if n:
            findings.append(
                Finding(
                    lint="host-transfer",
                    program=prog.name,
                    message=f"{n} {op} op(s) in the compiled program",
                )
            )
    for line in txt.splitlines():
        if "custom-call" not in line or "custom_call_target=" not in line:
            continue
        target = line.split('custom_call_target="', 1)[-1].split('"', 1)[0]
        if any(t in target.lower() for t in _CALLBACK_TARGETS):
            findings.append(
                Finding(
                    lint="host-transfer",
                    program=prog.name,
                    message=f"host-callback custom-call: {target}",
                )
            )
    return findings


def donated_buffer_count(hlo_text: str) -> int:
    """Input buffers the compiled module aliases to outputs (the
    ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` header
    donation produces) — brace-matched, since the entries themselves
    contain nested braces."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return 0
    i = hlo_text.find("{", start)
    depth = 0
    for j in range(i, len(hlo_text)):
        ch = hlo_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return hlo_text[i: j + 1].count("-alias")
    return 0


def lint_donation(prog) -> list[Finding]:
    """A program declared as a donating hot loop must actually alias its
    state buffers in the compiled module."""
    if not getattr(prog, "expect_donation", False):
        return []
    n = donated_buffer_count(prog.hlo_text)
    want = getattr(prog, "donated_leaves", None)
    if n == 0:
        return [
            Finding(
                lint="missing-donation",
                program=prog.name,
                message=(
                    "hot-loop state is not donated: compiled module "
                    "aliases no input buffer (peak memory holds both "
                    "old and new state)"
                ),
            )
        ]
    if want is not None and n < want:
        return [
            Finding(
                lint="missing-donation",
                program=prog.name,
                message=(
                    f"only {n} of {want} hot-loop buffers donated "
                    "(partial aliasing — some state still double-buffers)"
                ),
                severity="warning",
                detail={"aliased": n, "expected": want},
            )
        ]
    return []


def lint_compress_wire(prog) -> list[Finding]:
    """Under grad compression every wide collective operand must carry
    the configured wire dtype; anything wider-typed and larger than the
    per-bucket scales is a payload that escaped the compressed wire."""
    if getattr(prog, "compress", None) is None:
        return []
    expect = prog.compress_expectations
    max_wide = expect["max_wide_operand_elems"]
    wire_size = expect["wire_itemsize"]
    # Engine programs under non-dp rule sets legitimately all-gather
    # wide f32 PARAMS (fsdp entry gathers, sharded-update output
    # gathers); a gradient payload escaping the wire shows up as a wide
    # reduce-class or all-to-all collective either way.
    allow_gather = bool(expect.get("allow_wide_gather"))
    findings = []
    for c in prog.plan:
        if allow_gather and "gather" in c.kind:
            continue
        for dt, shape in zip(c.dtypes, c.shapes):
            elems = int(np.prod(shape)) if shape else 1
            if itemsize(dt) > wire_size and elems > max_wide:
                findings.append(
                    Finding(
                        lint="compress-wire",
                        program=prog.name,
                        message=(
                            f"{c.kind} ships a {dt}[{','.join(map(str, shape))}] "
                            f"operand ({elems} elems) — gradient payload "
                            f"off the {expect['wire']} wire (scales cap: "
                            f"{max_wide} elems)"
                        ),
                        detail={"kind": c.kind, "dtype": dt,
                                "elems": elems},
                    )
                )
    return findings


def lint_dead_rules(prog) -> list[Finding]:
    """User partition rules that matched no leaf (see
    `parallel.partition.dead_user_rules` — the build-time warning's
    lint twin)."""
    built = getattr(prog, "built", None)
    if built is None:
        return []
    return [
        Finding(
            lint="dead-rule",
            program=prog.name,
            message=(
                f"user partition rule {pattern!r} matches no parameter "
                "leaf — the layer it meant to pin fell through to the "
                "built-ins"
            ),
            detail={"pattern": pattern},
        )
        for pattern in getattr(built, "dead_rules", ())
    ]


def lint_replicated_fallthrough(prog) -> list[Finding]:
    """Under a model-sharded (tp) rule set, a big leaf that only the
    catch-all matched AND that stayed replicated: the rule vocabulary
    does not know this parameter, and it silently costs full-size
    memory on every chip."""
    built = getattr(prog, "built", None)
    if built is None or not built.ruleset.model_axes:
        return []
    from tpu_dist.parallel import partition as part

    rules = built.ruleset.param_rules
    report = part.rule_match_report(rules, built.params, built.mesh)
    catch_all = len(rules) - 1
    findings = []
    for leaf in report["leaves"]:
        if leaf["rule"] != catch_all or not leaf["replicated"]:
            continue
        if int(np.prod(leaf["shape"])) < BIG_LEAF_ELEMS:
            continue
        findings.append(
            Finding(
                lint="replicated-fallthrough",
                program=prog.name,
                message=(
                    f"leaf {leaf['path']!r} (shape {leaf['shape']}) fell "
                    "through to the replicated catch-all under the "
                    f"model-sharded rule set {built.ruleset.name!r}"
                ),
                detail={"path": leaf["path"],
                        "shape": list(leaf["shape"])},
            )
        )
    return findings


def lint_replicated_residency(prog) -> list[Finding]:
    """fsdp promises sharded params+opt state, zero1 promises sharded
    opt state — a big shardable leaf living fully replicated under
    those rule sets defeats the memory story."""
    built = getattr(prog, "built", None)
    if built is None:
        return []
    axes = {str(k) for k in built.mesh.axis_names}
    name = built.ruleset.name
    targets = []
    if "fsdp" in axes:
        targets = [("params", built.params, built.param_specs),
                   ("opt_state", built.opt_state, built.opt_specs)]
        shard_axes = [a for a in ("fsdp", "dp") if a in axes]
    elif name == "zero1" or (name or "").startswith("zero1"):
        targets = [("opt_state", built.opt_state, built.opt_specs)]
        shard_axes = ["dp"]
    else:
        return []
    import jax

    from jax.sharding import PartitionSpec as P

    from tpu_dist.parallel.partition import _key_name

    sizes = [int(built.mesh.shape[a]) for a in shard_axes]
    findings = []
    for what, tree, specs in targets:
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        for (kp, leaf), spec in zip(leaves, spec_leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            elems = int(np.prod(shape)) if shape else 1
            if elems < BIG_LEAF_ELEMS:
                continue
            if any(e is not None for e in tuple(spec)):
                continue  # sharded somewhere
            if not any(
                d % s == 0 for d in shape for s in sizes
            ):
                continue  # nothing divides: replication is forced
            path = "/".join(_key_name(k) for k in kp)
            findings.append(
                Finding(
                    lint="replicated-residency",
                    program=prog.name,
                    message=(
                        f"{what} leaf {path!r} (shape {shape}, {elems} "
                        f"elems) is fully replicated under rule set "
                        f"{name!r} — it could shard over "
                        f"{'/'.join(shard_axes)}"
                    ),
                    detail={"what": what, "path": path,
                            "shape": list(shape)},
                )
            )
    return findings


def _ruleset_roles(ruleset) -> dict[str, str]:
    """role -> BOUND mesh axis name for one `parallel.RuleSet`.  The
    rule-set ``name`` is role-based ('dp+fsdp', 'zero1', ...) in the
    same order the spec named its axes, which is also the order
    ``data_axes`` was built in — so zipping recovers the binding even
    when the trainers bound roles onto a differently-named mesh axis."""
    name = ruleset.name or ""
    if name == "zero1":
        data_roles = ["dp"]
    else:
        data_roles = [r for r in name.split("+") if r in ("dp", "fsdp")]
    roles = dict(zip(data_roles, ruleset.data_axes))
    if ruleset.model_axes:
        roles["tp"] = ruleset.model_axes[0]
    return roles


def lint_unplanned_reshard(prog) -> list[Finding]:
    """Every MAJOR collective of an engine program must be derivable
    from the rule set's data/model axis roles:

    - ``reduce`` class over any subset of the data+model axes — the
      gradient sync / tp partial sums the rule set plans;
    - ``gather`` class over the axes that legitimately shard persistent
      state or tp activations: the fsdp-role axis (param entry/exit
      gathers), the dp axis when the update is sharded (every rule set
      but plain dp — the ZeRO output gather), the model axes, and the
      data axes under compression (the quantized all-gather leg);
    - ``all-to-all`` over any planned axes: GSPMD rotating which axis a
      tensor shards over (same total bytes — strictly cheaper than the
      gather+re-slice it replaces) or the compressed wire's chunk
      exchange;
    - ``collective-permute`` never (the engine plans no rings).

    Anything else is a GSPMD-inserted reshard the configuration never
    asked for — the signature of a fall-through or user rule forcing a
    replication round-trip (all-gather + re-slice) inside the step,
    silently costing wire bytes every iteration."""
    built = getattr(prog, "built", None)
    if built is None:
        return []  # no rule-set context: nothing to derive from
    rs = built.ruleset
    roles = _ruleset_roles(rs)
    data = set(rs.data_axes)
    known = data | set(rs.model_axes)
    compressed = getattr(prog, "compress", None) is not None
    gather_ok = set(rs.model_axes)
    if "fsdp" in roles:
        gather_ok.add(roles["fsdp"])
    if rs.name != "dp" and "dp" in roles:
        gather_ok.add(roles["dp"])
    if compressed:
        gather_ok |= data
    findings = []
    for c in prog.plan:
        if c.minor or c.axes is None:
            continue  # scalar plumbing / unrecognized sub-ring groups
        axes = set(c.axes)
        kls = KIND_CLASS.get(c.kind, c.kind)
        if kls == "reduce":
            ok = axes <= known
        elif kls == "gather":
            ok = axes <= gather_ok
        elif kls == "all-to-all":
            # an a2a over planned axes is GSPMD ROTATING which axis a
            # tensor shards over (or the compressed wire's chunk
            # exchange) — same total bytes, strictly cheaper than the
            # gather+re-slice it replaces; only foreign axes flag
            ok = axes <= known
        else:  # permute — the engine plans no rings
            ok = False
        if not ok:
            findings.append(
                Finding(
                    lint="unplanned-reshard",
                    program=prog.name,
                    message=(
                        f"{c.kind} over {tuple(sorted(axes))} "
                        f"({c.dtype_key}, {c.bytes} B) is not derivable "
                        f"from rule set {rs.name!r} (data axes "
                        f"{tuple(rs.data_axes)}, model axes "
                        f"{tuple(rs.model_axes)}"
                        + (", compressed" if compressed else "")
                        + ") — a fall-through or user rule is forcing a "
                        "replication round-trip inside the step"
                    ),
                    detail={"kind": c.kind, "axes": sorted(axes),
                            "dtype": c.dtype_key, "bytes": c.bytes},
                )
            )
    return findings


def lint_reused_keys(prog) -> list[Finding]:
    """The same PRNG key consumed by ≥2 samplers in one traced scope."""
    return [
        Finding(
            lint="reused-prng-key",
            program=prog.name,
            message=(
                f"PRNG key {hit['var']} consumed {hit['uses']} times in "
                f"scope {hit['scope']} — streams are correlated; derive "
                "per-use keys with split/fold_in"
            ),
            detail=hit,
        )
        for hit in find_reused_keys(prog.fn, prog.args)
    ]


ALL_LINTS = {
    "host-transfer": lint_host_transfer,
    "missing-donation": lint_donation,
    "compress-wire": lint_compress_wire,
    "dead-rule": lint_dead_rules,
    "replicated-fallthrough": lint_replicated_fallthrough,
    "replicated-residency": lint_replicated_residency,
    "unplanned-reshard": lint_unplanned_reshard,
    "reused-prng-key": lint_reused_keys,
}


def run_lints(prog, lints=None) -> list[Finding]:
    """Every applicable lint over one program (a lint whose context the
    program lacks — no rule set, no compress config — returns nothing)."""
    out = []
    for name in lints or ALL_LINTS:
        out.extend(ALL_LINTS[name](prog))
    return out
