"""Static per-program memory plans — the HBM twin of the collective gate.

`analysis.plan` made the compiled WIRE structure a comparable artifact;
this module does the same for the compiled MEMORY structure.  OOM is
the dominant production failure mode, and until now the repo's memory
story was three disconnected hooks (`train.metrics.device_memory_stats`,
`compiled_memory_analysis`, `parallel.per_device_bytes`) with no plans,
no budgets and no gate.  Here:

- `extract_memory_plan(program)` turns XLA's
  ``compiled.memory_analysis()`` (argument / output / temp / alias /
  generated-code bytes — a compile-time property, available on every
  backend including CPU-sim) plus rule-engine STATE attribution
  (per-class resident shard bytes on device 0 via
  `parallel.state_bytes_by_class`: params / opt / EF-residual for
  engine programs, weights / KV-pool for the serving steps) into a
  per-rank `MemoryPlan` for any `analysis.AnalysisProgram`.
- ``peak_bytes`` is the plan's headline: arguments + outputs + temps +
  generated code, minus the aliased (donated) overlap — the
  steady-state high-water a rank needs to run this program.
- `save_memory_golden` / `load_memory_golden` /
  `compare_to_memory_golden` persist the plan under
  ``tests/goldens/memory/`` and compare row-exact (every byte field),
  with the analyzer's version-skew tolerance: exact byte counts are an
  XLA-lowering artifact, so a golden blessed under a different jax
  reports skew instead of failing the gate.
- The CLI (``python -m tpu_dist.analysis.memory`` / ``make memcheck``)
  runs the gate over the canonical programs — a PR that regresses a hot
  path's peak HBM fails CI with the offending field named.  ``--bless``
  regenerates (``make memcheck-bless``).

The live counterpart is `observe.memory` (watermark sampling, OOM
forensics): plans say what SHOULD be resident, the sampler says what
IS, and `observe.memory.record_oom` joins the two when a step path
hits RESOURCE_EXHAUSTED.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from tpu_dist.analysis import plan as plan_mod

# XLA's compiled memory sections, in plan/golden order.
XLA_FIELDS = (
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "alias_bytes",
    "generated_code_bytes",
)


def compiled_memory_stats(fn, args) -> dict | None:
    """XLA's memory plan for one jitted fn on example args (arrays or
    ShapeDtypeStructs — nothing executes, nothing is donated): the
    `XLA_FIELDS` section bytes, or None where the backend exposes no
    `memory_analysis` (the plan then carries null XLA rows and the
    golden gate compares state rows only)."""
    import jax

    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    try:
        ma = fn.lower(*args).compile().memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }


@dataclass
class MemoryPlan:
    """The per-rank memory footprint of one compiled program.

    ``xla``: the compiled sections (`XLA_FIELDS`; values may be None on
    backends without `memory_analysis`).  ``state``: resident
    ``[{class, bytes}]`` rows attributed by the rule engine — what the
    arguments ARE (params vs opt vs EF residual vs KV pool), which the
    XLA section totals cannot say.  All numbers are PER-RANK shard
    bytes, same convention as `parallel.per_device_bytes`."""

    program: str
    mesh_axes: dict = field(default_factory=dict)
    xla: dict = field(default_factory=dict)
    state: list = field(default_factory=list)

    @property
    def peak_bytes(self) -> int | None:
        """The plan's headline: steady-state high-water per rank —
        arguments + outputs + temps + generated code minus the aliased
        (donated output reuses argument buffer) overlap.  None when the
        backend reported no sections."""
        vals = [self.xla.get(k) for k in XLA_FIELDS]
        if any(v is None for v in vals):
            return None
        arg, out, temp, alias, code = vals
        return int(arg + out + temp + code - alias)

    def state_bytes(self, cls: str) -> int | None:
        for row in self.state:
            if row.get("class") == cls:
                return int(row["bytes"])
        return None

    def rows(self) -> list[dict]:
        """The golden format: one row per XLA section, one per state
        class, plus the derived peak."""
        rows = [
            {"kind": "xla", "name": k, "bytes": self.xla.get(k)}
            for k in XLA_FIELDS
        ]
        rows += [
            {"kind": "state", "name": r["class"], "bytes": int(r["bytes"])}
            for r in sorted(self.state, key=lambda r: r["class"])
        ]
        rows.append({"kind": "derived", "name": "peak_bytes",
                     "bytes": self.peak_bytes})
        return rows

    def summary(self) -> dict:
        return {
            "program": self.program,
            "mesh_axes": dict(self.mesh_axes),
            "peak_bytes": self.peak_bytes,
            "xla": dict(self.xla),
            "state": [dict(r) for r in self.state],
        }

    def to_json(self) -> str:
        return json.dumps(self.summary(), indent=2, sort_keys=True) + "\n"

    @classmethod
    def from_json(cls, text: str) -> "MemoryPlan":
        payload = json.loads(text)
        return cls(
            program=payload.get("program", ""),
            mesh_axes=payload.get("mesh_axes", {}),
            xla=payload.get("xla", {}),
            state=payload.get("state", []),
        )


# ------------------------------------------------------------- extraction


def state_rows(program) -> list[dict]:
    """Rule-engine attribution of a program's resident state: what the
    argument bytes ARE.  Engine train steps: params / opt /
    EF-residual shard bytes on device 0 of the program's mesh (the
    rule-set truth `parallel.per_device_bytes` reads off the live
    shards).  Serve steps: weights vs KV pool (the two big arguments
    of the decode/prefill programs).  Pipeline / plain programs: the
    first argument as params.  Unattributable programs return []."""
    from tpu_dist import parallel

    dev = None
    if program.mesh is not None:
        dev = program.mesh.devices.flat[0]
    built = getattr(program, "built", None)
    if built is not None:
        return parallel.state_bytes_by_class(
            built.params, built.opt_state, dev
        )
    args = tuple(getattr(program, "args", ()) or ())
    tags = tuple(getattr(program, "tags", ()) or ())
    if "serve" in tags and len(args) >= 2:
        return parallel.state_bytes_by_class(
            None, None, dev, weights=args[0], kv_pool=args[1]
        )
    if args:
        return parallel.state_bytes_by_class(args[0], None, dev)
    return []


def extract_memory_plan(program) -> "MemoryPlan":
    """The `MemoryPlan` of one `analysis.AnalysisProgram` (cached on
    the program like its collective plan — one compile per process)."""
    cache = getattr(program, "_cache", None)
    if cache is not None and "memory_plan" in cache:
        return cache["memory_plan"]
    xla = compiled_memory_stats(program.fn, program.args) or {
        k: None for k in XLA_FIELDS
    }
    axes = {}
    if program.mesh is not None:
        axes = {
            str(k): int(v)
            for k, v in zip(
                program.mesh.axis_names, program.mesh.devices.shape
            )
        }
    plan = MemoryPlan(
        program=program.name,
        mesh_axes=axes,
        xla=xla,
        state=state_rows(program),
    )
    if cache is not None:
        cache["memory_plan"] = plan
    return plan


# ---------------------------------------------------------------- goldens


def memory_goldens_dir(goldens_dir: str) -> str:
    """Memory goldens live in a ``memory/`` subdir of the collective
    goldens dir — same blessing workflow, separate namespace."""
    return os.path.join(goldens_dir, "memory")


def memory_golden_path(goldens_dir: str, program: str) -> str:
    return os.path.join(memory_goldens_dir(goldens_dir), f"{program}.json")


def save_memory_golden(plan: MemoryPlan, goldens_dir: str) -> str:
    """Bless ``plan`` as its program's memory golden.  Records the jax
    version: exact section bytes are an XLA-lowering artifact, so a
    different jax reports skew instead of failing
    (`analysis.plan.golden_version_skew` — the same tolerance the
    collective gate uses)."""
    import jax

    os.makedirs(memory_goldens_dir(goldens_dir), exist_ok=True)
    path = memory_golden_path(goldens_dir, plan.program)
    payload = dict(plan.summary())
    payload["jax_version"] = jax.__version__
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return path


def load_memory_golden(goldens_dir: str, program: str) -> dict | None:
    path = memory_golden_path(goldens_dir, program)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def compare_to_memory_golden(
    plan: MemoryPlan, golden: dict, *, tolerance: float = 0.0
) -> list[str]:
    """Differences between a live memory plan and its blessed golden
    (empty = pass).  Row-exact by default: every XLA section, every
    state class, and the derived peak must match byte-for-byte — a PR
    that grows a hot path's footprint fails with the field named.
    ``tolerance`` relaxes the gate to a relative band (e.g. 0.02 allows
    2% drift) without hiding NEW or VANISHED state classes."""
    diffs = []
    if dict(plan.mesh_axes) != dict(golden.get("mesh_axes", {})):
        diffs.append(
            f"mesh axes changed: {golden.get('mesh_axes')} -> "
            f"{dict(plan.mesh_axes)}"
        )
    gold_plan = MemoryPlan(
        program=golden.get("program", ""),
        mesh_axes=golden.get("mesh_axes", {}),
        xla=golden.get("xla", {}),
        state=golden.get("state", []),
    )
    live = {(r["kind"], r["name"]): r["bytes"] for r in plan.rows()}
    gold = {(r["kind"], r["name"]): r["bytes"] for r in gold_plan.rows()}
    for key in sorted(set(gold) - set(live)):
        diffs.append(f"memory row gone: {key[0]}/{key[1]} "
                     f"({gold[key]} bytes in golden)")
    for key in sorted(set(live) - set(gold)):
        diffs.append(f"new memory row: {key[0]}/{key[1]} "
                     f"({live[key]} bytes)")
    for key in sorted(set(live) & set(gold)):
        lv, gv = live[key], gold[key]
        if gv is None or lv is None:
            if lv != gv:
                diffs.append(
                    f"{key[0]}/{key[1]}: {gv} -> {lv} "
                    f"(section tracking changed)"
                )
            continue
        band = abs(gv) * tolerance
        if abs(lv - gv) > band:
            grew = lv > gv
            diffs.append(
                f"{key[0]}/{key[1]}: {gv:,} -> {lv:,} bytes "
                f"({'+' if grew else ''}{lv - gv:,}"
                + (f", tolerance ±{band:,.0f}" if tolerance else "")
                + ")"
            )
    return diffs


# -------------------------------------------------------------------- CLI


def _default_goldens() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "tests", "goldens")


def main(argv=None) -> int:
    """``make memcheck`` — the peak-HBM regression gate.  Mirrors the
    collective analyzer CLI: per-program plan print, golden compare
    (``--bless`` regenerates), version-skew waiver, ``memcheck``
    telemetry event, exit 1 on any diff or missing golden."""
    import argparse

    from tpu_dist.utils.platform import pin_cpu

    # Same bootstrap as the collective analyzer: plans are compile-time
    # artifacts, so the 8-device CPU-sim mesh is always enough.
    pin_cpu(8, opt_out_env="TPU_DIST_ANALYZE_TPU")

    from tpu_dist.analysis import programs as prog_mod
    from tpu_dist.observe import events as ev_mod

    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis.memory",
        description="per-program HBM memory plans + the golden gate",
    )
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset (default: all canonical)")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--goldens", default=_default_goldens(),
                    help="goldens root (memory goldens live in memory/)")
    ap.add_argument("--bless", action="store_true",
                    help="(re)write memory goldens instead of comparing")
    ap.add_argument("--no-goldens", action="store_true")
    ap.add_argument("--tolerance", type=float, default=0.0,
                    help="relative byte drift allowed per row (0 = exact)")
    ap.add_argument("--json", default=None, metavar="PATH")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for name in prog_mod.CANONICAL:
            print(name)
        return 0

    names = (
        [n.strip() for n in args.programs.split(",") if n.strip()]
        if args.programs
        else list(prog_mod.CANONICAL)
    )
    say = (lambda *a: None) if args.quiet else print

    failures = 0
    report: dict = {"programs": {}, "golden": {}}
    for name in names:
        prog = prog_mod.canonical_program(name)
        mplan = extract_memory_plan(prog)
        peak = mplan.peak_bytes
        say(f"== {name}  (peak "
            + (f"{peak:,} B" if peak is not None else "untracked")
            + ")")
        for r in mplan.rows():
            b = f"{r['bytes']:,} B" if r["bytes"] is not None else "--"
            say(f"   {r['kind']:<8} {r['name']:<22} {b}")
        report["programs"][name] = mplan.summary()
        if args.bless:
            path = save_memory_golden(mplan, args.goldens)
            say(f"   blessed -> {os.path.relpath(path)}")
            report["golden"][name] = "blessed"
        elif not args.no_goldens:
            golden = load_memory_golden(args.goldens, name)
            if golden is None:
                say("   MEMORY GOLDEN MISSING (run `make memcheck-bless`)")
                report["golden"][name] = "missing"
                failures += 1
            elif (skew := plan_mod.golden_version_skew(golden)) is not None:
                say(f"   GOLDEN VERSION SKEW: blessed under jax {skew} "
                    f"— re-bless under this version to re-arm the gate")
                report["golden"][name] = "version-skew"
            else:
                diffs = compare_to_memory_golden(
                    mplan, golden, tolerance=args.tolerance
                )
                for d in diffs:
                    say(f"   MEMORY DIFF: {d}")
                report["golden"][name] = "stale" if diffs else "ok"
                failures += len(diffs)

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        say(f"report -> {args.json}")

    states = set(report["golden"].values())
    ev_mod.from_env().emit(
        "memcheck",
        programs=len(names),
        golden=(
            "blessed" if "blessed" in states
            else "missing" if "missing" in states
            else "stale" if "stale" in states
            else "version-skew" if "version-skew" in states
            else "ok" if states else None
        ),
    )
    say(
        f"\nmemchecked {len(names)} programs: "
        + ("clean" if failures == 0 else f"{failures} failure(s)")
    )
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
