"""Collective-plan extraction from compiled XLA programs.

The reference tutorial's whole value was that you could READ the
distributed program — every send/recv of the hand-rolled ring allreduce
is right there in the source.  Our GSPMD programs hide their collectives
inside XLA: the partition engine (`parallel.partition`) emits whatever
wire structure the SPMD partitioner derives, and until now the only way
to see it was ad-hoc regexes over ``compile().as_text()``.

This module makes the compiled wire structure a first-class, comparable
artifact:

- `extract_plan(fn, args, mesh=...)` lowers + compiles a jitted program
  and parses every collective op (all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute, sync or async-start
  form) out of the post-optimization HLO into a `CollectivePlan`:
  operand dtypes, per-participant shapes and payload bytes, and — by
  matching the op's ``replica_groups`` / ``source_target_pairs`` against
  the mesh — the MESH AXES the collective runs over, recovering the
  axis names GSPMD compiled away.
- `diff_plans(a, b)` compares two plans at collective-STRUCTURE
  granularity: XLA is free to lower one logical reduce-scatter as
  ``all-reduce + slice`` (it does, on CPU), and free to combine or split
  per-leaf all-reduces, so the default comparison is over
  ``(kind-class, axes, dtype)`` signatures of the MAJOR collectives
  (kind-class folds all-reduce/reduce-scatter into ``reduce``; minor =
  every operand ≤ `MINOR_ELEMS` elements, i.e. scalar loss/predicate
  reductions and control plumbing).  ``strict=True`` adds per-signature
  op counts and payload bytes — the golden-file gate.
- `save_golden` / `load_golden` / `compare_to_golden` persist a plan's
  aggregated rows as JSON under ``tests/goldens/`` so a PR that changes
  the collective structure of a hot path fails CI with a readable plan
  diff (``make analyze`` / ``make analyze-bless``).

Shapes in a partitioned module are PER-DEVICE shard shapes, so
``Collective.bytes`` is the payload one participant feeds the op — the
honest "what does this op put on the wire" number (topology factors like
the ring's 2(n-1)/n are deliberately not applied; see
`comm.compress.FlatPlan.bytes_on_wire` for those).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from itertools import combinations
from typing import Any, Iterable

import numpy as np

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# Folding for cross-implementation comparison: XLA lowers a logical
# reduce-scatter as all-reduce + dynamic-slice on some backends, so the
# two are one CLASS for diffing purposes.
KIND_CLASS = {
    "all-reduce": "reduce",
    "reduce-scatter": "reduce",
    "all-gather": "gather",
    "all-to-all": "all-to-all",
    "collective-permute": "permute",
}

# An op every one of whose operands is at most this many elements is
# "minor": scalar loss/aux reductions, all-finite predicates, tiny
# resharding plumbing.  Excluded from default plan signatures.
MINOR_ELEMS = 16

# HLO element type -> itemsize (bytes).
_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e4m3": 1,
    "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def itemsize(dtype: str) -> int:
    """Bytes per element of an HLO element type (unknown types count 4,
    so an exotic dtype inflates rather than hides payload)."""
    return _ITEMSIZE.get(dtype, 4)


@dataclass(frozen=True)
class Collective:
    """One collective op of a compiled program.

    ``axes``: the mesh axes the op communicates over, recovered from its
    replica groups / permute pairs (None when no mesh was supplied or
    the groups match no axis combination — e.g. a sub-ring permute).
    ``dtypes``/``shapes``: per-operand element types and per-participant
    shapes.  ``bytes``: summed per-participant operand payload.
    """

    kind: str
    axes: tuple[str, ...] | None
    dtypes: tuple[str, ...]
    shapes: tuple[tuple[int, ...], ...]
    bytes: int
    elems: int

    @property
    def max_elems(self) -> int:
        """Largest single operand (elements) — the minor-op test."""
        return max(
            (int(np.prod(s)) if s else 1 for s in self.shapes), default=0
        )

    @property
    def minor(self) -> bool:
        return self.max_elems <= MINOR_ELEMS

    @property
    def dtype_key(self) -> str:
        return "+".join(sorted(set(self.dtypes))) or "?"

    def sig(self) -> tuple:
        """Comparison signature: (kind-class, axes, dtype)."""
        return (
            KIND_CLASS.get(self.kind, self.kind),
            self.axes if self.axes is not None else ("?",),
            self.dtype_key,
        )

    def summary(self) -> dict:
        return {
            "kind": self.kind,
            "axes": list(self.axes) if self.axes is not None else None,
            "dtypes": list(self.dtypes),
            "shapes": [list(s) for s in self.shapes],
            "bytes": self.bytes,
            "elems": self.elems,
        }


@dataclass
class CollectivePlan:
    """Every collective of one compiled program, in a canonical order."""

    name: str
    collectives: tuple[Collective, ...]
    mesh_axes: dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        self.collectives = tuple(
            sorted(
                self.collectives,
                key=lambda c: (
                    c.kind,
                    c.axes if c.axes is not None else ("~",),
                    c.dtype_key,
                    -c.bytes,
                    c.shapes,
                ),
            )
        )

    def __iter__(self):
        return iter(self.collectives)

    def __len__(self) -> int:
        return len(self.collectives)

    def count(self, kind: str | None = None) -> int:
        """Ops of ``kind`` (all collectives when None)."""
        if kind is None:
            return len(self.collectives)
        return sum(1 for c in self.collectives if c.kind == kind)

    def major(self) -> tuple[Collective, ...]:
        return tuple(c for c in self.collectives if not c.minor)

    def total_bytes(self, *, major_only: bool = True) -> int:
        src = self.major() if major_only else self.collectives
        return sum(c.bytes for c in src)

    def signatures(self, *, include_minor: bool = False) -> set:
        """The set of `(kind-class, axes, dtype)` signatures —
        `diff_plans`'s default comparison granularity."""
        return {
            c.sig()
            for c in self.collectives
            if include_minor or not c.minor
        }

    def rows(self) -> list[dict]:
        """Aggregated (kind, axes, dtype) rows — the golden format."""
        agg: dict[tuple, dict] = {}
        for c in self.collectives:
            key = (c.kind, c.axes, c.dtype_key)
            row = agg.setdefault(
                key,
                {
                    "kind": c.kind,
                    "axes": list(c.axes) if c.axes is not None else None,
                    "dtype": c.dtype_key,
                    "count": 0,
                    "bytes": 0,
                    "max_elems": 0,
                },
            )
            row["count"] += 1
            row["bytes"] += c.bytes
            row["max_elems"] = max(row["max_elems"], c.max_elems)
        return sorted(
            agg.values(),
            key=lambda r: (r["kind"], r["axes"] or ["~"], r["dtype"]),
        )

    def summary(self) -> dict:
        return {
            "program": self.name,
            "mesh_axes": dict(self.mesh_axes),
            "n_collectives": len(self.collectives),
            "total_bytes": self.total_bytes(major_only=False),
            "rows": self.rows(),
        }

    def to_json(self) -> str:
        payload = dict(self.summary())
        payload["collectives"] = [c.summary() for c in self.collectives]
        return json.dumps(payload, indent=2, sort_keys=True) + "\n"

    @classmethod
    def from_json(cls, text: str) -> "CollectivePlan":
        payload = json.loads(text)
        return cls(
            name=payload.get("program", ""),
            mesh_axes=payload.get("mesh_axes", {}),
            collectives=tuple(
                Collective(
                    kind=c["kind"],
                    axes=tuple(c["axes"]) if c["axes"] is not None else None,
                    dtypes=tuple(c["dtypes"]),
                    shapes=tuple(tuple(s) for s in c["shapes"]),
                    bytes=int(c["bytes"]),
                    elems=int(c["elems"]),
                )
                for c in payload.get("collectives", [])
            ),
        )


# ----------------------------------------------------------- HLO parsing


_OP_RE = re.compile(
    r"=\s*(?:\([^)]*\)|\S+)\s+"
    rf"({'|'.join(COLLECTIVE_OPS)})(?:-start)?\("
)
_OPERAND_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{} ]*\}\}|"
    r"\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")


def _parse_shape(dims: str) -> tuple[int, ...]:
    return tuple(int(d) for d in dims.split(",") if d)


def _parse_replica_groups(text: str) -> tuple[tuple[int, ...], ...]:
    """Both HLO renderings: explicit ``{{0,4},{1,5}}`` lists and iota
    ``[G,S]<=[dims]T(perm)`` form (arange over dims, transposed by perm,
    reshaped to G groups of S)."""
    if text.startswith("{{"):
        return tuple(
            tuple(int(x) for x in g.split(",") if x.strip())
            for g in re.findall(r"\{([\d, ]+)\}", text)
        )
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", text)
    if m is None:
        return ()
    gshape = _parse_shape(m.group(1))
    dims = _parse_shape(m.group(2))
    arr = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(3):
        arr = arr.transpose(_parse_shape(m.group(3)))
    return tuple(tuple(int(x) for x in g) for g in arr.reshape(gshape))


class _MeshIndex:
    """Axis lookup tables for one mesh: canonical replica-group sets →
    axis-name tuples, and per-axis ring permute pairs.  Group ids are
    POSITIONS in ``mesh.devices.flat`` order (XLA's device assignment
    for a jit over this mesh), not raw device ids."""

    def __init__(self, mesh):
        names = tuple(str(n) for n in mesh.axis_names)
        shape = tuple(int(s) for s in mesh.devices.shape)
        idx = np.arange(int(np.prod(shape))).reshape(shape)
        self.axes = dict(zip(names, shape))
        self.groups: dict[frozenset, tuple[str, ...]] = {}
        # larger subsets first so a size-1 axis collision resolves to
        # the SMALLEST axis set producing those groups
        for r in range(len(names), 0, -1):
            for subset in combinations(range(len(names)), r):
                moved = np.moveaxis(
                    idx, subset, range(len(shape) - r, len(shape))
                )
                size = int(np.prod([shape[i] for i in subset]))
                groups = moved.reshape(-1, size)
                key = frozenset(
                    frozenset(int(x) for x in g) for g in groups
                )
                self.groups[key] = tuple(names[i] for i in subset)
        self.rings: dict[str, set] = {}
        for i, name in enumerate(names):
            fwd = set(
                zip(
                    (int(x) for x in idx.flatten()),
                    (int(x) for x in np.roll(idx, -1, axis=i).flatten()),
                )
            )
            bwd = {(b, a) for a, b in fwd}
            self.rings[name] = fwd | bwd

    def axes_for_groups(self, groups) -> tuple[str, ...] | None:
        key = frozenset(frozenset(g) for g in groups if g)
        return self.groups.get(key)

    def axes_for_pairs(self, pairs) -> tuple[str, ...] | None:
        pairs = set(pairs)
        if not pairs:
            return None
        for name, ring in self.rings.items():
            if pairs <= ring:
                return (name,)
        return None


def parse_hlo_collectives(
    hlo_text: str, mesh=None
) -> tuple[Collective, ...]:
    """Every collective op of one HLO module text.  Counts the sync form
    and the ``-start`` half of async pairs (never the ``-done`` half)."""
    index = _MeshIndex(mesh) if mesh is not None else None
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        kind = m.group(1)
        operands = line[m.end():]
        operands = operands[: operands.find(")")]
        parsed = [
            (dt, _parse_shape(dims))
            for dt, dims in _OPERAND_RE.findall(operands)
        ]
        if not parsed:
            continue
        axes = None
        if index is not None:
            gm = _GROUPS_RE.search(line)
            pm = _PAIRS_RE.search(line)
            if gm is not None:
                axes = index.axes_for_groups(
                    _parse_replica_groups(gm.group(1))
                )
            elif pm is not None:
                pairs = [
                    tuple(int(x) for x in p.split(","))
                    for p in re.findall(r"\{(\d+,\d+)\}", pm.group(1))
                ]
                axes = index.axes_for_pairs(pairs)
        dtypes = tuple(dt for dt, _ in parsed)
        shapes = tuple(s for _, s in parsed)
        elems = sum(int(np.prod(s)) if s else 1 for s in shapes)
        nbytes = sum(
            (int(np.prod(s)) if s else 1) * itemsize(dt)
            for dt, s in parsed
        )
        out.append(
            Collective(
                kind=kind,
                axes=axes,
                dtypes=dtypes,
                shapes=shapes,
                bytes=nbytes,
                elems=elems,
            )
        )
    return tuple(out)


def compiled_text(fn, args: Iterable) -> str:
    """Post-optimization HLO of a jitted fn on example args (arrays or
    `jax.ShapeDtypeStruct`s — nothing executes).  A plain callable is
    jitted first (NOTE: that outer jit carries no donation, so pass the
    already-jitted step when donation is under test)."""
    if not hasattr(fn, "lower"):
        import jax

        fn = jax.jit(fn)
    return fn.lower(*args).compile().as_text()


def extract_plan(
    fn,
    args: Iterable,
    *,
    mesh=None,
    name: str = "",
    hlo_text: str | None = None,
) -> CollectivePlan:
    """The `CollectivePlan` of one jitted program.

    ``fn``/``args`` are lowered and compiled (pass ``hlo_text`` to reuse
    an existing compile); ``mesh`` enables axis-name recovery from
    replica groups.  Extraction is deterministic — retracing the same
    program yields the identical plan (tested)."""
    text = hlo_text if hlo_text is not None else compiled_text(fn, args)
    axes = {}
    if mesh is not None:
        axes = {
            str(k): int(v)
            for k, v in zip(mesh.axis_names, mesh.devices.shape)
        }
    return CollectivePlan(
        name=name,
        collectives=parse_hlo_collectives(text, mesh),
        mesh_axes=axes,
    )


# ------------------------------------------------------------------ diff


def _rename_axes(plan: CollectivePlan, rename: dict) -> CollectivePlan:
    if not rename:
        return plan
    return CollectivePlan(
        name=plan.name,
        mesh_axes={rename.get(k, k): v for k, v in plan.mesh_axes.items()},
        collectives=tuple(
            Collective(
                kind=c.kind,
                axes=tuple(rename.get(a, a) for a in c.axes)
                if c.axes is not None
                else None,
                dtypes=c.dtypes,
                shapes=c.shapes,
                bytes=c.bytes,
                elems=c.elems,
            )
            for c in plan.collectives
        ),
    )


def _sig_str(sig: tuple) -> str:
    kind, axes, dtype = sig
    return f"{kind} over {'x'.join(axes)} [{dtype}]"


def diff_plans(
    a: CollectivePlan,
    b: CollectivePlan,
    *,
    strict: bool = False,
    include_minor: bool = False,
    rename: dict | None = None,
) -> list[str]:
    """Human-readable differences between two plans (empty list = same
    collective plan).

    Default granularity: the `(kind-class, axes, dtype)` signature SETS
    of the major collectives — robust to XLA's freedom to combine
    per-leaf all-reduces or lower reduce-scatter as all-reduce+slice,
    which is what lets the partition engine's GSPMD program compare
    equal to the hand-written shard_map builders (the pinned
    engine-vs-legacy contract for dp/zero1/fsdp).  ``strict=True`` also
    compares per-signature op counts and payload bytes — the golden
    gate's granularity.  ``rename`` maps axis names of ``b`` onto
    ``a``'s vocabulary (e.g. ``{"data": "dp"}``)."""
    if rename:
        b = _rename_axes(b, rename)
    diffs = []
    sa = a.signatures(include_minor=include_minor)
    sb = b.signatures(include_minor=include_minor)
    for sig in sorted(sa - sb):
        diffs.append(f"only in {a.name or 'a'}: {_sig_str(sig)}")
    for sig in sorted(sb - sa):
        diffs.append(f"only in {b.name or 'b'}: {_sig_str(sig)}")
    if strict:
        def keyed(plan):
            rows = {}
            for c in plan.collectives:
                if not include_minor and c.minor:
                    continue
                k = c.sig()
                cnt, byt = rows.get(k, (0, 0))
                rows[k] = (cnt + 1, byt + c.bytes)
            return rows

        ra, rb = keyed(a), keyed(b)
        for sig in sorted(set(ra) & set(rb)):
            (ca, ba), (cb, bb) = ra[sig], rb[sig]
            if ca != cb:
                diffs.append(
                    f"{_sig_str(sig)}: {ca} ops in {a.name or 'a'} vs "
                    f"{cb} in {b.name or 'b'}"
                )
            if ba != bb:
                diffs.append(
                    f"{_sig_str(sig)}: {ba} payload bytes in "
                    f"{a.name or 'a'} vs {bb} in {b.name or 'b'}"
                )
    return diffs


# --------------------------------------------------------------- goldens


def golden_path(goldens_dir: str, program: str) -> str:
    return os.path.join(goldens_dir, f"{program}.json")


def save_golden(plan: CollectivePlan, goldens_dir: str) -> str:
    """Bless ``plan`` as the golden for its program (returns the path).
    The golden stores the AGGREGATED rows — (kind, axes, dtype, count,
    bytes, max_elems) — not per-op shapes, so a pure leaf-order change
    inside one signature does not churn the file.  The jax version the
    golden was blessed under is recorded: exact counts/bytes are an
    XLA-lowering artifact, so comparisons across versions are reported
    as skew, not failure (see `golden_version_skew`)."""
    import jax

    os.makedirs(goldens_dir, exist_ok=True)
    path = golden_path(goldens_dir, plan.name)
    payload = {
        "program": plan.name,
        "mesh_axes": dict(plan.mesh_axes),
        "jax_version": jax.__version__,
        "rows": plan.rows(),
    }
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return path


def load_golden(goldens_dir: str, program: str) -> dict | None:
    path = golden_path(goldens_dir, program)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def golden_version_skew(golden: dict) -> str | None:
    """The golden's blessed jax version when it differs from the running
    one, else None.  Row-exact counts/bytes are deterministic within one
    jax/XLA version but legitimately shift across versions (combiner and
    async-lowering decisions), so callers report skew instead of failing
    the gate — and re-bless under the new version."""
    import jax

    blessed = golden.get("jax_version")
    if blessed is not None and blessed != jax.__version__:
        return str(blessed)
    return None


def compare_to_golden(plan: CollectivePlan, golden: dict) -> list[str]:
    """Differences between a live plan and its blessed golden (empty =
    pass).  Row-exact: kind (NOT kind-class), axes, dtype, op count and
    payload bytes must all match — any change to a hot path's collective
    structure fails with the offending row named."""
    diffs = []
    if dict(plan.mesh_axes) != dict(golden.get("mesh_axes", {})):
        diffs.append(
            f"mesh axes changed: {golden.get('mesh_axes')} -> "
            f"{dict(plan.mesh_axes)}"
        )

    def key(row):
        axes = row["axes"]
        return (row["kind"], tuple(axes) if axes is not None else None,
                row["dtype"])

    live = {key(r): r for r in plan.rows()}
    gold = {key(r): r for r in golden.get("rows", [])}
    for k in sorted(set(gold) - set(live), key=repr):
        r = gold[k]
        diffs.append(
            f"collective gone: {r['kind']} over "
            f"{r['axes']} [{r['dtype']}] x{r['count']}"
        )
    for k in sorted(set(live) - set(gold), key=repr):
        r = live[k]
        diffs.append(
            f"new collective: {r['kind']} over "
            f"{r['axes']} [{r['dtype']}] x{r['count']} "
            f"({r['bytes']} bytes)"
        )
    for k in sorted(set(live) & set(gold), key=repr):
        lr, gr = live[k], gold[k]
        for fieldname in ("count", "bytes", "max_elems"):
            if gr.get(fieldname) is not None and lr[fieldname] != gr[fieldname]:
                diffs.append(
                    f"{lr['kind']} over {lr['axes']} [{lr['dtype']}]: "
                    f"{fieldname} {gr[fieldname]} -> {lr[fieldname]}"
                )
    return diffs
