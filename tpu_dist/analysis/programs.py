"""The canonical entry programs the analyzer runs over.

One `AnalysisProgram` per hot path the repo ships: the partition
engine's GSPMD train step under every built-in rule set (dp / zero1 /
fsdp / dp×fsdp / dp×tp), the engine's COMPRESSED gradient wire
(``engine_dp_int8`` / ``engine_dp_fsdp_int8`` — the s8 bucket
collectives must show up in the plan, and the `compress-wire` lint
consumes the engine FlatPlan's `analysis_expectations`), the 1F1B
pipeline engine, and the serving decode/prefill steps.  The legacy
shard_map strategy builders (and their engine-vs-legacy diff pins) are
gone: the pins held through PR 11 and the builders were deleted once
every trainer flag routed through the engine.

Models are deliberately tiny (a 2-layer MLP, a 2-block LM) — the
analyzer checks PROGRAM STRUCTURE, which does not depend on width, and
every program must compile in seconds on the CPU-sim mesh.  All
programs build lazily and cache per process (`canonical_program`), so
the CLI, the golden gate, and the test suite share one compile per
program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from tpu_dist.analysis import lints as lints_mod
from tpu_dist.analysis import plan as plan_mod

WORLD = 8
PIPE_WORLD = 4

# small buckets/blocks so the tiny MLP still ships several buckets —
# program STRUCTURE is what the analyzer checks, not wire volume
COMPRESS_SPEC = "int8,bucket_bytes=32768,block=64"


@dataclass
class AnalysisProgram:
    """One analyzable compiled program: a jitted fn + example args
    (arrays or ShapeDtypeStructs) plus whatever context the lints can
    use.  Lowering/compiling happens lazily and once."""

    name: str
    fn: Callable
    args: tuple
    mesh: Any = None
    built: Any = None            # PartitionedTrainStep (engine programs)
    compress: Any = None         # CompressConfig (compressed programs)
    compress_expectations: dict | None = None
    expect_donation: bool = False
    donated_leaves: int | None = None
    params: Any = None           # the param tree (leaf-count asserts)
    tags: tuple[str, ...] = ()
    notes: str = ""
    _cache: dict = field(default_factory=dict, repr=False)

    @property
    def hlo_text(self) -> str:
        if "hlo" not in self._cache:
            self._cache["hlo"] = plan_mod.compiled_text(self.fn, self.args)
        return self._cache["hlo"]

    @property
    def plan(self) -> plan_mod.CollectivePlan:
        if "plan" not in self._cache:
            self._cache["plan"] = plan_mod.extract_plan(
                self.fn, self.args, mesh=self.mesh, name=self.name,
                hlo_text=self.hlo_text,
            )
        return self._cache["plan"]

    def findings(self) -> list:
        if "findings" not in self._cache:
            self._cache["findings"] = lints_mod.run_lints(self)
        return self._cache["findings"]


def _n_leaves(tree) -> int:
    """Donation-eligible leaves: XLA reliably aliases array buffers but
    routinely declines 0-d scalars (e.g. the engine EF 'err' scalar) —
    counting them would turn an intact donation story into a spurious
    partial-aliasing warning."""
    import jax

    return sum(
        1 for leaf in jax.tree.leaves(tree)
        if getattr(leaf, "ndim", 0) > 0
    )


def _mlp_loss_pair():
    """The shared tiny model + loss both engine and legacy programs
    compile, so engine-vs-legacy plans are comparable."""
    import jax

    from tpu_dist import models, nn

    model = nn.Sequential([
        nn.flatten(), nn.Dense(48), nn.relu(), nn.Dense(10),
        nn.log_softmax(),
    ])
    params, state = model.init(jax.random.key(0), models.IN_SHAPE)

    def loss_fn(p, batch, key):
        x, y = batch
        scores, _ = model.apply(p, state, x, train=False)
        return nn.nll_loss(scores, y), {}

    return params, state, loss_fn, model


def _engine(spec: str, *, name: str, user_rules=None,
            donate: bool = True, compress=None) -> AnalysisProgram:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from tpu_dist import models, parallel, train

    mesh = parallel.build_mesh(spec, platform="cpu")
    rules = parallel.resolve_rules(spec, mesh, user_rules=user_rules)
    params, _, loss_fn, _ = _mlp_loss_pair()
    built = parallel.make_partitioned_train_step(
        loss_fn, train.sgd(0.05, momentum=0.5), mesh, params, rules,
        donate=donate, compress=compress,
    )
    sh = NamedSharding(mesh, rules.batch_spec())
    batch = (
        jax.device_put(
            jnp.zeros((2 * WORLD,) + models.IN_SHAPE, jnp.float32), sh
        ),
        jax.device_put(jnp.zeros((2 * WORLD,), jnp.int32), sh),
    )
    expectations = None
    if built.compress is not None:
        expectations = built.flat_plan.analysis_expectations()
        # Any rule set but plain dp legitimately all-gathers f32 PARAMS
        # (fsdp entry gathers, sharded-update output gathers) — only
        # reduce-class / all-to-all wide operands are gradient payloads
        # escaping the wire there.
        if rules.name != "dp":
            expectations["allow_wide_gather"] = True
    return AnalysisProgram(
        name=name,
        fn=built.step,
        args=(built.params, built.opt_state, batch, jax.random.key(0)),
        mesh=mesh,
        built=built,
        compress=built.compress,
        compress_expectations=expectations,
        expect_donation=donate,
        donated_leaves=(
            _n_leaves(built.params) + _n_leaves(built.opt_state)
        ) if donate else None,
        params=params,
        tags=("engine", "train") + (("compress",) if compress else ()),
    )


def _engine_dp_tp() -> AnalysisProgram:
    """dp×tp on the tiny LM — the Megatron rule vocabulary needs the
    transformer parameter names to bind to."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from tpu_dist import parallel, train
    from tpu_dist.models.transformer_lm import TransformerLM, lm_loss

    spec = "dp=4,tp=2"
    mesh = parallel.build_mesh(spec, platform="cpu")
    rules = parallel.resolve_rules(spec, mesh)
    lm = TransformerLM(vocab=64, dim=32, depth=2, heads=4, max_seq=32)
    params, state = lm.init(jax.random.key(0))

    def loss_fn(p, tokens, key):
        logits, _ = lm.apply(p, state, tokens, train=False)
        return lm_loss(logits.astype(jnp.float32), tokens), {}

    built = parallel.make_partitioned_train_step(
        loss_fn, train.sgd(0.05, momentum=0.5), mesh, params, rules,
        donate=True,
    )
    sh = NamedSharding(mesh, rules.batch_spec())
    tokens = jax.device_put(
        jnp.zeros((2 * 4, 16), jnp.int32) % 64, sh
    )
    return AnalysisProgram(
        name="engine_dp_tp",
        fn=built.step,
        args=(built.params, built.opt_state, tokens, jax.random.key(0)),
        mesh=mesh,
        built=built,
        expect_donation=True,
        donated_leaves=_n_leaves(built.params) + _n_leaves(built.opt_state),
        params=params,
        tags=("engine", "train", "tp"),
    )




def _pipeline_1f1b() -> AnalysisProgram:
    """The schedule-driven 1F1B engine (toy uniform stages): the plan
    must be the two neighbor ppermute rings + the gradient psum."""
    import jax
    import jax.numpy as jnp

    from tpu_dist import comm, parallel

    n, v, M, D = PIPE_WORLD, 1, 4, 8
    mesh = comm.make_mesh(n, ("pipe",), platform="cpu")
    sched = parallel.build_schedule(n, M, v, "1f1b")

    def stage_fn(p, x):
        return jax.nn.tanh(x @ p["w"] + p["b"])

    def last_fn(pc, hp, x_in, args):
        (t,) = args
        return jnp.mean((stage_fn(pc, x_in) * hp["g"] - t) ** 2)

    ks = jax.random.split(jax.random.key(0), n * v)
    stages = [
        {
            "w": jax.random.normal(k, (D, D)) / jnp.sqrt(D),
            "b": jax.random.normal(k, (D,)) * 0.1,
        }
        for k in ks
    ]
    nest = [[stages[c * n + s] for c in range(v)] for s in range(n)]
    stacked = parallel.stack_chunk_params(nest)
    hp = {"g": jnp.float32(1.3)}
    x = jax.random.normal(jax.random.key(1), (16, D))
    tgt = jax.random.normal(jax.random.key(2), (16, D))
    fn = parallel.engine_program(
        stage_fn, last_fn, sched, mesh, axis_name="pipe"
    )
    return AnalysisProgram(
        name="pipeline_1f1b",
        fn=fn,
        args=(stacked, hp, x, (tgt,)),
        mesh=mesh,
        tags=("pipeline", "train"),
    )


def _serve(which: str) -> AnalysisProgram:
    """The serving hot paths from a real `ServeEngine` over a tiny LM
    (single chip: the plan should be collective-free; the lints check
    donation, host transfers, and per-slot PRNG hygiene)."""
    import jax

    from tpu_dist.models.transformer_lm import TransformerLM
    from tpu_dist.serve.engine import ServeConfig, ServeEngine

    lm = TransformerLM(vocab=32, dim=16, depth=1, heads=2, max_seq=64)
    params, _ = lm.init(jax.random.key(0))
    eng = ServeEngine(
        lm, params,
        ServeConfig(max_batch=4, block_size=8, num_blocks=32, max_seq=64,
                    prefill_chunk=8, prefill_batch=2),
    )
    fn, args = eng.analysis_programs()[which]
    return AnalysisProgram(
        name=which,
        fn=fn,
        args=args,
        expect_donation=True,
        tags=("serve",),
    )


_BUILDERS: dict[str, Callable[[], AnalysisProgram]] = {
    "engine_dp": lambda: _engine(f"dp={WORLD}", name="engine_dp"),
    "engine_zero1": lambda: _engine(
        f"zero1:dp={WORLD}", name="engine_zero1"
    ),
    "engine_fsdp": lambda: _engine(f"fsdp={WORLD}", name="engine_fsdp"),
    "engine_dp_fsdp": lambda: _engine(
        "dp=2,fsdp=4", name="engine_dp_fsdp"
    ),
    "engine_dp_tp": _engine_dp_tp,
    "engine_dp_int8": lambda: _engine(
        f"dp={WORLD}", name="engine_dp_int8", compress=COMPRESS_SPEC
    ),
    "engine_dp_fsdp_int8": lambda: _engine(
        "dp=2,fsdp=4", name="engine_dp_fsdp_int8", compress=COMPRESS_SPEC
    ),
    "pipeline_1f1b": _pipeline_1f1b,
    "serve_decode": lambda: _serve("serve_decode"),
    "serve_prefill": lambda: _serve("serve_prefill"),
}

CANONICAL = tuple(_BUILDERS)

_cache: dict[str, AnalysisProgram] = {}


def canonical_program(name: str) -> AnalysisProgram:
    """Build (once per process) one canonical program by name."""
    if name not in _BUILDERS:
        raise ValueError(
            f"unknown analysis program {name!r}; one of {list(_BUILDERS)}"
        )
    if name not in _cache:
        _cache[name] = _BUILDERS[name]()
    return _cache[name]


def canonical_programs(names=None) -> dict[str, AnalysisProgram]:
    """The selected (default: all) canonical programs, cached."""
    return {n: canonical_program(n) for n in (names or CANONICAL)}


def fresh_program(name: str) -> AnalysisProgram:
    """Build an UNCACHED instance of a canonical program — for callers
    that EXECUTE it (e.g. `observe.attribution` step timing): the engine
    train steps donate their params/opt-state args, so running the
    shared cached instance would consume buffers other consumers (the
    golden gate, the lints) still hold."""
    if name not in _BUILDERS:
        raise ValueError(
            f"unknown analysis program {name!r}; one of {list(_BUILDERS)}"
        )
    return _BUILDERS[name]()
