"""`tpu_dist.comm` — communication core (L0-L2 of SURVEY.md §1).

Mesh construction (process-group analog), collectives over mesh axes, p2p
via ppermute, sub-groups, and process bootstrap.
"""

from tpu_dist.comm.collectives import (
    Group,
    ReduceOp,
    all_gather,
    all_reduce,
    all_reduce_quantized,
    all_to_all,
    barrier,
    broadcast,
    gather,
    new_group,
    rank,
    reduce,
    reduce_scatter,
    ring_perm,
    scatter,
    send,
    sendrecv,
    shift,
    world_size,
)
from tpu_dist.comm import compress
from tpu_dist.comm.compress import CompressConfig, compressed_all_reduce
from tpu_dist.comm.launch import launch
from tpu_dist.comm.init import (
    InitConfig,
    init,
    process_count,
    process_rank,
)
from tpu_dist.comm.mesh import DEFAULT_AXIS, devices, make_mesh, world_mesh
from tpu_dist.comm.runner import spmd

__all__ = [
    "DEFAULT_AXIS",
    "CompressConfig",
    "Group",
    "InitConfig",
    "ReduceOp",
    "all_gather",
    "all_reduce",
    "all_reduce_quantized",
    "all_to_all",
    "barrier",
    "broadcast",
    "compress",
    "compressed_all_reduce",
    "devices",
    "gather",
    "init",
    "launch",
    "make_mesh",
    "new_group",
    "reduce_scatter",
    "ring_perm",
    "process_count",
    "process_rank",
    "rank",
    "reduce",
    "scatter",
    "send",
    "sendrecv",
    "shift",
    "spmd",
    "world_mesh",
    "world_size",
]
