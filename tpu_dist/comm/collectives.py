"""The collective-communication API — TPU-native rebuild of the tutorial's
``torch.distributed`` surface.

Every call here is designed to be used *inside* SPMD code (under
``shard_map`` over a mesh axis, see `tpu_dist.comm.runner.spmd`): each
program instance is the analog of one reference "rank", and the collectives
lower to XLA HLO collectives (AllReduce, AllGather, CollectivePermute) that
ride ICI between chips — compiled into the program, not interpreted per-call
the way THD dispatches each ``dist.*`` invocation (tuto.md:404-419).

Coverage of the reference API catalog (tuto.md:176-202):

- ``all_reduce`` with ``ReduceOp.{SUM, PRODUCT, MAX, MIN}``
  (reduce_op enum, tuto.md:190-193)
- ``reduce`` (root semantics are post-hoc on a symmetric collective —
  TPU collectives have no privileged root)
- ``broadcast``, ``scatter``, ``gather``, ``all_gather``
- sub-groups via ``new_group`` (tuto.md:178-186)
- point-to-point ``send``/``shift``/``sendrecv`` over ``lax.ppermute``
  (tuto.md:79-121); blocking semantics are native — an SPMD program is
  lockstep by construction, and "immediate" isend/irecv maps to XLA's
  async dispatch with data-flow ordering playing the role of ``wait()``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.mesh import DEFAULT_AXIS


class ReduceOp(enum.Enum):
    """The four reduction ops the tutorial teaches (tuto.md:190-193)."""

    SUM = "sum"
    PRODUCT = "product"
    MAX = "max"
    MIN = "min"


@dataclass(frozen=True)
class Group:
    """A communication sub-group — ``dist.new_group([ranks])`` analog.

    The reference builds groups as subsets of WORLD (tuto.md:178-186).
    Semantics everywhere: members communicate among themselves only;
    non-members pass their input through unchanged (matching torch, where
    non-members don't participate).  Reductions (all_reduce SUM/MAX/MIN,
    reduce, broadcast) lower to a NATIVE grouped AllReduce — the group
    plus one singleton per non-member is a valid unequal-size
    ``axis_index_groups`` partition, so wire traffic is O(group).  Only
    PRODUCT (no XLA reduce primitive) and the shape-changing collectives
    (gather/scatter/all_gather, whose grouped XLA forms require
    equal-size groups) use an all-gather + mask path.
    """

    ranks: tuple[int, ...]

    def __post_init__(self):
        object.__setattr__(self, "ranks", tuple(sorted(set(self.ranks))))

    def is_member(self, axis_name: str = DEFAULT_AXIS):
        return jnp.isin(lax.axis_index(axis_name), jnp.array(self.ranks))

    def mask(self, n: int) -> jnp.ndarray:
        return jnp.isin(jnp.arange(n), jnp.array(self.ranks))


def new_group(ranks: Sequence[int]) -> Group:
    """``dist.new_group(ranks)`` analog (tuto.md:180)."""
    return Group(tuple(ranks))


def rank(axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """``dist.get_rank()`` inside SPMD code."""
    return lax.axis_index(axis_name)


def world_size(axis_name: str = DEFAULT_AXIS) -> int:
    """``dist.get_world_size()`` inside SPMD code (static under trace)."""
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------

_IDENTITY = {
    ReduceOp.SUM: 0.0,
    ReduceOp.PRODUCT: 1.0,
    ReduceOp.MAX: -jnp.inf,
    ReduceOp.MIN: jnp.inf,
}


def _masked_identity(op: ReduceOp, dtype) -> jax.Array:
    ident = _IDENTITY[op]
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        ident = {
            ReduceOp.SUM: 0,
            ReduceOp.PRODUCT: 1,
            ReduceOp.MAX: info.min,
            ReduceOp.MIN: info.max,
        }[op]
    return jnp.asarray(ident, dtype)


def _reduce_stacked(stacked: jax.Array, op: ReduceOp) -> jax.Array:
    if op is ReduceOp.SUM:
        return stacked.sum(axis=0)
    if op is ReduceOp.PRODUCT:
        return stacked.prod(axis=0)
    if op is ReduceOp.MAX:
        return stacked.max(axis=0)
    if op is ReduceOp.MIN:
        return stacked.min(axis=0)
    raise ValueError(f"unknown op {op}")


def all_reduce(
    x: jax.Array,
    op: ReduceOp = ReduceOp.SUM,
    axis_name: str = DEFAULT_AXIS,
    *,
    group: Group | None = None,
) -> jax.Array:
    """``dist.all_reduce(tensor, op, group)`` (tuto.md:182-186).

    WORLD reductions lower directly to XLA AllReduce (psum/pmax/pmin),
    and so do sub-group SUM/MAX/MIN: the group plus one singleton per
    non-member is a valid (unequal-size) ``axis_index_groups`` partition —
    members reduce over the group while each singleton's "reduction" is
    its own input, which IS torch's non-member passthrough.  Wire traffic
    stays O(group), not O(world).  PRODUCT (no XLA primitive) takes an
    all-gather + on-device reduction.  Known answer: all_reduce of ones
    over n ranks with SUM prints n (tuto.md:184-185).
    """
    if group is None:
        if op is ReduceOp.SUM:
            return lax.psum(x, axis_name)
        if op is ReduceOp.MAX:
            return lax.pmax(x, axis_name)
        if op is ReduceOp.MIN:
            return lax.pmin(x, axis_name)
        stacked = lax.all_gather(x, axis_name, axis=0)
        return _reduce_stacked(stacked, op)
    n = lax.axis_size(axis_name)
    if group.ranks and not (0 <= min(group.ranks) and max(group.ranks) < n):
        raise ValueError(
            f"group ranks {group.ranks} out of range for world size {n}"
        )
    if not group.ranks:
        return x
    if op is not ReduceOp.PRODUCT:
        groups = _group_partition(group, n)
        if op is ReduceOp.SUM:
            return lax.psum(x, axis_name, axis_index_groups=groups)
        if op is ReduceOp.MAX:
            return lax.pmax(x, axis_name, axis_index_groups=groups)
        return lax.pmin(x, axis_name, axis_index_groups=groups)
    stacked = lax.all_gather(x, axis_name, axis=0)
    mask = group.mask(n).reshape((n,) + (1,) * x.ndim)
    ident = _masked_identity(op, stacked.dtype)
    reduced = _reduce_stacked(jnp.where(mask, stacked, ident), op)
    return jnp.where(group.is_member(axis_name), reduced, x)


def _group_partition(group: Group, n: int) -> list[list[int]]:
    """``axis_index_groups`` partition for a sub-group collective: the
    group itself + a singleton per non-member (XLA allows unequal-size
    AllReduce replica groups; a singleton reduction is passthrough)."""
    members = set(group.ranks)
    return [list(group.ranks)] + [[r] for r in range(n) if r not in members]


def _check_root(root: int, axis_name: str, what: str) -> None:
    n = lax.axis_size(axis_name)
    if not 0 <= root < n:
        raise ValueError(
            f"{what} root {root} out of range for world size {n} — a "
            f"masked select would silently produce zeros/passthrough"
        )


def reduce(
    x: jax.Array,
    dst: int,
    op: ReduceOp = ReduceOp.SUM,
    axis_name: str = DEFAULT_AXIS,
    *,
    group: Group | None = None,
) -> jax.Array:
    """``dist.reduce(tensor, dst, op)`` — result stored at dst only
    (tuto.md:196).  TPU collectives are symmetric; "root" is a post-hoc
    select: dst receives the reduction, other ranks keep their input
    (torch leaves non-dst buffers unspecified; passthrough is our defined
    behavior).  With ``group``, dst must be a member (non-members must
    never observe the group's reduction).
    """
    _check_root(dst, axis_name, "reduce")
    if group is not None and dst not in group.ranks:
        raise ValueError(f"reduce dst {dst} not in group {group.ranks}")
    reduced = all_reduce(x, op, axis_name, group=group)
    return jnp.where(lax.axis_index(axis_name) == dst, reduced, x)


# ---------------------------------------------------------------------------
# Data movement
# ---------------------------------------------------------------------------


def broadcast(
    x: jax.Array,
    src: int,
    axis_name: str = DEFAULT_AXIS,
    *,
    group: Group | None = None,
) -> jax.Array:
    """``dist.broadcast(tensor, src)`` (tuto.md:195): all ranks end with
    src's value.  Implemented as a masked AllReduce (multicast is not a
    permutation, so ppermute can't express it; XLA fuses the mask).
    With ``group``, only members receive src's value (src must be a
    member); non-members keep their input, matching torch semantics.
    """
    _check_root(src, axis_name, "broadcast")
    contrib = jnp.where(lax.axis_index(axis_name) == src, x, jnp.zeros_like(x))
    if group is None:
        return lax.psum(contrib, axis_name)
    if src not in group.ranks:
        raise ValueError(f"broadcast src {src} not in group {group.ranks}")
    # Grouped AllReduce keeps the multicast on group members' wires only;
    # each non-member singleton just gets its own (masked) contribution
    # back, replaced by its input in the final select.
    value = lax.psum(
        contrib, axis_name,
        axis_index_groups=_group_partition(group, lax.axis_size(axis_name)),
    )
    return jnp.where(group.is_member(axis_name), value, x)


def all_gather(
    x: jax.Array,
    axis_name: str = DEFAULT_AXIS,
    *,
    axis: int = 0,
    tiled: bool = False,
    group: Group | None = None,
) -> jax.Array:
    """``dist.all_gather(tensor_list, tensor)`` (tuto.md:199): every rank
    receives the stacked contributions (shape ``(n, ...)`` on a new leading
    axis by default).  With ``group``, members receive the
    ``(len(group), ...)`` stack of member contributions (sorted by rank)
    and non-members receive zeros (``axis``/``tiled`` must be defaults)."""
    if group is None:
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    if axis != 0 or tiled:
        raise ValueError("group= supports the default axis=0, tiled=False")
    n = lax.axis_size(axis_name)
    stacked = lax.all_gather(x, axis_name, axis=0)  # (n, ...)
    members = jnp.array(group.ranks)
    member_stack = stacked[members]  # (len(group), ...)
    return jnp.where(
        group.is_member(axis_name), member_stack, jnp.zeros_like(member_stack)
    )


def gather(
    x: jax.Array,
    dst: int,
    axis_name: str = DEFAULT_AXIS,
    *,
    group: Group | None = None,
) -> jax.Array:
    """``dist.gather(tensor, dst, gather_list)`` (tuto.md:198; demoed at
    ptp.py:21-28): dst receives the stack of all contributions; other ranks
    receive zeros (torch gives them nothing — SPMD outputs are uniform, so
    "nothing" is zeros).  With ``group``, non-member rows of dst's stack
    are zeroed and only the (member) dst receives anything."""
    _check_root(dst, axis_name, "gather")
    stacked = lax.all_gather(x, axis_name, axis=0)
    if group is not None:
        if dst not in group.ranks:
            raise ValueError(f"gather dst {dst} not in group {group.ranks}")
        n = lax.axis_size(axis_name)
        mask = group.mask(n).reshape((n,) + (1,) * x.ndim)
        stacked = jnp.where(mask, stacked, jnp.zeros_like(stacked))
    return jnp.where(
        lax.axis_index(axis_name) == dst, stacked, jnp.zeros_like(stacked)
    )


def scatter(
    xs: jax.Array,
    src: int,
    axis_name: str = DEFAULT_AXIS,
    *,
    group: Group | None = None,
) -> jax.Array:
    """``dist.scatter(tensor, src, scatter_list)`` (tuto.md:197): src's i-th
    chunk (leading axis) lands on rank i.  Only src's ``xs`` matters; it is
    broadcast (chips share ICI bandwidth; XLA may optimize to a true
    scatter) and each rank slices its own chunk.  With ``group``, chunk i
    goes to the i-th member (src must be a member; non-members keep
    zeros); ``xs`` then carries ``len(group.ranks)`` chunks."""
    n = lax.axis_size(axis_name)
    expected = len(group.ranks) if group is not None else n
    if xs.shape[0] != expected:
        raise ValueError(
            f"scatter needs one leading-axis chunk per participant: got "
            f"xs.shape[0]={xs.shape[0]} for {expected} (torch raises on "
            f"mismatched scatter_list length too)"
        )
    if group is not None and src not in group.ranks:
        raise ValueError(f"scatter src {src} not in group {group.ranks}")
    from_src = broadcast(xs, src, axis_name)
    if group is None:
        return lax.dynamic_index_in_dim(
            from_src, lax.axis_index(axis_name), axis=0, keepdims=False
        )
    # member index of this rank within the (sorted) group, 0 for others
    r = lax.axis_index(axis_name)
    ranks = jnp.array(group.ranks)
    member_idx = jnp.argmax(ranks == r)
    chunk = lax.dynamic_index_in_dim(from_src, member_idx, 0, keepdims=False)
    return jnp.where(group.is_member(axis_name), chunk, jnp.zeros_like(chunk))


# ---------------------------------------------------------------------------
# Point-to-point (ppermute) — tuto.md:79-121
# ---------------------------------------------------------------------------


def reduce_scatter(
    x: jax.Array,
    op: ReduceOp = ReduceOp.SUM,
    axis_name: str = DEFAULT_AXIS,
    *,
    scatter_axis: int = 0,
) -> jax.Array:
    """Reduce across ranks, scatter the result: rank r gets chunk r
    (size ``dim / n``) of the reduction along ``scatter_axis`` — always
    tiled semantics, identical across ops.  The building block of the
    bandwidth-optimal allreduce (tuto.md:354 exercise); SUM lowers to XLA
    ReduceScatter via ``lax.psum_scatter``."""
    if op is ReduceOp.SUM:
        return lax.psum_scatter(
            x, axis_name, scatter_dimension=scatter_axis, tiled=True
        )
    reduced = all_reduce(x, op, axis_name)
    n = lax.axis_size(axis_name)
    if x.shape[scatter_axis] % n:
        raise ValueError(
            f"scatter axis {scatter_axis} size {x.shape[scatter_axis]} not "
            f"divisible by world size {n}"
        )
    piece = x.shape[scatter_axis] // n
    return lax.dynamic_slice_in_dim(
        reduced, lax.axis_index(axis_name) * piece, piece, scatter_axis
    )


def all_to_all(
    x: jax.Array,
    axis_name: str = DEFAULT_AXIS,
    *,
    split_axis: int,
    concat_axis: int,
) -> jax.Array:
    """All-to-all: split ``x`` into n chunks along ``split_axis``, send
    chunk i to rank i, concatenate what arrives along ``concat_axis``.
    The resharding primitive behind Ulysses-style sequence parallelism
    (`tpu_dist.parallel.ulysses_attention`)."""
    n = lax.axis_size(axis_name)
    if x.shape[split_axis] % n:
        raise ValueError(
            f"split axis {split_axis} size {x.shape[split_axis]} not "
            f"divisible by world size {n}"
        )
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


_WIRE_DTYPES = {
    # name -> (jnp dtype, max representable magnitude; None = scale-free
    # wire, the cast itself is the codec)
    "int8": ("int8", 127.0),
    "float8_e4m3": ("float8_e4m3fn", 448.0),
    "float8_e5m2": ("float8_e5m2", 57344.0),
    "bfloat16": ("bfloat16", None),
}

# Short spellings accepted wherever a wire dtype is named (configs, env
# vars, CLI flags) — one table shared with `comm.compress`.
WIRE_ALIASES = {
    "int8": "int8",
    "fp8": "float8_e4m3",
    "fp8_e4m3": "float8_e4m3",
    "float8_e4m3": "float8_e4m3",
    "fp8_e5m2": "float8_e5m2",
    "float8_e5m2": "float8_e5m2",
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
}


def _wire_spec(dtype: str):
    canon = WIRE_ALIASES.get(str(dtype).lower())
    if canon is None or canon not in _WIRE_DTYPES:
        raise ValueError(
            f"unknown wire dtype {dtype!r}; one of {sorted(set(WIRE_ALIASES))}"
        )
    name, maxv = _WIRE_DTYPES[canon]
    return jnp.dtype(name), maxv


def _quantize_wire(x: jax.Array, dtype: str) -> tuple[jax.Array, jax.Array]:
    wire, maxv = _wire_spec(dtype)
    if maxv is None:  # scale-free wire (bf16): the cast rounds
        return x.astype(wire), jnp.ones((), jnp.float32)
    scale = jnp.max(jnp.abs(x)) / maxv + 1e-30
    if wire == jnp.dtype("int8"):
        q = jnp.clip(jnp.round(x / scale), -maxv, maxv).astype(wire)
    else:  # fp8: the cast itself rounds; clip guards the saturating edge
        q = jnp.clip(x / scale, -maxv, maxv).astype(wire)
    return q, scale


def _quantize_i8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    return _quantize_wire(x, "int8")


def all_reduce_quantized(
    x: jax.Array,
    axis_name: str = DEFAULT_AXIS,
    *,
    dtype: str = "int8",
) -> jax.Array:
    """Bandwidth-compressed all-reduce: 8-bit payloads, O(size) wire
    traffic (EQuARX-style quantized collective — see PAPERS.md).

    ``dtype`` picks the wire format: ``"int8"`` (uniform grid over the
    chunk scale — best when magnitudes are homogeneous),
    ``"float8_e4m3"`` (relative precision over ~±448·scale — better for
    heavy-tailed gradients, the MXU-native fp8), ``"float8_e5m2"``
    (wider range, coarser mantissa) — all 1 byte/element — or
    ``"bfloat16"`` (scale-free cast, 2 bytes/element, ~2x less wire than
    f32 with bf16-mantissa accuracy).

    Structure mirrors the bandwidth-optimal allreduce: a quantized
    REDUCE-SCATTER (all_to_all of int8 chunks + per-chunk scales; each
    rank dequantizes and sums its chunk) followed by a quantized
    ALL-GATHER of the re-quantized reduced chunks.  Each rank ships
    ~2·(n-1)/n·size int8 bytes total — ~4× less than the f32 ring at any
    world size (the naive all-gather formulation would grow O(n·size) and
    lose to exact f32 beyond n≈8).

    Lossy: two quantization rounds put the error at ~1-2% of the TENSOR
    SCALE (max|result|) — absolute, not per-component, so near-zero
    entries carry the same absolute error.  Intended for gradient
    averaging, where that sits below gradient noise; use `all_reduce`
    where exactness matters.
    """
    from tpu_dist.utils.tree import pad_to_multiple

    wire, maxv = _wire_spec(dtype)
    n = lax.axis_size(axis_name)
    chunks = pad_to_multiple(x.reshape(-1), n).reshape(n, -1)  # chunk c -> rank c
    if maxv is None:  # scale-free wire (bf16): unit scales, the cast rounds
        scales = jnp.ones((n,), jnp.float32)
        q = chunks.astype(wire)
    else:
        # Per-chunk symmetric quantization (one scale per destination chunk).
        scales = jnp.max(jnp.abs(chunks), axis=1) / maxv + 1e-30
        scaled = chunks / scales[:, None]
        if wire == jnp.dtype("int8"):
            q = jnp.clip(jnp.round(scaled), -maxv, maxv).astype(wire)
        else:
            q = jnp.clip(scaled, -maxv, maxv).astype(wire)
    # Quantized reduce-scatter: rank r receives every rank's chunk r.
    q_in = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_in = lax.all_to_all(
        scales.reshape(n, 1), axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    reduced = jnp.einsum(
        "nc,n->c", q_in.astype(jnp.float32), s_in[:, 0].astype(jnp.float32)
    )
    # Quantized all-gather of the reduced chunk.
    q2, s2 = _quantize_wire(reduced, dtype)
    q_all = lax.all_gather(q2, axis_name, axis=0)  # (n, C) 1-byte wire
    s_all = lax.all_gather(s2, axis_name, axis=0)  # (n,)
    total = (q_all.astype(jnp.float32) * s_all[:, None]).reshape(-1)
    return total[: x.size].reshape(x.shape).astype(x.dtype)


def ring_perm(n: int) -> list[tuple[int, int]]:
    """The neighbor ring: every rank sends right, receives from left
    (allreduce.py:18-20).  Shared by `shift`, the ring allreduce, and ring
    attention so the topology is defined once."""
    return [(i, (i + 1) % n) for i in range(n)]


def sendrecv(
    x: jax.Array,
    perm: Sequence[tuple[int, int]],
    axis_name: str = DEFAULT_AXIS,
) -> jax.Array:
    """Raw ``lax.ppermute``: each (src, dst) pair delivers src's x to dst;
    ranks receiving nothing get zeros.  This is the compiled-SPMD form of
    blocking send/recv (tuto.md:79-97): the collective permute is a
    lockstep step of the program, so "both processes stop until the
    communication is completed" holds by construction.
    """
    n = lax.axis_size(axis_name)
    for s, d in perm:
        if not (0 <= s < n and 0 <= d < n):
            raise ValueError(
                f"sendrecv pair ({s}, {d}) out of range for world size {n}"
            )
    return lax.ppermute(x, axis_name, perm)


def send(
    x: jax.Array, dst: int, src: int, axis_name: str = DEFAULT_AXIS
) -> jax.Array:
    """One ``dist.send(tensor, dst)`` / ``dist.recv(tensor, src)`` pair
    (tuto.md:85-90) as a single SPMD op: dst receives src's value; every
    other rank (src included) keeps its input unchanged — send buffers
    don't change, and non-participants are unaffected."""
    received = sendrecv(x, [(src, dst)], axis_name)
    return jnp.where(lax.axis_index(axis_name) == dst, received, x)


def shift(
    x: jax.Array, offset: int = 1, axis_name: str = DEFAULT_AXIS
) -> jax.Array:
    """Ring shift: every rank sends to ``(rank + offset) % n`` — the
    neighbor-exchange pattern of the ring allreduce (allreduce.py:18-20:
    ``left = (rank-1) % size; right = (rank+1) % size``)."""
    n = lax.axis_size(axis_name)
    if offset == 1:
        return lax.ppermute(x, axis_name, ring_perm(n))
    return lax.ppermute(x, axis_name, [(i, (i + offset) % n) for i in range(n)])


def barrier(axis_name: str = DEFAULT_AXIS) -> None:
    """``dist.barrier()`` analog. SPMD programs are lockstep at every
    collective, so this is a documentation-level no-op realized as a tiny
    psum (forces a synchronization point in the schedule)."""
    lax.psum(jnp.zeros((), jnp.int32), axis_name)
