"""Bucketed error-feedback compressed gradient sync (the wire engine).

`collectives.all_reduce_quantized` is a per-leaf collective: every
parameter tensor ships as its own quantized allreduce, with one scale for
the whole chunk and the quantization error thrown away.  This module is
the production form of that idea (EQuARX-style, PAPERS.md arxiv
2506.17615): the gradient pytree is flattened into fixed-size flat
BUCKETS (~4 MB of fp32 payload each, per-block scales inside), each
bucket ships exactly once over a quantized collective — int8,
float8_e4m3, float8_e5m2, or a scale-free bfloat16 wire — and the
quantization error is carried as an explicit ERROR-FEEDBACK residual
that is added back into the next step's gradient, so the compressed
trajectory converges like exact sync instead of accumulating bias.

Layout (`FlatPlan`): every leaf is flattened and zero-padded to ``(n,
k_leaf)`` rows exactly like `parallel.fsdp` stores its shards, the rows
concatenate into one ``(n, K)`` matrix (row r = the data destined to
rank r), and K pads up to a whole number of per-destination bucket
chunks.  That single layout serves BOTH wire patterns:

- ``all_reduce_rows``: per bucket, a quantized reduce-scatter
  (``all_to_all`` of 1-byte chunks + per-block scales, dequantize-sum in
  f32) followed by a quantized all-gather of the re-quantized reduced
  chunk — the bandwidth-optimal allreduce with 1-byte lanes.  Used by
  the replicated-DP step.
- ``reduce_scatter_rows``: the first half only — each rank ends with its
  f32-reduced row, which `FlatPlan.shard_rows` slices back into
  per-leaf ``(1, k)`` rows.  Half the wire cost of the allreduce; kept
  as a manual-sharding primitive (the retired fsdp/zero1 builders'
  gradient hop).

The production consumer is the PARTITION ENGINE:
`parallel.make_partitioned_train_step(compress=...)` runs
`all_reduce_rows` over the rule set's composed data axes inside its
GSPMD program (model-sharded leaves at their shard shape via a nested
shard_map over the model axes), with the EF residual as engine opt
state (`init_engine_ef_state` / `engine_residual_spec`).

Error feedback covers BOTH quantization rounds of the allreduce: the
local error ``acc - dequant(quant(acc))`` is fed back everywhere, and
rank r additionally feeds back the second-round (all-gather leg) error
of its own chunk — which it alone can compute exactly — so the engine's
only systematic loss is one step of delay on the residual.

Non-finite safety: NaN does NOT propagate through an int8 cast the way
it does through an exact psum, so a poisoned gradient could silently
corrupt the residual forever while shipping finite garbage.  Every
compressed sync therefore reduces a global all-finite predicate first
(one scalar psum); on a poisoned step the residual is held unchanged and
the OUTPUT gradients are NaN'd, so a `resilience.nan_guard` optimizer
skips the step exactly as it would under exact sync.

Config parsing (`parse` / `resolve`) rejects unknown wire dtypes at
config-parse time — a typo'd ``TPU_DIST_COMPRESS`` fails at trainer
construction, not at trace time deep inside a compiled step.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import WIRE_ALIASES, _wire_spec
from tpu_dist.comm.mesh import DEFAULT_AXIS

ENV_COMPRESS = "TPU_DIST_COMPRESS"

_OFF = ("", "off", "none", "0", "false")


@dataclass(frozen=True)
class CompressConfig:
    """How gradients ride the wire.

    ``wire``: canonical wire dtype name (see `WIRE_ALIASES`).
    ``bucket_bytes``: fp32 gradient payload per collective (~4 MB
    default); the engine issues O(total_bytes / bucket_bytes)
    collectives, each a fixed-size flat bucket.
    ``block``: elements per quantization scale inside a bucket (per-block
    scales bound the error to the BLOCK's dynamic range, not the
    tensor's).  Ignored by the scale-free bfloat16 wire.
    ``error_feedback``: carry the quantization error into the next step's
    gradient (on by default — turning it off is for ablations only).
    """

    wire: str = "int8"
    bucket_bytes: int = 4 << 20
    block: int = 256
    error_feedback: bool = True

    def __post_init__(self):
        canon = WIRE_ALIASES.get(str(self.wire).lower())
        if canon is None:
            raise ValueError(
                f"unknown compress wire dtype {self.wire!r}; one of "
                f"{sorted(set(WIRE_ALIASES))}"
            )
        object.__setattr__(self, "wire", canon)
        _wire_spec(canon)  # must exist in the collective wire table
        if self.bucket_bytes < 4:
            raise ValueError(f"bucket_bytes must be >= 4, got {self.bucket_bytes}")
        if self.block < 1:
            raise ValueError(f"block must be >= 1, got {self.block}")

    @property
    def wire_itemsize(self) -> int:
        return jnp.dtype(_wire_spec(self.wire)[0]).itemsize


def parse(spec) -> CompressConfig | None:
    """Parse a compress spec into a `CompressConfig` (or None = off).

    Accepts a `CompressConfig` (validated passthrough), None / "off" /
    "none" / "", a bare wire name (``"int8"``, ``"fp8"``, ``"bf16"``,
    ``"float8_e5m2"``), or a comma-form with knobs:
    ``"int8,bucket_mb=4,block=256,ef=1"``.  Unknown wire dtypes and
    malformed knobs raise HERE — config-parse time, not trace time.
    """
    if spec is None:
        return None
    if isinstance(spec, CompressConfig):
        return spec
    text = str(spec).strip().lower()
    if text in _OFF:
        return None
    parts = [p.strip() for p in text.split(",") if p.strip()]
    kw: dict[str, Any] = {"wire": parts[0]}
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(
                f"malformed compress option {part!r} in {spec!r} "
                f"(expected key=value)"
            )
        k, v = (s.strip() for s in part.split("=", 1))
        if k in ("bucket_mb",):
            kw["bucket_bytes"] = int(float(v) * (1 << 20))
        elif k == "bucket_bytes":
            kw["bucket_bytes"] = int(v)
        elif k == "block":
            kw["block"] = int(v)
        elif k in ("ef", "error_feedback"):
            if v in ("1", "true", "on", "yes"):
                kw["error_feedback"] = True
            elif v in _OFF or v == "no":
                kw["error_feedback"] = False
            else:  # a typo must not silently flip an ablation switch
                raise ValueError(
                    f"bad compress option {k}={v!r} in {spec!r} "
                    f"(expected on/off)"
                )
        else:
            raise ValueError(f"unknown compress option {k!r} in {spec!r}")
    return CompressConfig(**kw)


def resolve(config_value=None) -> CompressConfig | None:
    """The effective compression config: an explicit config value wins
    (use ``"off"`` to force-disable); otherwise the ``TPU_DIST_COMPRESS``
    environment variable; otherwise off."""
    if config_value is not None:
        return parse(config_value)
    return parse(os.environ.get(ENV_COMPRESS))


def refuse_model_axes(
    where: str,
    axes,
    *,
    rules: str | None = None,
    hint: str | None = None,
) -> None:
    """Raise the model-sharding refusal with its CAUSE attached: the
    compressed wire reduces over the pure data axis only, and a bare
    "not supported" hides which axis (and which mode / partition rule)
    put the gradient on a model-sharded layout.  ``axes`` names the
    offending mesh axes; ``rules`` names the trainer mode or partition
    rule set that produced them."""
    axes = tuple(axes)
    axes_s = (
        f"model-sharded ax{'is' if len(axes) == 1 else 'es'} "
        + ", ".join(repr(a) for a in axes)
        if axes
        else "a model-sharded gradient layout"
    )
    raise ValueError(
        f"{where}: grad_compress compresses the pure data-axis gradient "
        f"sync only; {axes_s}"
        + (f" (produced by {rules})" if rules else "")
        + " cannot ride the quantized wire — drop grad_compress or the "
        "model-sharding axes"
        + (f". {hint}" if hint else "")
    )


# ---------------------------------------------------------------------------
# Flat bucket layout
# ---------------------------------------------------------------------------


class FlatPlan:
    """Static layout of a gradient pytree as one ``(n, K_pad)`` matrix.

    Row r carries the data destined to rank r (the fsdp row convention:
    each leaf flattens and zero-pads to ``(n, k_leaf)``; rows concatenate
    leaf by leaf).  ``K_pad`` rounds K up to a whole number of
    per-destination bucket chunks of ``chunk`` elements, and ``chunk`` is
    a multiple of the scale block, so every bucket quantizes uniformly.
    Built from SHAPES only — usable on tracers and templates alike.
    """

    def __init__(self, template: Any, n: int, cfg: CompressConfig):
        self.n = int(n)
        self.cfg = cfg
        leaves, self.treedef = jax.tree_util.tree_flatten(template)
        self.shapes = [tuple(leaf.shape) for leaf in leaves]
        self.dtypes = [jnp.dtype(leaf.dtype) for leaf in leaves]
        self.ks = [
            -(-max(int(math.prod(s)), 0) // self.n) for s in self.shapes
        ]  # ceil(size / n): the fsdp (n, k) row length per leaf
        self.K = sum(self.ks)
        block = max(1, int(cfg.block))
        # per-destination chunk: bucket_bytes of fp32 payload across the
        # whole (n, chunk) slab, rounded up to whole scale blocks — but
        # never beyond the payload itself (a tiny model must not ship a
        # mostly-padding 4 MB bucket)
        per_dest = max(1, cfg.bucket_bytes // 4 // self.n)
        k_blocks = -(-max(self.K, 1) // block) * block
        self.chunk = min(-(-per_dest // block) * block, k_blocks)
        self.block = block
        self.K_pad = -(-max(self.K, 1) // self.chunk) * self.chunk
        self.n_buckets = self.K_pad // self.chunk

    # --- tree <-> rows ----------------------------------------------------

    def to_rows(self, grads: Any) -> jax.Array:
        """Pytree -> the ``(n, K_pad)`` f32 row matrix."""
        from tpu_dist.utils.tree import pad_to_multiple

        leaves = jax.tree_util.tree_leaves(grads)
        rows = [
            pad_to_multiple(jnp.ravel(g).astype(jnp.float32), self.n).reshape(
                self.n, -1
            )
            for g in leaves
        ]
        out = jnp.concatenate(rows, axis=1) if rows else jnp.zeros((self.n, 0))
        if self.K_pad > self.K:
            out = jnp.pad(out, ((0, 0), (0, self.K_pad - self.K)))
        return out

    def from_rows(self, rows: jax.Array) -> Any:
        """``(n, K_pad)`` row matrix -> pytree (original shapes/dtypes)."""
        leaves, off = [], 0
        for shape, dtype, k in zip(self.shapes, self.dtypes, self.ks):
            size = int(math.prod(shape))
            flat = lax.slice_in_dim(rows, off, off + k, axis=1).reshape(-1)
            leaves.append(flat[:size].reshape(shape).astype(dtype))
            off += k
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def shard_rows(self, local_row: jax.Array) -> Any:
        """One rank's reduced ``(K_pad,)`` row -> the per-leaf ``(1, k)``
        row shards the fsdp/zero1 optimizer update consumes (the exact
        output format of `parallel.fsdp._reduce_scatter_grads`)."""
        shards, off = [], 0
        for k in self.ks:
            shards.append(
                lax.slice_in_dim(local_row, off, off + k, axis=0).reshape(1, k)
            )
            off += k
        return jax.tree_util.tree_unflatten(self.treedef, shards)

    # --- accounting -------------------------------------------------------

    def payload_bytes(self, wire: bool = True) -> int:
        """Per-step quantized payload bytes across the whole (n, K_pad)
        slab (scales included), or the fp32 equivalent (``wire=False``)."""
        total = self.n * self.K_pad
        if not wire:
            return total * 4
        per_elem = self.cfg.wire_itemsize
        scale_bytes = 0
        if self.cfg.wire != "bfloat16":  # f32 scale per block
            scale_bytes = (total // self.block) * 4
        return total * per_elem + scale_bytes

    def bytes_on_wire(self, mode: str = "all_reduce") -> int:
        """Bytes each rank moves per step (ring lower bound: allreduce =
        2(n-1)/n of the payload, reduce-scatter = (n-1)/n)."""
        factor = 2 if mode == "all_reduce" else 1
        return int(factor * (self.n - 1) / max(self.n, 1) * self.payload_bytes())

    def bytes_exact(self, mode: str = "all_reduce") -> int:
        factor = 2 if mode == "all_reduce" else 1
        return int(
            factor * (self.n - 1) / max(self.n, 1) * self.payload_bytes(False)
        )

    def wire_summary(self, mode: str = "all_reduce") -> dict:
        """The telemetry record: what one step costs on the wire."""
        return {
            "wire": self.cfg.wire,
            "mode": mode,
            "buckets": self.n_buckets,
            "bucket_bytes": self.chunk * self.n * 4,
            "bytes_on_wire": self.bytes_on_wire(mode),
            "bytes_exact": self.bytes_exact(mode),
        }

    def analysis_expectations(self) -> dict:
        """What `tpu_dist.analysis` should find in a compiled step that
        syncs through this plan: the wire itemsize every gradient-payload
        collective must carry, and the widest operand of a WIDER dtype
        that is still legitimate — per-bucket f32 scales ship
        ``chunk/block`` elements per destination, and scalar loss /
        all-finite-predicate reductions stay.  Anything wider-typed and
        larger is a gradient payload that escaped the compressed wire
        (the `compress-wire` lint)."""
        return {
            "wire": self.cfg.wire,
            "wire_itemsize": self.cfg.wire_itemsize,
            "n_buckets": self.n_buckets,
            "max_wide_operand_elems": max(
                (self.chunk // self.block) * self.n, 16
            ),
        }

    # --- error-feedback state --------------------------------------------

    def init_residual(self, mesh=None, axis_name: str = DEFAULT_AXIS):
        """The zero residual: globally ``(n, n, K_pad)`` f32, sharded over
        the data axis (rank r's block is ITS ``(n, K_pad)`` local error —
        per-rank state, never synced).  With ``mesh=None`` returns the
        uncommitted array (tests/manual shard_map harnesses)."""
        shape = (self.n, self.n, self.K_pad)
        if mesh is None:
            return jnp.zeros(shape, jnp.float32)
        return _sharded_zeros(shape, mesh, axis_name)


def _sharded_zeros(shape, mesh, axis_name: str = DEFAULT_AXIS):
    """Zeros born sharded P(axis) — never materializing the global array
    on one device (the residual is n× a gradient; a transient global
    allocation would OOM a chip at pod scale)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis_name))
    return jax.jit(
        lambda: jnp.zeros(shape, jnp.float32), out_shardings=sharding
    )()


def init_ef_state(template: Any, n: int, cfg: CompressConfig, mesh=None,
                  axis_name: str = DEFAULT_AXIS) -> dict:
    """The error-feedback state the compressed step builders thread
    through the optimizer-state slot: ``{"residual": (n, n, K_pad)
    sharded, "err": scalar}`` — ``err`` is the last step's relative
    quantization error (the `compression_error` gauge's source)."""
    plan = FlatPlan(template, n, cfg)
    err = jnp.zeros((), jnp.float32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # committed replicated scalar: an uncommitted device-0 scalar
        # round-trips through sharded checkpoints committed, clashing
        # with the mesh-wide step at dispatch (see fsdp._commit_scalars)
        err = jax.device_put(err, NamedSharding(mesh, P()))
    return {"residual": plan.init_residual(mesh, axis_name), "err": err}


def wrap_opt_state(inner, template: Any, n: int, cfg: CompressConfig,
                   mesh=None, axis_name: str = DEFAULT_AXIS) -> dict:
    """The ``{"opt", "ef"}`` opt-state wrapper around a single-axis EF
    state — ONE constructor for manual shard_map harnesses and tests
    (the ENGINE builds its own wrapper via `init_engine_ef_state`).
    ``inner`` is the (already placed) optimizer state; ``template``
    supplies the gradient shapes."""
    return {
        "opt": inner,
        "ef": init_ef_state(template, n, cfg, mesh, axis_name),
    }


def reset_resized_residual(opt_state, meta: dict, *,
                           axis_name: str = DEFAULT_AXIS):
    """Zero a restored EF residual whose SAVED shape differs from the
    live one (checkpoint from a different world size).

    `train.checkpoint.restore_fsdp`'s world-size translation flat-copies
    leaves — valid for fsdp's zero-padded rows, but the residual is
    dense per-(owner rank, destination) state whose rows would land on
    the wrong pairs.  Starting from a zero residual merely re-pays one
    step of quantization error; a misdirected one injects garbage.
    ``meta`` is the checkpoint's `read_meta` dict; returns ``opt_state``
    (with a fresh zero residual when the shapes differ)."""
    if not (isinstance(opt_state, dict) and "ef" in opt_state):
        return opt_state
    res = opt_state["ef"]["residual"]
    for rec in meta.get("leaves", ()):
        if rec["path"].endswith("['ef']['residual']"):
            if tuple(rec["shape"]) != tuple(res.shape):
                zeros = jax.jit(
                    lambda: jnp.zeros(res.shape, res.dtype),
                    out_shardings=res.sharding,
                )()
                return {
                    **opt_state,
                    "ef": {**opt_state["ef"], "residual": zeros},
                }
            break
    return opt_state


def ef_error(opt_state) -> float | None:
    """The last compressed sync's relative quantization error from a
    wrapped ``{"opt", "ef"}`` optimizer state (the `compression_error`
    gauge's source; None when the state carries no EF wrapper).  Reading
    it syncs one replicated device scalar — call at drained boundaries."""
    if isinstance(opt_state, dict) and "ef" in opt_state:
        return float(opt_state["ef"]["err"])
    return None


def engine_residual_spec(data_axes, model_axes=()):
    """PartitionSpec of the ENGINE's EF residual: globally ``(n_data,
    n_data, K_pad · n_model)`` with dim 0 sharded over the composed data
    axes (rank r's block is ITS local error) and the K dim sharded over
    the model axes (each model shard carries the residual of ITS slice
    of every gradient leaf — the wire compresses tp-sharded grads at
    their shard shape)."""
    from jax.sharding import PartitionSpec as P

    d = tuple(data_axes)
    m = tuple(model_axes)
    return P(
        d if len(d) > 1 else d[0],
        None,
        (m if len(m) > 1 else m[0]) if m else None,
    )


def init_engine_ef_state(
    plan: "FlatPlan", mesh, data_axes, model_axes=()
) -> dict:
    """The engine's error-feedback state (`make_partitioned_train_step
    (compress=...)`): ``{"residual", "err"}`` with the residual born
    sharded per `engine_residual_spec` — ``plan`` is the engine's
    FlatPlan over MODEL-LOCAL leaf shapes, so its ``K_pad`` is the
    per-model-shard row length and the global K dim is ``K_pad`` times
    the model-axis size."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    model_k = (
        int(np.prod([int(mesh.shape[a]) for a in model_axes]))
        if model_axes
        else 1
    )
    shape = (plan.n, plan.n, plan.K_pad * model_k)
    sharding = NamedSharding(mesh, engine_residual_spec(data_axes, model_axes))
    residual = jax.jit(
        lambda: jnp.zeros(shape, jnp.float32), out_shardings=sharding
    )()
    err = jax.device_put(
        jnp.zeros((), jnp.float32), NamedSharding(mesh, P())
    )
    return {"residual": residual, "err": err}


# ---------------------------------------------------------------------------
# Quantization (per-block scales)
# ---------------------------------------------------------------------------


def _quant_blocks(x: jax.Array, cfg: CompressConfig):
    """Quantize ``x`` (last dim a multiple of the block) with one scale
    per block.  Returns ``(q, scales)``; bfloat16 is scale-free
    (``scales`` is None)."""
    wire, maxv = _wire_spec(cfg.wire)
    if maxv is None:  # bf16: the cast is the whole codec
        return x.astype(wire), None
    shape = x.shape
    blocks = x.reshape(shape[:-1] + (shape[-1] // cfg.block, cfg.block))
    scales = jnp.max(jnp.abs(blocks), axis=-1) / maxv + 1e-30
    scaled = blocks / scales[..., None]
    if cfg.wire == "int8":
        q = jnp.clip(jnp.round(scaled), -maxv, maxv).astype(wire)
    else:  # fp8: the cast rounds; clip guards the saturating edge
        q = jnp.clip(scaled, -maxv, maxv).astype(wire)
    return q.reshape(shape), scales


def _dequant_blocks(q: jax.Array, scales, cfg: CompressConfig) -> jax.Array:
    if scales is None:
        return q.astype(jnp.float32)
    shape = q.shape
    blocks = q.astype(jnp.float32).reshape(
        shape[:-1] + (shape[-1] // cfg.block, cfg.block)
    )
    return (blocks * scales[..., None]).reshape(shape)


def _nonfinite_count(x: jax.Array) -> jax.Array:
    return jnp.sum(~jnp.isfinite(x)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# The compressed collectives (inside shard_map)
# ---------------------------------------------------------------------------


def all_reduce_rows(
    rows: jax.Array,
    residual: jax.Array | None,
    plan: FlatPlan,
    axis_name: str = DEFAULT_AXIS,
    *,
    predicate_axes=None,
):
    """Bucketed quantized all-reduce of an ``(n, K_pad)`` row matrix.

    ``axis_name`` may be one mesh axis or a TUPLE of axes (the engine
    reduces over composed data axes, e.g. ``('dp', 'fsdp')``) — every
    collective inside treats the tuple as one flattened axis.

    Returns ``(sum_rows, new_residual, stats)`` — ``sum_rows`` is the
    cross-rank SUM (callers divide by n for the mean), ``new_residual``
    is None iff ``residual`` was, and ``stats`` is ``{"err": relative
    quantization error (pmean'd), "ok": all-finite predicate}``.  On a
    globally non-finite input the output rows are NaN (so a NaN guard
    trips exactly as under exact sync) and the residual is held
    unchanged — a skipped step must not absorb a poisoned residual.
    ``predicate_axes`` widens the all-finite reduction (default: the
    reduction axes) — the engine passes data+model axes so a NaN on one
    model shard poisons the WHOLE step, not one tp slice of it.
    """
    cfg = plan.cfg
    acc = rows + residual if residual is not None else rows
    ok = lax.psum(
        _nonfinite_count(acc),
        predicate_axes if predicate_axes is not None else axis_name,
    ) == 0
    q, scales = _quant_blocks(acc, cfg)
    deq = _dequant_blocks(q, scales, cfg)
    err1 = acc - deq  # this rank's first-round quantization error
    c, nb = plan.chunk, plan.n_buckets
    out_parts, err2_parts = [], []
    for j in range(nb):  # ONE wire exchange per bucket
        sl = slice(j * c, (j + 1) * c)
        qj = lax.all_to_all(
            q[:, sl], axis_name, split_axis=0, concat_axis=0, tiled=True
        )
        sj = None
        if scales is not None:
            sj = lax.all_to_all(
                scales[:, j * (c // plan.block): (j + 1) * (c // plan.block)],
                axis_name, split_axis=0, concat_axis=0, tiled=True,
            )
        reduced = _dequant_blocks(qj, sj, cfg).sum(axis=0)  # (c,) exact f32
        q2, s2 = _quant_blocks(reduced, cfg)
        err2_parts.append(reduced - _dequant_blocks(q2, s2, cfg))
        qa = lax.all_gather(q2, axis_name, axis=0)  # (n, c) 1-byte wire
        sa = (
            lax.all_gather(s2, axis_name, axis=0) if s2 is not None else None
        )
        out_parts.append(_dequant_blocks(qa, sa, cfg))
    total = jnp.concatenate(out_parts, axis=1)  # (n, K_pad) cross-rank sum
    err = jnp.linalg.norm(err1) / (jnp.linalg.norm(acc) + 1e-12)
    stats = {"err": lax.pmean(jnp.where(ok, err, jnp.nan), axis_name), "ok": ok}
    total = jnp.where(ok, total, jnp.nan)
    if residual is None:
        return total, None, stats
    # Rank r alone knows the second-round error of chunk r — feed it back
    # into r's own next contribution so BOTH rounds are error-compensated.
    r = lax.axis_index(axis_name)
    err2 = jnp.concatenate(err2_parts)  # (K_pad,)
    own = lax.dynamic_slice_in_dim(err1, r, 1, axis=0) + err2[None]
    new_residual = lax.dynamic_update_slice_in_dim(err1, own, r, axis=0)
    new_residual = jnp.where(ok, new_residual, residual)
    return total, new_residual, stats


def reduce_scatter_rows(
    rows: jax.Array,
    residual: jax.Array | None,
    plan: FlatPlan,
    axis_name: str = DEFAULT_AXIS,
):
    """Bucketed quantized reduce-scatter: each rank ends with ITS
    f32-reduced ``(K_pad,)`` row (cross-rank SUM of row r) — the
    compressed form of the fsdp/zero1 ``psum_scatter`` hop, at half the
    allreduce's wire cost and with a single quantization round (the
    reduction itself is exact f32).  Same EF / non-finite contract as
    `all_reduce_rows`; returns ``(local_row, new_residual, stats)``."""
    cfg = plan.cfg
    acc = rows + residual if residual is not None else rows
    ok = lax.psum(_nonfinite_count(acc), axis_name) == 0
    q, scales = _quant_blocks(acc, cfg)
    err1 = acc - _dequant_blocks(q, scales, cfg)
    c, nb = plan.chunk, plan.n_buckets
    parts = []
    for j in range(nb):
        sl = slice(j * c, (j + 1) * c)
        qj = lax.all_to_all(
            q[:, sl], axis_name, split_axis=0, concat_axis=0, tiled=True
        )
        sj = None
        if scales is not None:
            sj = lax.all_to_all(
                scales[:, j * (c // plan.block): (j + 1) * (c // plan.block)],
                axis_name, split_axis=0, concat_axis=0, tiled=True,
            )
        parts.append(_dequant_blocks(qj, sj, cfg).sum(axis=0))
    local = jnp.concatenate(parts)  # (K_pad,) this rank's reduced row
    err = jnp.linalg.norm(err1) / (jnp.linalg.norm(acc) + 1e-12)
    stats = {"err": lax.pmean(jnp.where(ok, err, jnp.nan), axis_name), "ok": ok}
    local = jnp.where(ok, local, jnp.nan)
    if residual is None:
        return local, None, stats
    new_residual = jnp.where(ok, err1, residual)
    return local, new_residual, stats


# ---------------------------------------------------------------------------
# Convenience wrappers (demos / benchmarks / tests)
# ---------------------------------------------------------------------------


def compressed_all_reduce(
    x: jax.Array,
    cfg: CompressConfig | str = "int8",
    axis_name: str = DEFAULT_AXIS,
) -> jax.Array:
    """Stateless bucketed quantized all-reduce of ONE array (sum
    semantics, like `comm.all_reduce`) — the demo/bench entry point; the
    trainers use the residual-threading row forms directly."""
    cfg = parse(cfg)
    if cfg is None:
        return lax.psum(x, axis_name)
    plan = FlatPlan(x, lax.axis_size(axis_name), cfg)
    total, _, _ = all_reduce_rows(plan.to_rows(x), None, plan, axis_name)
    return plan.from_rows(total)
