"""Process/runtime bootstrap — ``dist.init_process_group`` analog.

The reference's init contract (tuto.md:404-428, exercised at
train_dist.py:130-135): set ``MASTER_ADDR``/``MASTER_PORT``, call
``init_process_group(backend, rank, world_size)``; rank 0 acts as master,
workers rendezvous through it, ending fully connected.  Config comes from
env vars ``MASTER_PORT/MASTER_ADDR/WORLD_SIZE/RANK`` (tuto.md:421-428).

TPU-native equivalent: ``jax.distributed.initialize(coordinator_address,
num_processes, process_id)`` — the coordinator is the MASTER_ADDR/PORT
analog, and the XLA runtime plays THD's role (channel setup, peer
discovery, collective transport over ICI/DCN).  On a single host (or under
CPU simulation) no coordinator is needed and init is a no-op, mirroring how
every reference demo also runs single-machine over loopback (SURVEY.md §4).

The MPI-style rank-less init (``allreduce.py:54`` — rank assigned by
``mpirun``) maps to TPU pod launch, where process ids come from the
environment; ``init()`` with no arguments covers it.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax

from tpu_dist.resilience import chaos as _chaos
from tpu_dist.resilience.retry import RendezvousTimeout, RetryPolicy, retry_call


@dataclass(frozen=True)
class InitConfig:
    """Resolved bootstrap configuration (the four env vars of
    tuto.md:421-428, plus platform as the backend-string analog)."""

    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None
    platform: str | None = None

    @staticmethod
    def from_env() -> "InitConfig":
        addr = os.environ.get("MASTER_ADDR")
        port = os.environ.get("MASTER_PORT")
        coordinator = f"{addr}:{port}" if addr and port else None
        world = os.environ.get("WORLD_SIZE")
        rank_ = os.environ.get("RANK")
        return InitConfig(
            coordinator_address=coordinator,
            num_processes=int(world) if world is not None else None,
            process_id=int(rank_) if rank_ is not None else None,
            platform=os.environ.get("TPU_DIST_PLATFORM"),
        )


def _addr_is_remote(addr: str) -> bool:
    """True only when ``addr`` definitely names another machine: not
    loopback, not this hostname, and not resolving to any of this host's
    addresses.  Unresolvable addresses are treated as local-unknown
    (warn-free pass) — a guard must not produce false positives."""
    import socket

    if addr in ("127.0.0.1", "localhost", "::1") or addr == socket.gethostname():
        return False
    try:
        target = {ai[4][0] for ai in socket.getaddrinfo(addr, None)}
    except OSError:
        return False
    if any(ip.startswith("127.") or ip == "::1" for ip in target):
        return False
    try:
        local = {
            ai[4][0] for ai in socket.getaddrinfo(socket.gethostname(), None)
        }
    except OSError:
        local = set()
    if target & local:
        return False
    # gethostname() may only map to loopback (Debian-style 127.0.1.1
    # /etc/hosts) while MASTER_ADDR carries the real interface IP: the
    # source address the kernel would route FROM to reach the target is
    # the target itself iff the target is one of our interfaces.  (UDP
    # connect assigns a route without sending any packet.)
    for ip in target:
        fam = socket.AF_INET6 if ":" in ip else socket.AF_INET
        try:
            s = socket.socket(fam, socket.SOCK_DGRAM)
            try:
                s.connect((ip, 9))
                if s.getsockname()[0] == ip:
                    return False
            finally:
                s.close()
        except OSError:
            continue
    return True


ENV_COMPILE_CACHE = "TPU_DIST_COMPILE_CACHE"

_compile_cache_dir: str | None = None


def _setup_compile_cache() -> str | None:
    """Wire the persistent XLA compilation cache from the environment.

    ``TPU_DIST_COMPILE_CACHE=<dir>`` points JAX's
    ``jax_compilation_cache_dir`` at a durable directory, so a restarted
    job (preemption resume, the gang supervisor's relaunch, a re-run
    bench) pays compile time once instead of on every boot — at pod
    scale XLA compilation is minutes of lost goodput per restart.  The
    entry-size/compile-time thresholds are zeroed because our hottest
    restart path is the LATENCY-bound parity workload, whose small fast
    programs the defaults would decline to cache.

    Every cache hit/miss surfaces as telemetry: a ``compile_cache``
    event (when ``TPU_DIST_TELEMETRY`` is set) and the
    ``tpu_dist_compile_cache_{hits,misses}_total`` registry counters,
    via a `jax.monitoring` listener.  Idempotent — the FIRST configured
    dir wins for the process lifetime (a later env change is not
    honored; the return value always names the dir actually in effect).
    Returns None when the env var is unset."""
    global _compile_cache_dir
    path = os.environ.get(ENV_COMPILE_CACHE)
    if not path:
        return None
    if _compile_cache_dir is not None:
        return _compile_cache_dir
    _compile_cache_dir = path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        # jax memoizes its is-the-cache-used decision at the FIRST
        # compile of the process: if anything compiled before init()
        # (a backend probe, an earlier fit), the new dir would be
        # silently ignored without this reset.  Private API, so degrade
        # to "cache from next process" if it moves.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass

    from tpu_dist.observe import events as events_mod
    from tpu_dist.observe import registry

    hits = registry.REGISTRY.counter(
        "tpu_dist_compile_cache_hits_total",
        "XLA programs loaded from the persistent compilation cache",
    )
    misses = registry.REGISTRY.counter(
        "tpu_dist_compile_cache_misses_total",
        "XLA programs compiled and written to the persistent cache",
    )

    def _listen(event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            hits.inc()
            events_mod.from_env().emit(
                "compile_cache", outcome="hit", dir=path
            )
        elif event == "/jax/compilation_cache/cache_misses":
            misses.inc()
            events_mod.from_env().emit(
                "compile_cache", outcome="miss", dir=path
            )

    jax.monitoring.register_event_listener(_listen)
    return path


_initialized = False


def init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    platform: str | None = None,
) -> InitConfig:
    """Initialize the distributed runtime.

    Arguments default from the reference's env-var contract
    (``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE``/``RANK``,
    tuto.md:421-428).  Single-process (num_processes in (None, 1)): no-op —
    the runtime is already live.  Multi-process (one process per TPU host):
    wraps ``jax.distributed.initialize``, the rendezvous of tuto.md:404-419.
    """
    global _initialized
    # Persistent compile cache rides every init flavor, including the
    # single-process no-op path (it only touches jax.config, which is
    # safe before OR after backend initialization).
    _setup_compile_cache()
    env = InitConfig.from_env()
    cfg = InitConfig(
        coordinator_address=coordinator_address or env.coordinator_address,
        num_processes=num_processes or env.num_processes,
        process_id=process_id if process_id is not None else env.process_id,
        platform=platform or env.platform,
    )
    if _initialized:
        return cfg
    if cfg.platform is not None:
        # The backend-string analog ('tcp'/'gloo'/'mpi' → 'cpu'/'tpu'):
        # restrict JAX to the chosen platform.  Must happen before any
        # backend initialization to take effect.
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.num_processes and cfg.num_processes > 1:
        from tpu_dist import runtime

        rank = cfg.process_id if cfg.process_id is not None else -1
        # Precedence matches every other parameter: an EXPLICIT
        # coordinator_address argument beats the env-var init method (a
        # stale exported TPU_DIST_INIT_METHOD must not hijack a job that
        # names its coordinator).
        init_method = (
            "" if coordinator_address is not None
            else os.environ.get("TPU_DIST_INIT_METHOD", "")
        )
        if init_method.startswith("file://"):
            # file:// init (tuto.md:430-437): rank assignment + startup
            # barrier through an fcntl-locked file; the process that gets
            # rank 0 publishes the JAX coordinator address as its payload
            # (every payload carries a candidate; rank 0's wins).
            path = init_method[len("file://"):]
            # file:// rendezvous is single-host only (fcntl on a local
            # file; the published coordinator is loopback).  A MASTER_ADDR
            # that resolves OFF this host signals a multi-host job this
            # init method cannot serve — fail fast instead of hanging
            # later in jax.distributed.initialize.  Launchers that export
            # the local host's own IP/hostname (SLURM-style boilerplate)
            # are legitimately single-host and pass.
            master = os.environ.get("MASTER_ADDR")
            if master and _addr_is_remote(master):
                raise ValueError(
                    f"TPU_DIST_INIT_METHOD=file:// is single-host only "
                    f"(loopback coordinator), but MASTER_ADDR={master!r} "
                    f"resolves off this host — use the TCP init path "
                    f"(tuto.md:421-428 contract) instead"
                )
            candidate = f"127.0.0.1:{runtime.free_port()}"
            my_rank, peers = runtime.file_rendezvous(
                path, cfg.num_processes, rank, payload=candidate
            )
            coordinator = peers[0]
        else:
            if cfg.coordinator_address is None:
                raise ValueError(
                    "multi-process init needs MASTER_ADDR/MASTER_PORT, an "
                    "explicit coordinator_address (tuto.md:421-428 "
                    "contract), or TPU_DIST_INIT_METHOD=file:///path"
                )
            addr, _, port_s = cfg.coordinator_address.partition(":")
            port = int(port_s)
            # Native TCP bootstrap (tpu_dist/runtime/rendezvous.cc):
            # startup barrier + rank assignment (process_id=None →
            # master-assigned, the MPI-style rank-less path of
            # allreduce.py:54).  Retried under bounded exponential
            # backoff (TPU_DIST_RDZV_* / TPU_DIST_STARTUP_DEADLINE
            # knobs): a flaky coordinator or a slow-booting peer is the
            # common case at pod scale, and every process runs the same
            # schedule so the gang re-converges on a later attempt.  The
            # chaos gate (`TPU_DIST_CHAOS=rdzv_fail=N`) injects failures
            # through the identical path.
            policy = RetryPolicy.from_env()

            def _rendezvous(attempt):
                _chaos.rendezvous_attempt(attempt)
                return runtime.rendezvous(
                    addr, port, cfg.num_processes, rank,
                    payload=os.uname().nodename,
                )

            my_rank, _peers = retry_call(
                _rendezvous,
                policy=policy,
                retry_on=(RuntimeError, OSError),
                describe=f"rendezvous at {addr}:{port}",
                error_type=RendezvousTimeout,
            )
            # Steady-state coordinator: one port above the rendezvous
            # port — both come from the same MASTER contract.
            coordinator = f"{addr}:{port + 1}"
        cfg = InitConfig(
            coordinator_address=coordinator,
            num_processes=cfg.num_processes,
            process_id=my_rank,
            platform=cfg.platform,
        )
        retry_call(
            lambda _attempt: jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=cfg.num_processes,
                process_id=my_rank,
            ),
            policy=RetryPolicy.from_env(),
            retry_on=(RuntimeError,),
            describe=f"jax.distributed.initialize via {coordinator}",
            error_type=RendezvousTimeout,
        )
    _initialized = True
    return cfg


def process_rank() -> int:
    """Host-level ``dist.get_rank()`` (outside SPMD code)."""
    return jax.process_index()


def process_count() -> int:
    """Host-level ``dist.get_world_size()`` (outside SPMD code)."""
    return jax.process_count()
