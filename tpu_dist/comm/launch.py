"""Multi-process launcher — the fork-join ``__main__`` template, natively
bootstrapped.

The reference spawns ``size`` local processes, each running
``init_processes(rank, size, fn)``, then joins them (train_dist.py:138-147
and the other three scripts).  `launch` reproduces that shape: it forks
``world`` OS processes with the MASTER_ADDR/PORT/WORLD_SIZE/RANK env
contract (tuto.md:421-428), each child runs `tpu_dist.comm.init` — whose
multi-process path does the native C++ rendezvous (startup barrier + rank
assignment, `tpu_dist.runtime`) and then ``jax.distributed.initialize`` —
and finally calls ``fn(rank, world)``.

This is the path that scales to one-process-per-TPU-host pods; the same
launcher with ``platform='cpu'`` is the loopback development harness (the
reference's fork-over-loopback strategy, SURVEY.md §4.2).  The external
``mpirun``-style launch (tuto.md:393-398) is covered by setting the env
vars outside and calling ``init()`` with no arguments (rank -1 lets the
native rendezvous assign one, mirroring rank-less MPI init,
allreduce.py:54).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import sys
import traceback
from typing import Any, Callable


def _child(fn, rank, world, addr, port, platform, conn, devices_per_proc,
           init_method=None, assign_ranks=True, chaos_attempt=0):
    try:
        # Chaos hooks first (import-light, pre-JAX): a `delay=` clause
        # sleeps this rank, a `kill=` clause hard-exits it — the parent
        # observes a child that died without reporting, which is exactly
        # the failure mode the supervisor exists to detect.
        from tpu_dist.resilience import chaos as _chaos
        from tpu_dist.observe import events as _events

        # Pin the telemetry rank before anything can open an event log or
        # heartbeat file: the jax-level rank isn't known yet, and every
        # rank writing to events.jsonl (rank 0's file) would interleave.
        os.environ[_events.ENV_RANK] = str(rank)
        os.environ[_chaos.ATTEMPT_ENV_VAR] = str(chaos_attempt)
        _chaos.at_launch(rank)
        if init_method:
            os.environ["TPU_DIST_INIT_METHOD"] = init_method
        else:
            # an inherited env var must not override this launch's TCP
            # bootstrap (explicit configuration wins)
            os.environ.pop("TPU_DIST_INIT_METHOD", None)
            os.environ["MASTER_ADDR"] = addr
            os.environ["MASTER_PORT"] = str(port)
        os.environ["WORLD_SIZE"] = str(world)
        if assign_ranks:
            os.environ["RANK"] = str(rank)
        else:
            # mpirun-style: ranks come from the rendezvous master election
            # (allreduce.py:54's rank-less init)
            os.environ.pop("RANK", None)
        if platform == "cpu" and devices_per_proc:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={devices_per_proc}"
            )
        from tpu_dist import comm

        comm.init(platform=platform)
        result = fn(rank, world)
        conn.send(("ok", pickle.dumps(result)))
    except BaseException as e:  # report child failures to the parent
        # The exception is caught here, so the excepthook-based flight
        # dump never fires — dump the ring explicitly: the crashing
        # rank's last steps are exactly what the merge CLI needs.
        try:
            from tpu_dist.observe import flightrec as _flightrec

            _flightrec.crash_dump(f"exception:{type(e).__name__}")
        except Exception:
            pass
        conn.send(("error", f"rank {rank}: {type(e).__name__}: {e}\n"
                   f"{traceback.format_exc()}"))
    finally:
        conn.close()


def launch(
    fn: Callable[[int, int], Any],
    world: int,
    *,
    platform: str | None = None,
    addr: str = "127.0.0.1",
    port: int | None = None,
    devices_per_proc: int = 1,
    timeout: float = 300.0,
    init_method: str | None = None,
    assign_ranks: bool = True,
    restarts: int = 0,
    probe_world: Callable[[], int | None] | None = None,
) -> list[Any]:
    """Fork-join ``world`` processes running ``fn(rank, world)``.

    ``fn`` must be picklable (module-level).  Returns each rank's result,
    index = LAUNCH slot (== jax rank when ``assign_ranks``).  Any child
    failure raises, fail-stop, after terminating the others (the
    reference's failure model: blocked peers + ``join()``, SURVEY.md §5).
    ``init_method='file:///path'`` bootstraps through the fcntl file
    rendezvous instead of the TCP master (tuto.md:430-437).
    ``assign_ranks=False`` leaves RANK unset — every child does the
    MPI-style rank-less init and the rendezvous election assigns ranks
    (allreduce.py:54 analog).

    ``restarts=N`` turns the fail-stop into a supervisor: when a child
    dies (or fails) the whole gang is reaped and relaunched, up to N
    times — a fork-join collective group has no single-rank recovery
    (the survivors hold dead collective state), so the restart unit is
    the gang.  Each attempt gets a fresh rendezvous port (when ``port``
    is None) and exports its attempt index to the children
    (`resilience.chaos.ATTEMPT_ENV_VAR`) so chaos kill clauses can be
    scoped to one attempt.  Exhausted restarts raise
    `resilience.WorkerFailed` with the last failure.

    ``probe_world`` makes the relaunch ELASTIC: before each relaunch the
    supervisor re-probes how many workers the machine can actually field
    (a preemption may have taken chips with it) instead of replaying the
    original world size — the callable returns the new world (None =
    keep the current one).  Without it, the env var
    ``TPU_DIST_PROBE_WORLD`` (an integer, read fresh per relaunch) is
    honored, else the world is replayed unchanged.  Each supervisor
    event carries ``relaunch_world`` — the world the NEXT attempt will
    run (None once restarts are exhausted) — so the event stream shows
    the topology change next to the failure that forced it.  Elastic
    workloads resume their checkpoints through
    `train.reshard.redistribute`, which maps the old topology's shards
    onto whatever mesh the re-probed world builds.
    """
    from tpu_dist.observe import events as events_mod
    from tpu_dist.resilience.retry import WorkerFailed, logger

    # The gang supervisor's own event stream (events_supervisor.jsonl):
    # restarts and final failure become machine-parseable records instead
    # of vanishing into stderr.  NULL logger when telemetry is off.
    elog = events_mod.from_env(role="supervisor")
    last_error: Exception | None = None
    attempt_world = world
    for attempt in range(restarts + 1):
        try:
            results = _launch_once(
                fn, attempt_world, platform=platform, addr=addr, port=port,
                devices_per_proc=devices_per_proc, timeout=timeout,
                init_method=init_method, assign_ranks=assign_ranks,
                attempt=attempt,
            )
            if attempt > 0:
                elog.emit(
                    "retry", what="gang_relaunch", attempt=attempt + 1,
                    max_attempts=restarts + 1, error=None,
                    world=attempt_world, relaunch_world=attempt_world,
                    outcome="succeeded",
                )
            return results
        except WorkerFailed as e:
            last_error = e
            # Forensics before anything else: gather the per-rank flight
            # dumps (chaos kills, crashed children, and watchdog fires
            # all dump into the telemetry dir) into an attempt-scoped
            # subdir so a relaunch's fresh dumps can't overwrite them,
            # and record where they went.  `python -m
            # tpu_dist.observe.flightrec merge <dir>` names the
            # divergent rank from the gathered set.
            _gather_flight_dumps(elog, attempt)
            exhausted = attempt >= restarts
            next_world = (
                None if exhausted
                else _reprobe_world(probe_world, attempt_world)
            )
            elog.emit(
                "retry", what="gang_relaunch", attempt=attempt + 1,
                max_attempts=restarts + 1, error=str(e),
                world=attempt_world, relaunch_world=next_world,
                outcome="exhausted" if exhausted else "relaunching",
            )
            if exhausted:
                break
            if next_world != attempt_world:
                logger.warning(
                    "elastic relaunch: world %d -> %d (re-probed)",
                    attempt_world, next_world,
                )
            attempt_world = next_world
            logger.warning(
                "launch attempt %d/%d failed (%s); relaunching the gang",
                attempt + 1, restarts + 1, e,
            )
    assert last_error is not None
    raise last_error


def _reprobe_world(
    probe_world: Callable[[], int | None] | None, current: int
) -> int:
    """The world size the next relaunch attempt should run.  A probe
    callable wins (its errors propagate — a broken probe must be loud);
    else ``TPU_DIST_PROBE_WORLD`` (garbage raises, same reasoning); else
    the current world, unchanged.  Clamped to >= 1."""
    if probe_world is not None:
        probed = probe_world()
        return max(1, int(probed)) if probed is not None else current
    env = os.environ.get("TPU_DIST_PROBE_WORLD")
    if env is not None:
        return max(1, int(env))
    return current


def _gather_flight_dumps(elog, attempt: int) -> None:
    """Move per-rank flight-recorder dumps from the telemetry dir root
    into ``flight/attempt<k>/`` and record a ``flight_dump`` event —
    best-effort (a gang failure must surface even if the gather can't)."""
    try:
        from tpu_dist.observe import events as events_mod
        from tpu_dist.observe import flightrec as flightrec_mod

        # Same dir precedence the recorders dump under: children write
        # to TPU_DIST_FLIGHTREC_DIR when telemetry is off, and those
        # dumps must be attempt-scoped too or a relaunch overwrites them.
        dirpath = (os.environ.get(events_mod.ENV_DIR)
                   or os.environ.get(flightrec_mod.ENV_DIR))
        if not dirpath:
            return
        ranks, dest = flightrec_mod.gather_dumps(dirpath, attempt)
        if dest is not None:
            elog.emit(
                "flight_dump", reason="gang_failure", ranks=ranks,
                dir=dest, attempt=attempt,
            )
    except Exception:
        pass


def _launch_once(
    fn: Callable[[int, int], Any],
    world: int,
    *,
    platform: str | None,
    addr: str,
    port: int | None,
    devices_per_proc: int,
    timeout: float,
    init_method: str | None,
    assign_ranks: bool,
    attempt: int = 0,
) -> list[Any]:
    """One supervised fork-join attempt (the pre-`restarts` launch body)."""
    from tpu_dist import runtime
    from tpu_dist.resilience.retry import WorkerFailed

    if port is None:
        # Fresh port per attempt: a relaunch must not race the dying
        # gang's master socket (TIME_WAIT / stale registrations).
        port = runtime.free_port()
    ctx = mp.get_context("spawn")
    procs, conns = [], []
    for rank in range(world):
        parent_conn, child_conn = ctx.Pipe(duplex=False)
        p = ctx.Process(
            target=_child,
            args=(fn, rank, world, addr, port, platform, child_conn,
                  devices_per_proc, init_method, assign_ranks, attempt),
        )
        p.start()
        # Close the parent's copy of the child end NOW: with it open, a
        # child that dies without reporting never EOFs its pipe and the
        # supervisor would only notice at the full timeout — dead-child
        # detection must be event-driven (pipe EOF), not timeout-driven.
        child_conn.close()
        procs.append(p)
        conns.append(parent_conn)
    results: list[Any] = [None] * world
    error = None
    # Collect from ALL pipes concurrently: one dead rank leaves the others
    # blocked in collectives/coordination barriers, so rank-by-rank
    # polling would burn the full timeout before the real error surfaced.
    # Fail-stop: after the first reported error, survivors get a short
    # grace period, then are terminated.
    import time as _time
    from multiprocessing.connection import wait as mp_wait

    pending = {conn: rank for rank, conn in enumerate(conns)}
    deadline = _time.monotonic() + timeout
    while pending:
        limit = min(deadline, _time.monotonic() + 5.0) if error else deadline
        wait_s = limit - _time.monotonic()
        ready = mp_wait(list(pending), timeout=max(wait_s, 0)) if wait_s > 0 else []
        if not ready:
            break
        for conn in ready:
            rank = pending.pop(conn)  # type: ignore[arg-type]
            try:
                status, payload = conn.recv()
            except EOFError:
                error = error or f"rank {rank}: died without reporting a result"
                continue
            if status == "ok":
                results[rank] = pickle.loads(payload)
            else:
                error = error or payload
    for conn, rank in pending.items():
        error = error or f"rank {rank}: no result before timeout/fail-stop"
    for p in procs:
        if (error is not None or pending) and p.is_alive():
            p.terminate()
        p.join(timeout=10)
        if p.is_alive():
            p.kill()
    if error is not None:
        # WorkerFailed subclasses RuntimeError, so pre-supervisor callers
        # catching RuntimeError (and matching "launch failed") still work.
        raise WorkerFailed(f"launch failed — {error}")
    return results
