"""Device-mesh construction — the process-group analog.

The reference's ``init_process_group`` (used at
/root/reference/train_dist.py:134, ptp.py:34, allreduce.py:54, gloo.py:54)
establishes a fully-connected group of ``world_size`` ranks over a native
transport. On TPU the analog is a `jax.sharding.Mesh`: a named arrangement
of devices over which SPMD programs are compiled and XLA lowers collectives
onto ICI (intra-slice) / DCN (inter-slice).

Backend plurality ('tcp' / 'gloo' / 'mpi' strings, tuto.md:363-398) maps to
*platform* selection here: ``platform='tpu'`` for real chips, ``'cpu'`` with
``--xla_force_host_platform_device_count=N`` for the loopback-fork-style
simulation the reference uses for development (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DEFAULT_AXIS = "ranks"


def devices(platform: str | None = None) -> list[jax.Device]:
    """All addressable devices, optionally restricted to a platform.

    ``platform=None`` resolves to the default backend (TPU when present).
    """
    if platform is None:
        return list(jax.devices())
    return list(jax.devices(platform))


def make_mesh(
    shape: int | Sequence[int] | None = None,
    axis_names: Sequence[str] = (DEFAULT_AXIS,),
    *,
    platform: str | None = None,
    mesh_devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh — the ``init_process_group`` + group-of-all-ranks analog.

    Args:
      shape: int (1-D world) or tuple of per-axis sizes. ``None`` uses every
        device on one axis.
      axis_names: mesh axis names; collectives address these names (the way
        reference code addresses ``group=0`` meaning WORLD,
        train_dist.py:99).
      platform: 'tpu' | 'cpu' | None (default backend) — the backend-string
        analog.
      mesh_devices: explicit device list (overrides platform).
    """
    devs = list(mesh_devices) if mesh_devices is not None else devices(platform)
    if shape is None:
        shape = (len(devs),) if len(axis_names) == 1 else None
        if shape is None:
            raise ValueError("shape required for multi-axis meshes")
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(shape)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices; only {len(devs)} "
            f"available (platform={platform!r})"
        )
    grid = np.array(devs[:n], dtype=object).reshape(shape)
    return Mesh(grid, tuple(axis_names))


def world_mesh(axis_name: str = DEFAULT_AXIS, platform: str | None = None) -> Mesh:
    """1-D mesh over all devices — WORLD, the reference's default group."""
    return make_mesh(None, (axis_name,), platform=platform)
