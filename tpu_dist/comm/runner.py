"""SPMD runner — the process-launcher analog.

The reference forks ``size`` OS processes, each running
``init_processes(rank, size, fn)`` (train_dist.py:138-147, ptp.py:38-47,
gloo.py:58-68).  On TPU the "processes" are program instances of one
compiled SPMD program over a device mesh; ``spmd(fn, ...)`` plays the role
of the fork-join ``__main__`` template: it wraps rank-style ``fn`` in
``shard_map`` over a 1-D mesh and returns every rank's result stacked on a
leading axis (what the reference observes via per-rank ``print``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.comm.mesh import DEFAULT_AXIS, world_mesh, make_mesh


def spmd(
    fn: Callable[..., Any],
    *args: Any,
    world: int | None = None,
    mesh: Mesh | None = None,
    axis_name: str = DEFAULT_AXIS,
    platform: str | None = None,
    jit: bool = True,
    shard_argnums: tuple[int, ...] = (),
) -> Any:
    """Run ``fn(*args)`` as one program instance per mesh device.

    ``fn`` is written rank-style, using `tpu_dist.comm` collectives with
    ``axis_name``.  By default ``args`` are replicated to every rank (like
    each forked process constructing the same inputs); argument positions
    in ``shard_argnums`` are instead SPLIT over their leading axis — rank
    r's instance receives its own slice (leading dim must be divisible by
    the world size), the device-mesh analog of handing each process its
    partition.  Returns ``fn``'s result pytree with a leading ``(world,)``
    axis stacking each rank's value — the analog of collecting every
    process's prints.
    """
    if mesh is None:
        mesh = (
            make_mesh(world, (axis_name,), platform=platform)
            if world is not None
            else world_mesh(axis_name, platform=platform)
        )
    n = int(mesh.shape[axis_name])
    for i in shard_argnums:
        for leaf in jax.tree.leaves(args[i]):
            dim = jnp.asarray(leaf).shape[0] if jnp.asarray(leaf).ndim else 0
            if dim % n:
                raise ValueError(
                    f"shard_argnums arg {i}: leading dim {dim} not "
                    f"divisible by world size {n}"
                )

    def per_rank(*a):
        out = fn(*a)
        return jax.tree.map(lambda y: jnp.expand_dims(jnp.asarray(y), 0), out)

    in_specs = tuple(
        P(axis_name) if i in shard_argnums else P() for i in range(len(args))
    )
    mapped = jax.shard_map(
        per_rank, mesh=mesh, in_specs=in_specs, out_specs=P(axis_name),
        check_vma=False,
    )
    if jit:
        mapped = jax.jit(mapped)
    # Place inputs onto the mesh so host arrays land on the right platform
    # (tests drive a CPU mesh while the default backend is TPU).
    repl = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(axis_name))
    placed = []
    for i, a in enumerate(args):
        if i in shard_argnums:
            placed.append(
                jax.tree.map(
                    lambda x: jax.device_put(jnp.asarray(x), sharded), a
                )
            )
        else:
            placed.append(
                jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), repl), a)
            )
    out = mapped(*placed)
    try:
        _emit_rank_results(out, n)
    except Exception:
        pass  # telemetry must never break a successful spmd call
    return out


def _summarize_leaf(leaf, r: int) -> Any:
    """Rank ``r``'s slice of one stacked result leaf, JSONL-sized: the
    value itself when tiny (the per-rank scalars the reference printed),
    shape/dtype otherwise.  Shape/dtype come from metadata — only the
    tiny case reads any bytes back from the device."""
    import math

    import numpy as np

    shape = tuple(leaf.shape[1:])
    if math.prod(shape) <= 4:
        return np.asarray(leaf[r]).tolist()
    return {"shape": list(shape), "dtype": str(leaf.dtype)}


def _emit_rank_results(out: Any, world: int) -> None:
    """The machine-parseable form of the reference's per-rank ``print``
    (train_dist.py:125-127): with ``TPU_DIST_TELEMETRY`` set, each rank's
    stacked result slice becomes one ``spmd_result`` event.  No-op (and
    no device readback) when telemetry is off; stdout is untouched."""
    from tpu_dist.observe import events as ev_mod

    elog = ev_mod.from_env()
    if not elog.enabled:
        return
    leaves = jax.tree_util.tree_flatten_with_path(out)[0]
    for r in range(world):
        summary = {
            jax.tree_util.keystr(path) or ".": _summarize_leaf(leaf, r)
            for path, leaf in leaves
        }
        elog.emit("spmd_result", spmd_rank=r, summary=summary)
