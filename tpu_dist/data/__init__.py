"""`tpu_dist.data` — partitioning and loading (SURVEY.md §1 L4)."""

from tpu_dist.data.cifar import load_cifar10, synthetic_cifar10, synthetic_images
from tpu_dist.data.digits import load_real_digits
from tpu_dist.data.loader import (
    DistributedLoader,
    HostLoader,
    Loader,
    prefetch_to_mesh,
)
from tpu_dist.data.mnist import (
    Dataset,
    load_idx_images,
    load_idx_labels,
    load_mnist,
    synthetic_mnist,
)
from tpu_dist.data.partition import DataPartitioner, Partition, equal_shards
from tpu_dist.data.text import VOCAB as TEXT_VOCAB, TextCorpus, load_text

__all__ = [
    "DataPartitioner",
    "Dataset",
    "DistributedLoader",
    "HostLoader",
    "Loader",
    "Partition",
    "TEXT_VOCAB",
    "TextCorpus",
    "equal_shards",
    "load_text",
    "load_cifar10",
    "load_idx_images",
    "load_idx_labels",
    "load_mnist",
    "load_real_digits",
    "prefetch_to_mesh",
    "synthetic_cifar10",
    "synthetic_images",
    "synthetic_mnist",
]
