"""`tpu_dist.data` — see package modules."""
