"""CIFAR-10 — extended config 4's dataset (BASELINE.json: "ResNet-18 /
CIFAR-10 ... larger grads over ICI").

Reads the standard binary format (``data_batch_*.bin`` / ``test_batch.bin``:
10000 records of 1 label byte + 3072 channel-major pixel bytes) from
``$TPU_DIST_DATA_DIR``/common locations; falls back to the deterministic
synthetic generator (same scheme as `tpu_dist.data.mnist.synthetic_mnist`,
32×32×3) in zero-egress environments.  NHWC float32, per-channel
normalized.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from tpu_dist.data.mnist import Dataset

MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
STD = np.array([0.2470, 0.2435, 0.2616], np.float32)

_SEARCH_DIRS = (
    os.environ.get("TPU_DIST_DATA_DIR", ""),
    "data/cifar10",
    "data/cifar-10-batches-bin",
    os.path.expanduser("~/data/cifar10"),
)


def _parse_bin(path: Path) -> tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(path.read_bytes(), np.uint8)
    rec = 1 + 3072
    if raw.size % rec:
        raise ValueError(f"{path}: not a CIFAR-10 binary batch (size {raw.size})")
    raw = raw.reshape(-1, rec)
    labels = raw[:, 0].astype(np.int32)
    # channel-major (3, 32, 32) -> NHWC
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs, labels


def _normalize(imgs_u8: np.ndarray) -> np.ndarray:
    return (imgs_u8.astype(np.float32) / 255.0 - MEAN) / STD


def synthetic_cifar10(n: int, *, seed: int = 0) -> Dataset:
    """Deterministic CIFAR-shaped stand-in (fixed class templates + noise;
    see `tpu_dist.data.mnist.synthetic_mnist` for the scheme)."""
    trng = np.random.default_rng(4242)
    low = trng.normal(size=(10, 8, 8, 3))
    templates = low.repeat(4, axis=1).repeat(4, axis=2)
    templates = (templates - templates.min()) / (np.ptp(templates) + 1e-9)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    noise = rng.normal(scale=0.25, size=(n, 32, 32, 3))
    imgs = np.clip(templates[labels] + noise, 0.0, 1.0)
    return Dataset(
        _normalize((imgs * 255).astype(np.uint8)), labels, synthetic=True
    )


def synthetic_images(
    n: int,
    *,
    shape: tuple[int, int, int] = (224, 224, 3),
    classes: int = 1000,
    seed: int = 0,
) -> Dataset:
    """Generic deterministic image-classification stand-in at arbitrary
    resolution/class count — the ImageNet-shaped path for BASELINE config
    5 (ViT-Ti/16 @ 224) in zero-egress environments.  Same template+noise
    scheme as the MNIST/CIFAR generators (fixed-seed class templates, so
    train/test splits share classes)."""
    h, w, c = shape
    if h % 8 or w % 8:
        raise ValueError(f"image dims {shape} must be multiples of 8")
    trng = np.random.default_rng(777)
    low = trng.normal(size=(classes, h // 8, w // 8, c)).astype(np.float32)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    # upsample per-sample to keep memory bounded at large class counts
    imgs = np.empty((n, h, w, c), np.float32)
    noise_scale = 0.25
    for i in range(n):
        t = low[labels[i]].repeat(8, axis=0).repeat(8, axis=1)
        t = (t - t.min()) / (np.ptp(t) + 1e-9)
        imgs[i] = np.clip(
            t + rng.normal(scale=noise_scale, size=(h, w, c)), 0.0, 1.0
        )
    return Dataset(imgs.astype(np.float32), labels, synthetic=True)


def load_cifar10(split: str = "train", *, limit: int | None = None) -> Dataset:
    files = (
        [f"data_batch_{i}.bin" for i in range(1, 6)]
        if split == "train"
        else ["test_batch.bin"]
    )
    for d in _SEARCH_DIRS:
        if not d:
            continue
        base = Path(d)
        paths = [base / f for f in files]
        if all(p.exists() for p in paths):
            # Truncate in uint8, and stop parsing files once `limit`
            # records are in hand — normalizing all 50k to float32 just to
            # keep a slice would waste ~600 MB of work.
            img_parts, label_parts, have = [], [], 0
            for p in paths:
                imgs, labels = _parse_bin(p)
                img_parts.append(imgs)
                label_parts.append(labels)
                have += len(labels)
                if limit is not None and have >= limit:
                    break
            imgs = np.concatenate(img_parts)[:limit]
            labels = np.concatenate(label_parts)[:limit]
            return Dataset(_normalize(imgs), labels)
    n = limit if limit is not None else (50000 if split == "train" else 10000)
    return synthetic_cifar10(n, seed=0 if split == "train" else 1)
