"""Real handwritten digits with zero egress — sklearn's bundled set.

The reference trains on real MNIST (train_dist.py:76-83); this build
container cannot download it (see tools/fetch_mnist.py for data-ful
deploys).  What the image DOES bundle is scikit-learn's UCI optical
recognition digits: 1,797 genuine handwritten 8×8 samples shipped inside
the sklearn wheel.  ``load_real_digits`` upsamples them to the MNIST
geometry (28, 28, 1) so the reference-parity ConvNet trains unmodified —
real pixels through the full pipeline, clearly labeled as not-MNIST.
"""

from __future__ import annotations

import numpy as np

from tpu_dist.data.mnist import MEAN, STD, Dataset

TRAIN_FRACTION = 0.8
_SPLIT_SEED = 1234  # the reference's seed (train_dist.py:35)


def load_real_digits(split: str = "train") -> Dataset:
    """Deterministic 80/20 split of sklearn's real digit scans.

    8×8 → 28×28 by 3× nearest-neighbor upsampling (24×24) + 2px border,
    then the reference's MNIST normalization constants.  The split
    shuffle is seeded so every process computes identical disjoint
    train/test sets with no communication (the SURVEY §2c.6 invariant).
    """
    from sklearn.datasets import load_digits as _sk_load

    bunch = _sk_load()
    images = bunch.images.astype(np.float32) / 16.0  # (1797, 8, 8) in [0,1]
    labels = bunch.target.astype(np.int32)

    up = images.repeat(3, axis=1).repeat(3, axis=2)  # (n, 24, 24)
    up = np.pad(up, ((0, 0), (2, 2), (2, 2)))  # (n, 28, 28)
    imgs = ((up - MEAN) / STD)[..., None].astype(np.float32)

    rng = np.random.default_rng(_SPLIT_SEED)
    order = rng.permutation(len(imgs))
    n_train = int(len(imgs) * TRAIN_FRACTION)
    idx = order[:n_train] if split == "train" else order[n_train:]
    return Dataset(imgs[idx], labels[idx], synthetic=False)
