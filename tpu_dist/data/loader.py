"""Batch loading — the ``DataLoader(partition, bsz, shuffle=True)`` analog
(train_dist.py:89-90) plus the mesh-aware distributed loader.

XLA needs static shapes, so batches are fixed-size: with ``drop_last=True``
(default) the trailing partial batch is dropped — one compiled program for
every step.  Shuffling is seeded per epoch (reproducible, and identical
across hosts given the same seed, preserving the reference's determinism
invariant SURVEY.md §2c.6).

`DistributedLoader` reproduces the reference's per-rank semantics on a
single-controller mesh: rank r's batch comes from partition r
(`DataPartitioner.use(r)`, each with its own per-epoch shuffle), and the
per-rank batches are stacked rank-major so slicing the global batch over
the ``data`` mesh axis hands each device exactly its partition's samples —
the same samples the reference's per-process loaders would deliver.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from tpu_dist.data.mnist import Dataset
from tpu_dist.data.partition import DataPartitioner, Partition, equal_shards


class Loader:
    """Single-shard loader: seeded per-epoch shuffle, fixed batch size."""

    def __init__(
        self,
        partition,
        batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 1234,
    ):
        self.partition = partition
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed

    def __len__(self) -> int:
        n = len(self.partition)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, epoch: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        n = len(self.partition)
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(n)
        nb = len(self)
        # Fast path: a Partition over an array-backed Dataset admits fancy
        # indexing — one vectorized gather per batch instead of per-sample
        # Python __getitem__ calls (this is the host-side hot input path).
        part = self.partition
        data = getattr(part, "data", None)
        if (
            hasattr(part, "indices")
            and hasattr(data, "images")
            and hasattr(data, "labels")
        ):
            global_idx = np.asarray(part.indices)[order]
            for b in range(nb):
                idx = global_idx[b * self.batch_size : (b + 1) * self.batch_size]
                yield data.images[idx], data.labels[idx]
            return
        for b in range(nb):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            xs, ys = zip(*(part[int(i)] for i in idx))
            yield np.stack(xs), np.asarray(ys)


class DistributedLoader:
    """Global-batch loader over a deterministic partition per rank.

    Reproduces ``partition_dataset`` (train_dist.py:74-91): equal
    fractional shards from a seed-1234 global shuffle, per-rank batch size
    ``global_batch // world_size`` (constant global batch, train_dist.py:85),
    per-epoch per-rank shuffles.  Yields ``(x, y)`` global batches stacked
    rank-major, ready for `tpu_dist.parallel.shard_batch`.
    """

    def __init__(
        self,
        dataset: Dataset,
        world_size: int,
        global_batch: int = 128,
        *,
        seed: int = 1234,
        shuffle: bool = True,
    ):
        if global_batch % world_size:
            raise ValueError(
                f"global batch {global_batch} not divisible by world size "
                f"{world_size}"
            )
        self.world_size = world_size
        self.local_batch = global_batch // world_size
        partitioner = DataPartitioner(dataset, equal_shards(world_size), seed=seed)
        self.loaders = [
            Loader(
                partitioner.use(r),
                self.local_batch,
                shuffle=shuffle,
                # Distinct stream per rank, like each process's own
                # DataLoader shuffle.
                seed=seed + 1000 * r,
            )
            for r in range(world_size)
        ]

    def __len__(self) -> int:
        return min(len(l) for l in self.loaders)

    @property
    def steps_per_epoch(self) -> int:
        return len(self)

    def epoch(self, epoch: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        iters = [l.epoch(epoch) for l in self.loaders]
        for _ in range(len(self)):
            parts = [next(it) for it in iters]
            x = np.concatenate([p[0] for p in parts])
            y = np.concatenate([p[1] for p in parts])
            yield x, y


def prefetch_to_mesh(iterator, mesh, *, depth: int = 2, axis_name: str = "data"):
    """Overlap host batch assembly + H2D transfer with device compute.

    Wraps a host batch iterator: batches are `shard_batch`-placed onto the
    mesh ``depth`` steps ahead (device_put is async), so the accelerator
    never waits on the input pipeline — the torch-DataLoader-workers role
    (the reference relies on torch's native prefetching loader,
    train_dist.py:89) played by XLA's async transfers.
    """
    import collections

    from tpu_dist.parallel.data_parallel import shard_batch

    queue = collections.deque()
    it = iter(iterator)
    try:
        for _ in range(depth):
            queue.append(shard_batch(next(it), mesh, axis_name))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(shard_batch(next(it), mesh, axis_name))
        except StopIteration:
            pass
        yield out


class _WorkerFailure:
    """Queue marker carrying the worker thread's exception."""

    def __init__(self, error: BaseException):
        self.error = error


_END = object()  # queue marker: the wrapped iterator is exhausted


class HostLoader:
    """Background host loader: numpy batch assembly AND the sharded
    ``device_put`` run on a daemon thread, feeding a bounded queue.

    `prefetch_to_mesh` overlaps the H2D *transfer* with compute, but the
    host-side work — pulling the next batch from the wrapped iterator
    (shuffle indexing, np.concatenate) and issuing the device_put — still
    runs on the training loop's thread, between two dispatches.  Under
    the pipelined driver that host slice is the only thing left on the
    critical path, so `HostLoader` moves it off: the worker stays
    ``depth`` batches ahead, and the loop's ``next()`` is a queue pop.

    Semantics are identical to iterating the wrapped iterator through
    `shard_batch` inline: one worker + a FIFO queue preserve order and
    content exactly (the determinism invariant, SURVEY.md §2c.6).  A
    worker exception is re-raised in the consumer — never a hang — and
    `close` (or the ``with`` exit, covering early breaks on preemption)
    always unblocks and joins the thread."""

    def __init__(
        self,
        iterator: Iterator,
        mesh,
        *,
        depth: int = 2,
        axis_name: str = "data",
        spec=None,
    ):
        if depth < 1:
            raise ValueError(f"HostLoader depth must be >= 1, got {depth}")
        import queue as queue_mod
        import threading

        from tpu_dist.parallel.data_parallel import shard_batch

        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
        self._Empty = queue_mod.Empty
        self._Full = queue_mod.Full
        self._stop = threading.Event()
        self._done = False

        def work():
            try:
                for item in iterator:
                    placed = shard_batch(item, mesh, axis_name, spec=spec)
                    if not self._put(placed):
                        return  # closed mid-epoch: drop the batch, exit
                self._put(_END)
            except BaseException as e:  # noqa: BLE001 — must reach consumer
                self._put(_WorkerFailure(e))

        self._thread = threading.Thread(
            target=work, name="tpu-dist-host-loader", daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up when `close` raised the stop flag
        (the consumer is gone — blocking forever would leak the thread)."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except self._Full:
                continue
        return False

    def __iter__(self) -> "HostLoader":
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        while True:
            try:
                item = self._queue.get(timeout=0.5)
                break
            except self._Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # worker gone without an end marker (should be
                    # impossible — it posts _END or _WorkerFailure)
                    self._done = True
                    raise StopIteration from None
        if item is _END:
            self._done = True
            raise StopIteration
        if isinstance(item, _WorkerFailure):
            self._done = True
            raise item.error
        return item

    def close(self) -> None:
        """Shut the worker down (idempotent): raise the stop flag, drain
        the queue so a blocked put wakes, and join."""
        self._stop.set()
        self._done = True
        while True:
            try:
                self._queue.get_nowait()
            except self._Empty:
                break
        self._thread.join(timeout=10.0)

    def __enter__(self) -> "HostLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
