"""MNIST without torchvision.

The reference downloads MNIST via ``datasets.MNIST`` and normalizes with
(0.1307, 0.3081) (train_dist.py:76-83).  This container has zero egress and
no torchvision, so we provide:

1. an IDX-format parser (``load_idx_images``/``load_idx_labels``) that
   reads standard ``train-images-idx3-ubyte`` files (optionally .gz) from
   ``$TPU_DIST_DATA_DIR`` or common locations, and
2. a deterministic synthetic fallback (``synthetic_mnist``): 10 fixed
   seeded class templates + per-sample noise — learnable by the same
   ConvNet, fully reproducible, clearly labeled as synthetic.

Either path yields NHWC float32 images (28, 28, 1), normalized with the
reference's constants, and int32 labels.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass
from pathlib import Path

import numpy as np

MEAN, STD = 0.1307, 0.3081  # train_dist.py:81

_SEARCH_DIRS = (
    os.environ.get("TPU_DIST_DATA_DIR", ""),
    "data/mnist",
    "data",
    os.path.expanduser("~/data/mnist"),
    "/root/data/mnist",
)


@dataclass
class Dataset:
    """In-memory image-classification dataset (indexable like the torch
    Dataset the reference's DataLoader wraps)."""

    images: np.ndarray  # (n, 28, 28, 1) float32, normalized
    labels: np.ndarray  # (n,) int32
    synthetic: bool = False

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, i):
        return self.images[i], self.labels[i]


def _open_maybe_gz(path: Path):
    if path.suffix == ".gz":
        return gzip.open(path, "rb")
    return open(path, "rb")


def _native_idx(path: Path):
    """Try the native mmap reader (tpu_dist/runtime/idx_reader.cc);
    returns None to fall back to the numpy parser (gz files, build
    failures)."""
    if path.suffix == ".gz":
        return None
    try:
        from tpu_dist import runtime

        return runtime.read_idx(path)
    except Exception:
        return None


def load_idx_images(path: Path) -> np.ndarray:
    native = _native_idx(path)
    if native is not None and native.ndim == 3:
        return native[..., None]
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        buf = f.read(n * rows * cols)
    return np.frombuffer(buf, np.uint8).reshape(n, rows, cols, 1)


def load_idx_labels(path: Path) -> np.ndarray:
    native = _native_idx(path)
    if native is not None and native.ndim == 1:
        return native.astype(np.int32)
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        buf = f.read(n)
    return np.frombuffer(buf, np.uint8).astype(np.int32)


def _find_idx(split: str) -> tuple[Path, Path] | None:
    stem = "train" if split == "train" else "t10k"
    for d in _SEARCH_DIRS:
        if not d:
            continue
        base = Path(d)
        for ext in ("", ".gz"):
            img = base / f"{stem}-images-idx3-ubyte{ext}"
            lab = base / f"{stem}-labels-idx1-ubyte{ext}"
            if img.exists() and lab.exists():
                return img, lab
    return None


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    x = images_u8.astype(np.float32) / 255.0
    return (x - MEAN) / STD


def synthetic_mnist(n: int, *, seed: int = 0, n_classes: int = 10) -> Dataset:
    """Deterministic MNIST-shaped stand-in for zero-egress environments.

    Each class is a fixed smooth random template; samples are
    template + Gaussian noise, so the task is learnable (a few epochs reach
    >95% train accuracy with the reference ConvNet) and the loss-decrease /
    cross-replica-identity integration checks (SURVEY.md §4) behave like
    the real thing.  NOT the real MNIST — `load_mnist` prefers real IDX
    files whenever present.
    """
    # Class templates come from a FIXED seed so train/test share the same
    # classes; `seed` only drives the per-sample label/noise draws.
    trng = np.random.default_rng(42)
    # Smooth templates: low-res random fields upsampled to 28x28.
    low = trng.normal(size=(n_classes, 7, 7))
    templates = low.repeat(4, axis=1).repeat(4, axis=2)
    templates = (templates - templates.min()) / (np.ptp(templates) + 1e-9)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    noise = rng.normal(scale=0.25, size=(n, 28, 28))
    imgs = np.clip(templates[labels] + noise, 0.0, 1.0).astype(np.float32)
    imgs_u8 = (imgs * 255).astype(np.uint8)[..., None]
    return Dataset(_normalize(imgs_u8), labels, synthetic=True)


def load_mnist(split: str = "train", *, synthetic_size: int | None = None) -> Dataset:
    """Load MNIST: real IDX files when available, synthetic otherwise.

    ``synthetic_size`` caps the dataset size on BOTH paths (real data is
    truncated; the synthetic fallback is generated at that size).  Default:
    the real split sizes, 60k/10k (train_dist.py:112 assumes 60000).
    """
    found = _find_idx(split)
    if found is not None:
        imgs = load_idx_images(found[0])
        labels = load_idx_labels(found[1])
        if synthetic_size is not None:
            imgs, labels = imgs[:synthetic_size], labels[:synthetic_size]
        return Dataset(_normalize(imgs), labels)
    n = synthetic_size if synthetic_size is not None else (
        60000 if split == "train" else 10000
    )
    return synthetic_mnist(n, seed=0 if split == "train" else 1)
