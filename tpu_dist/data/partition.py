"""Deterministic dataset partitioning.

Rebuild of the reference's data layer (SURVEY.md §1 L4): an
index-indirection view (``Partition``, train_dist.py:17-29) plus a seeded
global-shuffle splitter (``DataPartitioner``, train_dist.py:32-50).

The correctness invariant (SURVEY.md §2c.6): every rank constructs the
partitioner with the *same seed*, computes the *same* global shuffle, and
takes its own disjoint fractional slice — disjoint shards with zero
communication.  We reuse pure-Python ``random.Random(seed)`` exactly so the
split is identical on every host regardless of accelerator (hard part (d)
of SURVEY.md §7: no dependence on any framework RNG).
"""

from __future__ import annotations

import random
from typing import Sequence


class Partition:
    """A view over ``data`` through an index list — ``len``/``getitem``
    indirection, same contract as train_dist.py:17-29."""

    def __init__(self, data, indices: Sequence[int]):
        self.data = data
        self.indices = list(indices)

    def __len__(self) -> int:
        return len(self.indices)

    def __getitem__(self, i: int):
        return self.data[self.indices[i]]


class DataPartitioner:
    """Seeded fractional splitter (train_dist.py:32-50 contract).

    ``sizes`` are fractions (default ``[0.7, 0.2, 0.1]`` like the
    reference); the index list is shuffled once with ``random.Random(seed)``
    and consumed front-to-back per fraction.  ``use(i)`` returns partition
    ``i``.  Default seed 1234 — the reference's determinism anchor
    (train_dist.py:35).
    """

    def __init__(
        self,
        data,
        sizes: Sequence[float] = (0.7, 0.2, 0.1),
        seed: int = 1234,
    ):
        self.data = data
        self.partitions: list[list[int]] = []
        rng = random.Random()
        rng.seed(seed)
        indices = list(range(len(data)))
        rng.shuffle(indices)
        n = len(data)
        for frac in sizes:
            take = int(frac * n)
            self.partitions.append(indices[:take])
            indices = indices[take:]

    def use(self, i: int) -> Partition:
        return Partition(self.data, self.partitions[i])


def equal_shards(n_shards: int) -> list[float]:
    """The training split: equal fractions ``1/world_size``
    (train_dist.py:86)."""
    return [1.0 / n_shards] * n_shards
