"""Byte-level text corpus for language modeling.

The reference's data layer stops at MNIST images (train_dist.py:74-91);
the LM family needs a text path.  Byte-level tokenization (vocab 256)
is the TPU-friendly choice: no tokenizer artifacts to ship, fully
deterministic, any file is a corpus.  The corpus packs the raw bytes
into fixed-length windows — static shapes for the compiled train step —
and splits train/validation by windows, deterministically, so every
host computes the same split with zero communication (the partitioner
invariant, SURVEY.md §2c.6, extended to text).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

VOCAB = 256


class TextCorpus:
    """Fixed-window byte dataset over a text blob or file.

    ``corpus[i] -> (seq_len,) int32`` token window (stride = seq_len,
    non-overlapping).  Compatible with `DataPartitioner` /
    `DistributedLoader` (len/getitem), and with
    `models.lm_loss` (predict byte t+1 from t).
    """

    def __init__(self, text: str | bytes, seq_len: int):
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        if len(data) < seq_len + 1:
            raise ValueError(
                f"corpus of {len(data)} bytes is shorter than one "
                f"window (seq_len={seq_len})"
            )
        self.seq_len = seq_len
        arr = np.frombuffer(data, np.uint8).astype(np.int32)
        n = len(arr) // seq_len
        self._windows = arr[: n * seq_len].reshape(n, seq_len)

    def __len__(self) -> int:
        return len(self._windows)

    def __getitem__(self, i: int):
        return self._windows[i]

    def decode(self, tokens) -> str:
        """Bytes → text (lossy on invalid UTF-8 boundaries)."""
        return bytes(np.asarray(tokens, np.uint8).tolist()).decode(
            "utf-8", errors="replace"
        )


def load_text(
    path: str | Path,
    seq_len: int = 256,
    *,
    val_fraction: float = 0.0,
    seed: int = 1234,
):
    """Load a text file as byte windows.  With ``val_fraction`` returns
    ``(train, val)`` — windows shuffled by ``random.Random(seed)`` and
    split, identically on every host (same contract as
    `DataPartitioner`)."""
    raw = Path(path).read_bytes()
    corpus = TextCorpus(raw, seq_len)
    if not val_fraction:
        return corpus
    import random

    idx = list(range(len(corpus)))
    random.Random(seed).shuffle(idx)
    n_val = max(1, int(len(idx) * val_fraction))
    if n_val >= len(idx):
        raise ValueError(
            f"corpus has only {len(idx)} window(s) of seq_len={seq_len}; "
            f"a val_fraction={val_fraction} split would leave no training "
            f"windows — use a larger corpus, a shorter seq_len, or "
            f"val_fraction=0"
        )
    from tpu_dist.data.partition import Partition

    return (
        Partition(corpus, idx[n_val:]),
        Partition(corpus, idx[:n_val]),
    )
