"""Ahead-of-time model export (serving artifacts).

No reference analog (the 2017 tutorial stops at training,
train_dist.py:103-127) — provided because a complete framework needs a
deployment story.  The TPU-native form is `jax.export`: the jitted
computation lowers to serialized StableHLO with the weights embedded as
constants, producing ONE self-contained artifact that any later JAX
process (same or different host type) can deserialize and call without
the model code, the parameter files, or retracing.

- `export_forward(model, params, state, in_shape, batch, path=)`:
  inference forward (``train=False``) over a fixed batch shape.
- `export_generate(lm, params, prompt_shape, steps, path=, ...)`:
  the KV-cache decode loop (`TransformerLM.generate`) — prefill +
  scanned sampling compiled into the artifact; sampling config is
  baked in unless ``runtime_sampling=True`` threads
  temperature/top_k/top_p through as call-time inputs.
- `load(path_or_bytes)`: returns a plain callable.
- `save_params(params, path)` / `load_params(path, like)`: raw-weights
  artifact for servers that keep sampling a runtime concern
  (`tpu_dist.serve.LMServer.from_artifact`).

Artifacts are platform-checked at call time by jax.export itself
(export on CPU runs on CPU; export under a TPU backend for TPU
serving); shapes are static — pad inputs to the exported batch.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import export as jexport


def _serialize(jitted, args_spec, path: str | Path | None):
    exp = jexport.export(jitted)(*args_spec)
    blob = exp.serialize()
    if path is not None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        Path(path).write_bytes(blob)
    return blob


def export_forward(
    model,
    params: Any,
    state: Any,
    in_shape: tuple[int, ...],
    batch: int = 8,
    *,
    path: str | Path | None = None,
    dtype=jnp.float32,
) -> bytes:
    """Serialize the inference forward ``x -> scores`` with weights
    embedded.  Returns the artifact bytes (also written to ``path``)."""

    @jax.jit
    def forward(x):
        scores, _ = model.apply(params, state, x, train=False)
        return scores

    spec = jax.ShapeDtypeStruct((batch,) + tuple(in_shape), dtype)
    return _serialize(forward, (spec,), path)


def export_generate(
    lm,
    params: Any,
    prompt_shape: tuple[int, int],
    steps: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    path: str | Path | None = None,
    runtime_sampling: bool = False,
) -> bytes:
    """Serialize the LM's KV-cache decode: ``(prompt, key) -> tokens``.
    Prompt shape ``(batch, prompt_len)`` and ``steps`` are baked in
    (static shapes); sampling randomness stays a runtime input.

    By default the SAMPLING CONFIG is baked in too — the artifact
    freezes ``temperature``/``top_k``/``top_p`` at export time.
    ``runtime_sampling=True`` threads them through as call-time inputs
    instead: the artifact's signature becomes ``(prompt, seed,
    temperature, top_k, top_p)`` (``top_k=0`` / ``top_p=1.0`` disable
    the truncations, ``temperature=0`` is greedy — the traced
    stand-ins for ``None``), one artifact serving every sampling
    configuration; the baked kwargs are then ignored.  Servers that
    need PER-REQUEST sampling should load raw weights instead
    (`save_params`/`load_params` + `serve.LMServer`)."""

    if runtime_sampling:
        from tpu_dist.serve.sampling import generate_runtime

        @jax.jit
        def gen_rt(prompt, seed, temperature_, top_k_, top_p_):
            return generate_runtime(
                lm, params, prompt, steps, key=jax.random.key(seed),
                temperature=temperature_, top_k=top_k_, top_p=top_p_,
            )

        spec = (
            jax.ShapeDtypeStruct(tuple(prompt_shape), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
        )
        return _serialize(gen_rt, spec, path)

    @jax.jit
    def gen_seeded(prompt, seed):
        return lm.generate(
            params, prompt, steps, key=jax.random.key(seed),
            temperature=temperature, top_k=top_k, top_p=top_p,
        )

    spec = (
        jax.ShapeDtypeStruct(tuple(prompt_shape), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.uint32),
    )
    return _serialize(gen_seeded, spec, path)


def save_params(params: Any, path: str | Path) -> None:
    """Raw-weights artifact (sha256-verified ``.npz`` via
    `train.checkpoint.save`) — the serving counterpart of the sealed
    StableHLO artifacts for deployments that keep sampling (and
    batching) a runtime concern: `serve.LMServer.from_artifact` loads
    these and decodes with per-request sampling params."""
    from tpu_dist.train import checkpoint

    checkpoint.save(path, params)


def load_params(path: str | Path, like: Any) -> Any:
    """Load a `save_params` artifact back into the structure of
    ``like`` (e.g. a freshly-initialized param pytree)."""
    from tpu_dist.train import checkpoint

    tree, _ = checkpoint.restore(path, like)
    return tree


def load(artifact: str | Path | bytes) -> Callable:
    """Deserialize an exported artifact into a plain callable."""
    blob = (
        artifact
        if isinstance(artifact, (bytes, bytearray))
        else Path(artifact).read_bytes()
    )
    exp = jexport.deserialize(bytes(blob))
    return lambda *args: exp.call(*args)
