"""`tpu_dist.models` — model zoo.

The parity MNIST ConvNet (train_dist.py:53-71 architecture) plus the
extended-config families: ResNet-18 (CIFAR-10) and ViT-Tiny (ImageNet),
BASELINE.json configs 4-5.
"""

from tpu_dist.models.mnist_net import IN_SHAPE, NUM_CLASSES, mnist_net
from tpu_dist.models.resnet import BasicBlock, resnet18
from tpu_dist.models.transformer_lm import (
    TransformerLM,
    lm_loss,
    lm_loss_seq_parallel,
    lm_perplexity,
    markov_table,
    synthetic_tokens,
)
from tpu_dist.models.vit import ViT, vit_tiny

__all__ = [
    "BasicBlock",
    "IN_SHAPE",
    "NUM_CLASSES",
    "TransformerLM",
    "ViT",
    "lm_loss",
    "lm_loss_seq_parallel",
    "lm_perplexity",
    "markov_table",
    "mnist_net",
    "resnet18",
    "synthetic_tokens",
    "vit_tiny",
]
