"""`tpu_dist.models` — see package modules."""
