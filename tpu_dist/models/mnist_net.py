"""The MNIST ConvNet — architecture parity with the reference's ``Net``.

train_dist.py:53-71: conv(1→10, k5) → maxpool2 → relu → conv(10→20, k5) →
dropout2d → maxpool2 → relu → flatten(320) → fc 320→50 → relu → dropout →
fc 50→10 → log_softmax.  Identical layer graph and sizes here, expressed
NHWC (TPU-native layout; flatten size 4·4·20 = 320 either way), with
torch-matching default inits so training dynamics align under the same
hyperparameters (SGD lr=0.01 momentum=0.5, train_dist.py:110).
"""

from __future__ import annotations

from tpu_dist import nn

IN_SHAPE = (28, 28, 1)
NUM_CLASSES = 10


def mnist_net() -> nn.Sequential:
    return nn.Sequential(
        [
            nn.Conv2D(10, 5),
            nn.MaxPool2D(2),
            nn.relu(),
            nn.Conv2D(20, 5),
            nn.Dropout2D(0.5),
            nn.MaxPool2D(2),
            nn.relu(),
            nn.flatten(),  # 4*4*20 = 320
            nn.Dense(50),
            nn.relu(),
            nn.Dropout(0.5),
            nn.Dense(NUM_CLASSES),
            nn.log_softmax(),
        ]
    )
