"""ResNet-18 — extended config 4 (BASELINE.json: "ResNet-18 / CIFAR-10,
larger grads over ICI").

Not in the reference (its only model is the MNIST ConvNet,
train_dist.py:53-71); included because the survey's extended configs use it
to stress gradient-allreduce bandwidth (~11M params vs the ConvNet's ~22k).
CIFAR-style stem (3×3 conv, no max-pool) by default; set ``imagenet_stem``
for the 7×7/maxpool variant.  NHWC throughout; batch-norm state threads
through `tpu_dist.nn.core` state handling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_dist import nn
from tpu_dist.nn.core import Module


class BasicBlock(Module):
    """Two 3×3 convs + identity (or 1×1-projected) shortcut."""

    def __init__(self, features: int, stride: int = 1):
        self.features = features
        self.stride = stride
        self.conv1 = nn.Conv2D(features, 3, stride=stride, padding=1, use_bias=False)
        self.bn1 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(features, 3, padding=1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.proj = nn.Conv2D(features, 1, stride=stride, use_bias=False)
        self.bn_proj = nn.BatchNorm()

    def _needs_proj(self, input_shape):
        return self.stride != 1 or input_shape[-1] != self.features

    def init(self, key, input_shape):
        ks = jax.random.split(key, 3)
        p1, s1 = self.conv1.init(ks[0], input_shape)
        mid_shape = self.conv1.out_shape(input_shape)
        b1, sb1 = self.bn1.init(ks[0], mid_shape)
        p2, s2 = self.conv2.init(ks[1], mid_shape)
        b2, sb2 = self.bn2.init(ks[1], mid_shape)
        params = {"conv1": p1, "bn1": b1, "conv2": p2, "bn2": b2}
        state = {"bn1": sb1, "bn2": sb2}
        if self._needs_proj(input_shape):
            pp, _ = self.proj.init(ks[2], input_shape)
            bp, sbp = self.bn_proj.init(ks[2], mid_shape)
            params["proj"] = pp
            params["bn_proj"] = bp
            state["bn_proj"] = sbp
        return params, state

    def out_shape(self, input_shape):
        return self.conv2.out_shape(self.conv1.out_shape(input_shape))

    def apply(self, params, state, x, *, train=False, key=None):
        new_state = dict(state)
        h, _ = self.conv1.apply(params["conv1"], {}, x)
        h, new_state["bn1"] = self.bn1.apply(params["bn1"], state["bn1"], h, train=train)
        h = jax.nn.relu(h)
        h, _ = self.conv2.apply(params["conv2"], {}, h)
        h, new_state["bn2"] = self.bn2.apply(params["bn2"], state["bn2"], h, train=train)
        if "proj" in params:
            sc, _ = self.proj.apply(params["proj"], {}, x)
            sc, new_state["bn_proj"] = self.bn_proj.apply(
                params["bn_proj"], state["bn_proj"], sc, train=train
            )
        else:
            sc = x
        return jax.nn.relu(h + sc), new_state


def resnet18(num_classes: int = 10, *, imagenet_stem: bool = False) -> nn.Sequential:
    """Standard [2,2,2,2] basic-block ResNet-18."""
    stem: list[Module] = (
        [
            nn.Conv2D(64, 7, stride=2, padding=3, use_bias=False),
            nn.BatchNorm(),
            nn.relu(),
            nn.MaxPool2D(3, 2),
        ]
        if imagenet_stem
        else [nn.Conv2D(64, 3, padding=1, use_bias=False), nn.BatchNorm(), nn.relu()]
    )
    blocks: list[Module] = []
    for stage, features in enumerate((64, 128, 256, 512)):
        for i in range(2):
            stride = 2 if (stage > 0 and i == 0) else 1
            blocks.append(BasicBlock(features, stride))
    head: list[Module] = [nn.GlobalAvgPool(), nn.Dense(num_classes)]
    return nn.Sequential(stem + blocks + head)
