"""Decoder-only transformer LM — the long-context flagship.

No reference analog (the 2017 tutorial has no sequence models,
SURVEY.md §2d) — this family exists because long-context/sequence
parallelism is first-class in this framework: the same parameter pytree
runs either dense (`TransformerLM.apply`) or sequence-parallel
(`TransformerLM.apply_seq_parallel` inside shard_map, attention cores
swapped for `tpu_dist.parallel.ring_attention`), and tests assert the two
agree numerically.  Token embedding, learned positions, pre-norm blocks,
weight-tied output head.

Inference is first-class too: `generate` runs KV-cache autoregressive
decode (prefill + `lax.scan` over single-token steps against a
static-shape cache — one compiled program end to end), with greedy,
temperature, and top-k sampling; `tests/test_generate.py` asserts the
cached path reproduces the dense forward exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_dist import nn
from tpu_dist.nn.core import Module
from tpu_dist.models.vit import EncoderBlock


def _make_sampler(temperature, top_k, top_p, dtype):
    """The decode sampling rule, shared by `TransformerLM.generate` and
    `generate_tensor_parallel`: greedy at ``temperature=0``, otherwise
    tempered softmax optionally truncated to the ``top_k`` highest logits
    and/or the ``top_p`` nucleus.  Deterministic given the key, so every
    model-parallel rank sampling replicated logits with the same key
    picks the same token."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(dtype)
        logits = logits / temperature
        if top_k is not None:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, -1e30, logits)
        if top_p is not None:
            # nucleus: drop tokens in the tail beyond cumulative
            # probability top_p (the highest-probability token always
            # survives: its exclusive-cumsum is 0 < top_p)
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive
            cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True) - 1
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, -1e30, logits)
        return jax.random.categorical(k, logits).astype(dtype)

    return sample


class TransformerLM(Module):
    def __init__(
        self,
        *,
        vocab: int = 256,
        dim: int = 128,
        depth: int = 4,
        heads: int = 4,
        max_seq: int = 1024,
        kv_heads: int | None = None,
        pos_embedding: str = "learned",
        remat: bool = False,
        moe_experts: int = 0,
        moe_capacity_factor: float = 2.0,
        moe_balance_weight: float = 0.01,
        sliding_window: int | None = None,
    ):
        if pos_embedding not in ("learned", "rope"):
            raise ValueError(
                f"pos_embedding must be 'learned' or 'rope', got "
                f"{pos_embedding!r}"
            )
        if moe_experts < 0 or moe_experts == 1:
            # top-2 routing needs at least two experts; a single-expert
            # "mixture" would otherwise surface as an obscure trace-time
            # top_k(k=2) crash deep in the MoE paths.
            raise ValueError(
                f"moe_experts must be 0 (dense MLP) or >= 2 (top-2 "
                f"routing), got {moe_experts}"
            )
        # moe_experts > 0 swaps every block's dense MLP for a top-2
        # (GShard-style) mixture of experts: per block a router
        # ``gate (d, E)`` plus expert-stacked ``up (E, d, 4d)`` /
        # ``down (E, 4d, d)`` weights replace the ``mlp`` subtree.  The
        # dense paths (`apply`, cached decode) evaluate every expert and
        # combine the top-2 (exact, no capacity bound); `loss_moe_ep`
        # trains with real expert parallelism (all_to_all dispatch over
        # a mesh axis, `parallel.moe_mlp_top2`).
        self.moe_experts = moe_experts
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_balance_weight = moe_balance_weight
        # sliding_window=w: every block attends only the local band
        # (q-w, q] — Mistral-style long-context attention; flows through
        # dense forward, cached decode/generate, and (with
        # TPU_DIST_FLASH=1) the windowed flash kernels.
        self.sliding_window = sliding_window
        # Rematerialize each block's forward during backward
        # (jax.checkpoint): activation HBM drops from O(depth · B·S·d)
        # to O(B·S·d) + one extra forward of FLOPs — the standard TPU
        # memory/compute trade for long sequences or big batches.
        self.remat = remat
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        self.kv_heads = heads if kv_heads is None else kv_heads
        self.max_seq = max_seq
        self.pos_embedding = pos_embedding
        self.embed = nn.Embedding(vocab, dim)
        self.blocks = [
            EncoderBlock(
                dim, heads, causal=True, kv_heads=kv_heads,
                use_rope=pos_embedding == "rope",
                sliding_window=sliding_window,
            )
            for _ in range(depth)
        ]
        self.ln = nn.LayerNorm()

    def init(self, key, input_shape=None):
        del input_shape
        ks = jax.random.split(key, len(self.blocks) + 3)
        tok_shape = (self.max_seq, self.dim)
        params = {
            "embed": self.embed.init(ks[0], ())[0],
            "blocks": [
                blk.init(k, tok_shape)[0] for blk, k in zip(self.blocks, ks[2:])
            ],
            "ln": self.ln.init(ks[-1], tok_shape)[0],
        }
        if self.moe_experts:
            E, d, hdim = self.moe_experts, self.dim, 4 * self.dim
            for pb, k in zip(params["blocks"], ks[2:]):
                kg, ku, kd = jax.random.split(jax.random.fold_in(k, 7), 3)
                del pb["mlp"]
                pb["moe"] = {
                    "gate": jax.random.normal(kg, (d, E)) * 0.02,
                    "up": jax.random.normal(ku, (E, d, hdim)) / jnp.sqrt(d),
                    "down": jax.random.normal(kd, (E, hdim, d))
                    / jnp.sqrt(hdim),
                }
        if self.pos_embedding == "learned":
            params["pos"] = (
                jax.random.normal(ks[1], (1, self.max_seq, self.dim)) * 0.02
            )
        return params, {}

    def _require_no_window(self, method: str) -> None:
        """Context-parallel decode does not carry the sliding-window
        band yet (its prompt-phase ring + LSE merge assume the full
        causal mask) — raise loudly instead of silently decoding wrong
        (same precedent as the rope/kv_heads guards).  Windowed
        elsewhere: dense + TP decode, and every training strategy
        except the flash-block ring (which has its own guard)."""
        if self.sliding_window is not None:
            raise ValueError(
                f"{method} does not support sliding_window yet — "
                "context-parallel decode computes the full causal mask; "
                "use dense generate() or generate_tensor_parallel()"
            )

    def _moe_dense(self, pm, x):
        """Exact dense evaluation of the top-2 MoE over ``(..., d)``
        activations: every expert computes every token, the router's
        top-2 (renormalized, GShard-style) combine selects — no capacity
        bound, so this is the drop-free reference the EP path
        (`loss_moe_ep` with ample capacity) matches to fp tolerance."""
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        scores = x2 @ pm["gate"]  # (T, E)
        probs = jax.nn.softmax(scores, axis=-1)
        top2_p, top2_e = jax.lax.top_k(probs, 2)
        gates = top2_p / jnp.maximum(top2_p.sum(-1, keepdims=True), 1e-9)
        hidden = jax.nn.gelu(jnp.einsum("td,edh->eth", x2, pm["up"]))
        y_all = jnp.einsum("eth,ehd->etd", hidden, pm["down"])  # (E, T, d)
        t_idx = jnp.arange(x2.shape[0])
        y = (
            gates[:, 0, None] * y_all[top2_e[:, 0], t_idx]
            + gates[:, 1, None] * y_all[top2_e[:, 1], t_idx]
        )
        return y.reshape(*lead, x.shape[-1])

    def _mlp_or_moe(self, blk, pb, x):
        """The feed-forward half of a block: dense MLP, or the dense
        (every-expert) MoE evaluation for ``moe_experts > 0`` models."""
        if self.moe_experts:
            return self._moe_dense(pb["moe"], x)
        return blk.mlp.apply(pb["mlp"], {}, x)[0]

    def _trunk(self, params, tokens, *, pos_offset=0):
        b, s = tokens.shape
        h = params["embed"]["table"][tokens]
        if self.pos_embedding == "learned":
            h = h + jax.lax.dynamic_slice_in_dim(
                params["pos"], pos_offset, s, axis=1
            )
        # rope: positions enter inside attention (q/k rotation), not here
        return h

    def apply(self, params, state, tokens, *, train=False, key=None,
              attn_mask=None):
        """Dense forward: (batch, seq) int tokens -> (batch, seq, vocab)
        logits (weight-tied head).

        ``attn_mask``: optional boolean — a key-padding mask ``(b, s)``
        (True = real token) or a full ``(..., s, s)`` mask; combined
        with the causal mask in every block (use for padded or packed
        batches)."""
        h = self._trunk(params, tokens)
        for blk, pb in zip(self.blocks, params["blocks"]):
            def block_fn(pb_, h_, blk=blk):
                if not self.moe_experts:
                    return blk.apply(pb_, {}, h_, train=train,
                                     mask=attn_mask)[0]
                x1, _ = blk.ln1.apply(pb_["ln1"], {}, h_)
                o, _ = blk.attn.apply(pb_["attn"], {}, x1, mask=attn_mask)
                h_ = h_ + o
                x2, _ = blk.ln2.apply(pb_["ln2"], {}, h_)
                return h_ + self._mlp_or_moe(blk, pb_, x2)

            if self.remat:
                h = jax.checkpoint(block_fn)(pb, h)
            else:
                h = block_fn(pb, h)
        h, _ = self.ln.apply(params["ln"], {}, h)
        logits = h @ params["embed"]["table"].T
        return logits, state

    # ---- autoregressive inference (KV cache) ----------------------------

    def init_cache(self, batch: int, cache_len: int | None = None, dtype=None):
        """Static-shape KV cache: one ``{"k", "v"}`` pair per block, each
        ``(batch, kv_heads, cache_len, head_dim)`` (GQA models cache only
        their kv heads).  Allocated once and updated in place
        (``dynamic_update_slice``) so every decode step reuses one
        compiled program."""
        L = cache_len or self.max_seq
        hd = self.dim // self.heads
        dt = dtype or jnp.float32
        z = jnp.zeros((batch, self.kv_heads, L, hd), dt)
        return [{"k": z, "v": z} for _ in self.blocks]

    def apply_cached(self, params, tokens, cache, index):
        """Forward ``tokens`` (``(b, s)`` new tokens at global positions
        ``index..index+s-1``) against/into the KV cache.  Same math as
        `apply` restricted to the new positions — `tests/test_generate.py`
        asserts prefill logits match the dense forward.  Returns
        ``(logits (b, s, vocab), new_cache)``."""
        h = self._trunk(params, tokens, pos_offset=index)
        new_cache = []
        for blk, pb, c in zip(self.blocks, params["blocks"], cache):
            x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
            o, ck, cv = blk.attn.apply_cached(
                pb["attn"], x1, c["k"], c["v"], index
            )
            h = h + o
            x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
            h = h + self._mlp_or_moe(blk, pb, x2)
            new_cache.append({"k": ck, "v": cv})
        h, _ = self.ln.apply(params["ln"], {}, h)
        logits = h @ params["embed"]["table"].T
        return logits, new_cache

    def generate(
        self,
        params,
        prompt,
        steps: int,
        *,
        key=None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        cache_len: int | None = None,
        stop_token: int | None = None,
        sampler=None,
    ):
        """Sample ``steps`` tokens after ``prompt`` ``(b, s_prompt)``.

        TPU-native decode: one multi-token prefill, then a ``lax.scan``
        over single-token steps against the static KV cache — the whole
        call is one compiled program (jit-compatible; ``steps``,
        ``temperature``, ``top_k``, ``top_p`` are static).
        ``temperature=0`` is greedy argmax; otherwise softmax sampling at
        the given temperature, optionally truncated to the ``top_k``
        highest-logit tokens and/or the nucleus of smallest-probability
        mass ``top_p`` (both cut the tail; tokens surviving both are
        renormalized by the categorical draw).  Returns ``(b, steps)``
        sampled tokens.

        ``stop_token``: EOS semantics under static shapes — a stream that
        emits it keeps emitting it for the remaining steps (frozen), so
        callers can trim on the first occurrence; shapes and compiled
        programs are unchanged.

        ``sampler``: optional ``(logits, key) -> tokens`` override used
        in place of the static sampling config — the hook through which
        `serve.sampling.generate_runtime` threads TRACED
        temperature/top_k/top_p (one compiled program for every
        sampling configuration); the static kwargs are then ignored.
        """
        from jax import lax

        b, s_p = prompt.shape
        L = cache_len or self.max_seq
        if s_p + steps > L:
            raise ValueError(
                f"prompt {s_p} + steps {steps} exceeds cache length {L}"
            )
        if key is None:
            key = jax.random.key(0)
        sample = (
            sampler
            if sampler is not None
            else _make_sampler(temperature, top_k, top_p, prompt.dtype)
        )

        cache = self.init_cache(b, L, dtype=params["embed"]["table"].dtype)
        logits, cache = self.apply_cached(params, prompt, cache, 0)
        last = logits[:, -1]
        done0 = jnp.zeros((b,), bool)

        def body(carry, k):
            cache, last, idx, done = carry
            tok = sample(last, k)
            if stop_token is not None:
                tok = jnp.where(done, jnp.asarray(stop_token, tok.dtype), tok)
                done = done | (tok == stop_token)
            logits, cache = self.apply_cached(params, tok[:, None], cache, idx)
            return (cache, logits[:, 0], idx + 1, done), tok

        keys = jax.random.split(key, steps)
        _, toks = lax.scan(body, (cache, last, jnp.int32(s_p), done0), keys)
        return jnp.moveaxis(toks, 0, 1)

    def generate_beam(
        self,
        params,
        prompt,
        steps: int,
        *,
        beams: int = 4,
        cache_len: int | None = None,
        return_all: bool = False,
    ):
        """Beam-search decode: keep the ``beams`` highest-total-log-prob
        continuations at every step (deterministic; the search analog of
        `generate`'s sampling).  One prefill on the un-tiled prompt, the
        cache tiled ``beams``-fold, then a ``lax.scan`` whose carry
        re-gathers the KV cache and token history under each step's
        surviving beam indices — still one compiled program.

        No EOS semantics (byte/markov corpora here have none): all beams
        run exactly ``steps`` tokens, so the total log-prob comparison
        needs no length normalization.  Returns the best beam's tokens
        ``(b, steps)`` — or, with ``return_all``, ``(tokens (b, beams,
        steps), scores (b, beams))`` sorted best-first.  ``beams=1``
        reproduces greedy `generate` exactly (tested).
        """
        from jax import lax

        if beams < 1:
            raise ValueError(f"beams must be >= 1, got {beams}")
        b, s_p = prompt.shape
        L = cache_len or self.max_seq
        if s_p + steps > L:
            raise ValueError(
                f"prompt {s_p} + steps {steps} exceeds cache length {L}"
            )
        k = beams
        cache = self.init_cache(b, L, dtype=params["embed"]["table"].dtype)
        logits, cache = self.apply_cached(params, prompt, cache, 0)
        # tile the cache beam-fold: rows [b0 x k, b1 x k, ...]
        cache = jax.tree.map(lambda c: jnp.repeat(c, k, axis=0), cache)
        last = jnp.repeat(logits[:, -1], k, axis=0)  # (b*k, V)
        V = last.shape[-1]
        # beam 0 live, the rest -inf: step 0 picks k distinct tokens from
        # beam 0 instead of k copies of the same argmax
        scores0 = jnp.tile(
            jnp.concatenate(
                [jnp.zeros((1,)), jnp.full((k - 1,), -1e30)]
            )[None, :],
            (b, 1),
        )
        toks0 = jnp.zeros((b, k, steps), prompt.dtype)
        batch_base = (jnp.arange(b)[:, None] * k)  # (b, 1)

        def body(carry, t):
            cache, last, scores, toks = carry
            logp = jax.nn.log_softmax(
                last.astype(jnp.float32), axis=-1
            ).reshape(b, k, V)
            total = scores[:, :, None] + logp  # (b, k, V)
            top_scores, top_idx = lax.top_k(total.reshape(b, k * V), k)
            beam_idx = top_idx // V  # (b, k) surviving parent beams
            tok = (top_idx % V).astype(prompt.dtype)  # (b, k)
            flat = (batch_base + beam_idx).reshape(-1)  # (b*k,)
            cache = jax.tree.map(lambda c: c[flat], cache)
            toks = jnp.take_along_axis(
                toks, beam_idx[:, :, None], axis=1
            )
            toks = lax.dynamic_update_slice_in_dim(
                toks, tok[:, :, None], t, axis=2
            )
            logits, cache = self.apply_cached(
                params, tok.reshape(b * k, 1), cache, s_p + t
            )
            return (cache, logits[:, 0], top_scores, toks), None

        (cache, last, scores, toks), _ = lax.scan(
            body, (cache, last, scores0, toks0), jnp.arange(steps)
        )
        order = jnp.argsort(-scores, axis=1)
        toks = jnp.take_along_axis(toks, order[:, :, None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        if return_all:
            return toks, scores
        return toks[:, 0]

    def apply_tensor_parallel(self, params, tokens, axis_name):
        """Tensor-parallel forward for use INSIDE shard_map over a
        ``model`` axis: attention heads and MLP hidden dims shard across
        ranks (Megatron layout, two psums per block —
        `tpu_dist.parallel.tp_encoder_block`); embeddings, LayerNorms and
        the tied vocab head stay replicated.  Same replicated params as
        `apply`; tests assert fp-tolerance agreement."""
        from tpu_dist.parallel.tensor_parallel import tp_encoder_block

        if self.pos_embedding != "learned":
            raise ValueError(
                "apply_tensor_parallel supports learned positions only "
                "(tp_attention does not apply rope)"
            )
        h = self._trunk(params, tokens)
        for blk, pb in zip(self.blocks, params["blocks"]):
            h = tp_encoder_block(blk, pb, h, axis_name)
        h, _ = self.ln.apply(params["ln"], {}, h)
        return h @ params["embed"]["table"].T

    def loss_tensor_parallel(self, params, tokens, axis_name):
        """Next-token loss with the whole model tensor-parallel INCLUDING
        the output head: blocks via `tp_encoder_block`, cross-entropy via
        `parallel.tp_vocab_cross_entropy` — the full `(b, s, vocab)`
        logits tensor is never materialized on any rank.  Equals
        `lm_loss(apply(...))` (tested).

        Gradient contract (tested): each rank's ``jax.grad`` of this
        loss is its shard's CONTRIBUTION; ``pmean`` over the model axis
        recovers the dense gradient exactly — i.e. treat the model axis
        like a data axis in the gradient average and the training step
        needs no other change."""
        from tpu_dist.parallel.tensor_parallel import (
            tp_encoder_block,
            tp_vocab_cross_entropy,
        )

        if self.pos_embedding != "learned":
            raise ValueError(
                "loss_tensor_parallel supports learned positions only "
                "(tp_attention does not apply rope)"
            )
        h = self._trunk(params, tokens)
        for blk, pb in zip(self.blocks, params["blocks"]):
            h = tp_encoder_block(blk, pb, h, axis_name)
        h, _ = self.ln.apply(params["ln"], {}, h)
        return tp_vocab_cross_entropy(
            h[:, :-1], params["embed"]["table"], tokens[:, 1:], axis_name
        )

    def apply_tensor_parallel_sp(self, params, tokens_local, axis_name):
        """Megatron-SP tensor-parallel forward for use INSIDE shard_map:
        ``tokens_local`` is this rank's SEQUENCE shard (rank-major global
        order), activations stay sequence-sharded between sublayers (1/n
        of `apply_tensor_parallel`'s activation memory), and every
        all-gather/reduce-scatter is a collective matmul
        (`parallel.tp_encoder_block_sp` — the overlap the reference names
        as the per-parameter-loop vs real-DDP gap, tuto.md:319-320,
        applied at layer granularity).  Heads and MLP hidden dims shard
        over ``axis_name`` exactly like `apply_tensor_parallel`.  Returns
        this rank's LOCAL logits ``(b, s_local, vocab)``; gathering them
        over the axis reproduces the dense `apply` (tested)."""
        from jax import lax

        from tpu_dist.parallel.overlap import tp_encoder_block_sp

        if self.pos_embedding != "learned":
            raise ValueError(
                "apply_tensor_parallel_sp supports learned positions only "
                "(tp_attention_overlapped does not apply rope)"
            )
        if self.kv_heads != self.heads:
            raise ValueError(
                "apply_tensor_parallel_sp requires kv_heads == heads "
                "(fused-QKV layout)"
            )
        b, s_local = tokens_local.shape
        n = lax.axis_size(axis_name)
        if n * s_local > self.max_seq:
            raise ValueError(
                f"global sequence {n} ranks x {s_local} tokens = "
                f"{n * s_local} exceeds max_seq {self.max_seq}"
            )
        r = lax.axis_index(axis_name)
        h = self._trunk(params, tokens_local, pos_offset=r * s_local)
        for blk, pb in zip(self.blocks, params["blocks"]):
            h = tp_encoder_block_sp(blk, pb, h, axis_name)
        h, _ = self.ln.apply(params["ln"], {}, h)
        return h @ params["embed"]["table"].T

    def loss_tensor_parallel_sp(self, params, tokens_local, axis_name):
        """Next-token loss over the Megatron-SP forward: local logits +
        `lm_loss_seq_parallel`'s boundary ppermute (each shard's first
        token travels left to become its left neighbor's last target).
        The ``pmean`` over ``axis_name`` equals the dense `lm_loss`
        (tested) — so the model axis folds into the gradient average like
        a data axis, same contract as `loss_tensor_parallel`."""
        logits_local = self.apply_tensor_parallel_sp(
            params, tokens_local, axis_name
        )
        return lm_loss_seq_parallel(logits_local, tokens_local, axis_name)

    def init_cache_tp(self, batch, axis_name, cache_len=None, dtype=None):
        """Per-rank KV cache for tensor-parallel decode, built INSIDE
        shard_map: each rank caches only its head shard —
        ``(batch, kv_heads/n, cache_len, head_dim)`` — so cache HBM
        drops n-fold per chip (the serving reason to decode
        tensor-parallel).  GQA composes: the smaller kv-head set shards
        the same way (``kv_heads % n == 0`` required)."""
        from jax import lax

        n = lax.axis_size(axis_name)
        if self.heads % n:
            raise ValueError(
                f"heads {self.heads} not divisible by axis size {n}"
            )
        if self.kv_heads % n:
            raise ValueError(
                f"kv_heads {self.kv_heads} not divisible by axis size "
                f"{n} — the per-rank KV cache cannot be head-sharded"
            )
        L = cache_len or self.max_seq
        hd = self.dim // self.heads
        z = jnp.zeros(
            (batch, self.kv_heads // n, L, hd), dtype or jnp.float32
        )
        return [{"k": z, "v": z} for _ in self.blocks]

    def apply_cached_tensor_parallel(
        self, params, tokens, cache, index, axis_name
    ):
        """Tensor-parallel `apply_cached` for use INSIDE shard_map:
        sharded-heads incremental attention against the per-rank cache
        (`parallel.tp_attention_cached`) + the Megatron MLP — two psums
        per block, replicated logits out.  Same replicated params as
        `apply`; tests assert the gathered decode equals the dense one."""
        from tpu_dist.parallel.tensor_parallel import (
            tp_attention_cached,
            tp_mlp_block,
        )

        h = self._trunk(params, tokens, pos_offset=index)
        new_cache = []
        for blk, pb, c in zip(self.blocks, params["blocks"], cache):
            x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
            o, ck, cv = tp_attention_cached(
                x1, pb["attn"], blk.attn.heads, c["k"], c["v"], index,
                axis_name, use_rope=self.pos_embedding == "rope",
                window=self.sliding_window,
            )
            h = h + o
            x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
            h = h + tp_mlp_block(x2, pb["mlp"], axis_name)
            new_cache.append({"k": ck, "v": cv})
        h, _ = self.ln.apply(params["ln"], {}, h)
        logits = h @ params["embed"]["table"].T
        return logits, new_cache

    def generate_tensor_parallel(
        self,
        params,
        prompt,
        steps: int,
        axis_name,
        *,
        key=None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        cache_len: int | None = None,
    ):
        """`generate` with the model tensor-parallel, for use INSIDE
        shard_map over ``axis_name``: one prefill + a ``lax.scan`` of
        single-token steps, heads and KV cache sharded n-ways, logits
        replicated by the per-block psum so every rank samples the SAME
        token from the same key (sampling is deterministic given both).
        Multi-chip serving: n chips' HBM bandwidth reads one model —
        the decode-latency analog of the training-side sharding."""
        from jax import lax

        b, s_p = prompt.shape
        L = cache_len or self.max_seq
        if s_p + steps > L:
            raise ValueError(
                f"prompt {s_p} + steps {steps} exceeds cache length {L}"
            )
        if key is None:
            key = jax.random.key(0)
        sample = _make_sampler(temperature, top_k, top_p, prompt.dtype)

        cache = self.init_cache_tp(
            b, axis_name, L, dtype=params["embed"]["table"].dtype
        )
        logits, cache = self.apply_cached_tensor_parallel(
            params, prompt, cache, 0, axis_name
        )
        last = logits[:, -1]

        def body(carry, k):
            cache, last, idx = carry
            tok = sample(last, k)
            logits, cache = self.apply_cached_tensor_parallel(
                params, tok[:, None], cache, idx, axis_name
            )
            return (cache, logits[:, 0], idx + 1), tok

        keys = jax.random.split(key, steps)
        _, toks = lax.scan(body, (cache, last, jnp.int32(s_p)), keys)
        return jnp.moveaxis(toks, 0, 1)

    def apply_pipeline(
        self, params, tokens, axis_name, *,
        n_microbatches: int = 4, interleave: int = 1, head_params=None,
    ):
        """Pipeline-parallel forward for use INSIDE shard_map over a
        ``pipe`` axis: rank r runs ``depth / n`` consecutive blocks as
        its stage; activations hop stage-to-stage through the GPipe
        microbatch schedule (`tpu_dist.parallel.pipeline_apply`).  The
        embedding trunk and the LN/vocab head are token-local and cheap,
        so they run replicated on every rank rather than as dedicated
        stages.  Same replicated params as `apply`; tests assert
        agreement.

        ``interleave=v > 1`` switches to the interleaved (Megatron
        1F1B-style) schedule: rank r holds ``v`` chunks of
        ``depth/(n·v)`` blocks (chunk c = global stage ``c·n + r``),
        cutting the bubble from ``(n-1)/(M+n-1)`` to
        ``(n-1)/(M·v+n-1)``; ``n_microbatches`` must then be a multiple
        of the pipe world.

        ``head_params``: optional ``(ln_params, embed_table)`` override
        for the replicated LN/vocab head — `loss_pipeline` passes
        gradient-scaled copies so the training gradient contract holds;
        forward values are unchanged."""
        from jax import lax

        from tpu_dist.parallel.pipeline import (
            pipeline_apply,
            pipeline_apply_interleaved,
        )
        from tpu_dist.utils.tree import stack_pytrees

        n = lax.axis_size(axis_name)
        r = lax.axis_index(axis_name)
        depth = len(self.blocks)
        if depth % (n * interleave):
            raise ValueError(
                f"depth {depth} not divisible by pipeline world {n} x "
                f"interleave {interleave}"
            )
        stacked = stack_pytrees(params["blocks"])  # (depth, ...) leaves
        blk = self.blocks[0]  # stages share the block architecture

        def run_blocks(stage_params, h, count):
            for i in range(count):
                pb = jax.tree.map(lambda t: t[i], stage_params)
                h, _ = blk.apply(pb, {}, h)
            return h

        h = self._trunk(params, tokens)
        if interleave == 1:
            per = depth // n
            mine = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, r * per, per, 0),
                stacked,
            )
            h = pipeline_apply(
                lambda p, a: run_blocks(p, a, per), mine, h,
                n_microbatches=n_microbatches, axis_name=axis_name,
            )
        else:
            pc = depth // (n * interleave)
            chunks = [
                jax.tree.map(
                    lambda t: lax.dynamic_slice_in_dim(
                        t, (c * n + r) * pc, pc, 0
                    ),
                    stacked,
                )
                for c in range(interleave)
            ]
            chunks_local = jax.tree.map(
                lambda *xs: jnp.stack(xs), *chunks
            )
            h = pipeline_apply_interleaved(
                lambda p, a: run_blocks(p, a, pc), chunks_local, h,
                n_microbatches=n_microbatches, axis_name=axis_name,
            )
        ln_p, table = (
            head_params
            if head_params is not None
            else (params["ln"], params["embed"]["table"])
        )
        h, _ = self.ln.apply(ln_p, {}, h)
        return h @ table.T

    def loss_pipeline(
        self, params, tokens, axis_name, *,
        n_microbatches: int = 4, interleave: int = 1,
        engine: bool = False, remat_stages: bool = False,
        schedule_kind: str | None = None,
    ):
        """Pipeline-parallel TRAINING loss for use INSIDE shard_map over
        a ``pipe`` axis (`parallel.make_spmd_train_step` with
        ``grad_psum_axes=(axis_name,)``).

        ``engine=False`` (the GPipe-era path): forward-only scheduling
        through `apply_pipeline`; autodiff replays the schedule scan in
        reverse, so activation memory is O(M) scan residuals.  Gradient
        contract: the psum over ``axis_name`` of the per-rank grad
        pytrees equals the dense `lm_loss` gradient (tested).  The
        pieces: block grads land only on the rank owning each stage
        (`parallel.pipeline_apply`'s convention — summing recovers the
        sequential grads); the embedding-lookup/positional grads land
        only on rank 0 (it alone injects microbatches); the LN/vocab
        head runs REPLICATED on every rank, so its params enter with
        their differentiable path scaled 1/n (forward value unchanged)
        — n identical head grads then psum back to exactly the dense
        grad, and the weight-tied embedding table gets its lookup and
        head contributions each counted once.

        ``engine=True`` routes through the schedule-driven TRUE 1F1B
        executor instead (`loss_pipeline_1f1b`): backward ticks
        interleave with forward ticks, activation stash O(n·v) not
        O(M).  Same psum gradient contract (tested against this path
        and against dense)."""
        if engine:
            return self.loss_pipeline_1f1b(
                params, tokens, axis_name,
                n_microbatches=n_microbatches, interleave=interleave,
                remat_stages=remat_stages, schedule_kind=schedule_kind,
            )
        from jax import lax

        n = lax.axis_size(axis_name)

        def scale(a):
            return a / n + lax.stop_gradient(a * (n - 1) / n)

        head = (
            jax.tree.map(scale, params["ln"]),
            scale(params["embed"]["table"]),
        )
        logits = self.apply_pipeline(
            params, tokens, axis_name,
            n_microbatches=n_microbatches, interleave=interleave,
            head_params=head,
        )
        return lm_loss(logits.astype(jnp.float32), tokens)

    def loss_pipeline_1f1b(
        self, params, tokens, axis_name, *,
        n_microbatches: int = 4, interleave: int = 1,
        remat_stages: bool = False, schedule_kind: str | None = None,
    ):
        """TRUE 1F1B pipeline training loss — the schedule-driven engine
        (`parallel.pipeline_engine_loss`) for use INSIDE shard_map over
        a ``pipe`` axis.

        Stage split matches `apply_pipeline` exactly (rank r, chunk c =
        global stage ``c·n + r`` of ``depth/(n·v)`` consecutive blocks;
        the embedding trunk runs replicated up front), but the loss is
        computed PER MICROBATCH on the last global stage, whose backward
        starts the tick after that microbatch's forward — forwards and
        backwards interleave tick-for-tick and the activation stash
        holds O(n·v) stage inputs instead of O(M) scan residuals.

        Gradient contract (psum over ``axis_name`` equals the dense
        `lm_loss` gradient, tested): chunk-block grads land on the
        owning rank, the LN/vocab-head grads land on rank n-1 (the only
        rank that runs the head), and the embedding-lookup/positional
        grads land on rank 0 via the engine's trunk cotangent — each
        contribution counted exactly once, no replicated-head 1/n
        scaling needed.

        ``schedule_kind`` overrides the schedule table (default:
        ``'interleaved_1f1b'`` when ``interleave > 1`` else ``'1f1b'``;
        ``'gpipe'`` gives the flush schedule with the O(M) stash —
        useful for measuring what 1F1B buys)."""
        from jax import lax

        from tpu_dist.parallel.pipeline import (
            build_schedule,
            default_schedule_kind,
            pipeline_engine_loss,
        )
        from tpu_dist.utils.tree import stack_pytrees

        n = lax.axis_size(axis_name)
        r = lax.axis_index(axis_name)
        v = interleave
        depth = len(self.blocks)
        if depth % (n * v):
            raise ValueError(
                f"depth {depth} not divisible by pipeline world {n} x "
                f"interleave {v}"
            )
        pc = depth // (n * v)
        stacked = stack_pytrees(params["blocks"])
        chunks = [
            jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, (c * n + r) * pc, pc, 0),
                stacked,
            )
            for c in range(v)
        ]
        chunks_local = stack_pytrees(chunks)
        blk = self.blocks[0]  # stages share the block architecture

        def stage_fn(chunk_params, a):
            for i in range(pc):
                pb = jax.tree.map(lambda t: t[i], chunk_params)
                a, _ = blk.apply(pb, {}, a)
            return a

        def last_fn(chunk_params, head, x_in, tok_mb):
            y = stage_fn(chunk_params, x_in)
            ln_p, table = head
            y, _ = self.ln.apply(ln_p, {}, y)
            return lm_loss((y @ table.T).astype(jnp.float32), tok_mb)

        kind = schedule_kind or default_schedule_kind(v)
        sched = build_schedule(n, n_microbatches, v, kind)
        h = self._trunk(params, tokens)
        return pipeline_engine_loss(
            stage_fn, last_fn, sched, chunks_local,
            (params["ln"], params["embed"]["table"]), h, tokens,
            axis_name=axis_name, remat_stages=remat_stages,
        )

    def apply_moe_ep(self, params, tokens_local, axis_name):
        """Expert-parallel forward for use INSIDE shard_map: the batch
        is sharded over ``axis_name`` (attention is per-sample, so batch
        sharding is exact) and each rank owns ONE expert per block —
        every MoE layer dispatches its local tokens to their routed
        experts with one ``all_to_all`` each way
        (`parallel.moe_mlp_top2`).  Requires ``moe_experts == axis
        size``.  Params enter replicated (each rank slices its expert
        row), which makes the gradient contract a UNIFORM pmean over
        ``axis_name``: shared params replicate per-rank full grads, and
        each expert's grads appear on exactly one rank (the psum inside
        pmean sums them once, the 1/n is the global-batch mean).

        Returns ``(logits_local, balance)`` — the mean GShard balance
        loss over blocks (its gradient flows into the routers).
        """
        from jax import lax

        from tpu_dist.parallel.moe import moe_mlp_top2

        n = lax.axis_size(axis_name)
        if self.moe_experts != n:
            raise ValueError(
                f"moe_experts {self.moe_experts} != expert-axis size {n} "
                "(one expert per rank)"
            )
        r = lax.axis_index(axis_name)
        b, s = tokens_local.shape
        h = self._trunk(params, tokens_local)
        balances = []
        for blk, pb in zip(self.blocks, params["blocks"]):
            x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
            o, _ = blk.attn.apply(pb["attn"], {}, x1)
            h = h + o
            x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
            pm = pb["moe"]
            y2, stats = moe_mlp_top2(
                x2.reshape(b * s, self.dim),
                pm["gate"],
                lax.dynamic_index_in_dim(pm["up"], r, 0, keepdims=False),
                lax.dynamic_index_in_dim(pm["down"], r, 0, keepdims=False),
                axis_name=axis_name,
                capacity_factor=self.moe_capacity_factor,
            )
            h = h + y2.reshape(b, s, self.dim)
            balances.append(stats["balance_loss"])
        h, _ = self.ln.apply(params["ln"], {}, h)
        logits = h @ params["embed"]["table"].T
        return logits, jnp.mean(jnp.stack(balances))

    def loss_moe_ep(self, params, tokens_local, axis_name):
        """Expert-parallel training loss: local next-token loss plus
        ``moe_balance_weight ×`` the mean balance loss (the router
        regularizer keeping experts utilized).  pmean over ``axis_name``
        == the global-batch loss; uniform-pmean gradient contract per
        `apply_moe_ep` (tested == dense in test_moe.py)."""
        logits, balance = self.apply_moe_ep(params, tokens_local, axis_name)
        return (
            lm_loss(logits.astype(jnp.float32), tokens_local)
            + self.moe_balance_weight * balance
        )

    def apply_seq_parallel(self, params, tokens_local, axis_name, *,
                           flash: bool = False, interpret: bool = False,
                           attention: str = "ring"):
        """Sequence-parallel forward for use INSIDE shard_map: tokens are
        the local sequence shard; attention runs as a ppermute ring over
        ``axis_name``; everything else is token-local.  Same params as
        `apply` — tests assert bitwise-tolerance agreement.

        ``flash=True`` computes each ring block with the Pallas flash
        kernel (`parallel.ring_attention_flash`) — same numbers, no
        per-block (s_local, s_local) score materialization; ``interpret``
        runs the kernel in interpret mode (CPU-sim testing).
        ``attention="ulysses"`` swaps the ring core for the all-to-all
        head-resharding strategy (`parallel.ulysses_attention`; needs
        ``heads % world == 0``) — pick by topology: the ring hides
        communication behind block matmuls on a torus, Ulysses pays two
        all-to-alls but runs full-sequence attention locally."""
        from jax import lax

        from tpu_dist.parallel.ring_attention import RingMultiHeadAttention

        # flash+window is refused by RingMultiHeadAttention's own guard
        if self.kv_heads != self.heads:
            raise ValueError(
                "apply_seq_parallel requires kv_heads == heads (the ring "
                "attention core uses the fused-QKV layout)"
            )
        b, s_local = tokens_local.shape
        # Same block math as `apply`, with the attention core swapped for
        # the ring module (identical param structure by construction).
        # Constructed BEFORE any axis query so its validation (e.g. the
        # flash+window refusal) raises cleanly outside shard_map too.
        ring_mha = RingMultiHeadAttention(
            self.dim, self.heads, axis_name=axis_name, causal=True,
            use_rope=self.pos_embedding == "rope",
            use_flash=flash, interpret=interpret, core=attention,
            sliding_window=self.sliding_window,
        )
        n = lax.axis_size(axis_name)
        if n * s_local > self.max_seq:
            raise ValueError(
                f"global sequence {n} ranks x {s_local} tokens = "
                f"{n * s_local} exceeds max_seq {self.max_seq} — the "
                f"positional table would silently clamp"
            )
        r = lax.axis_index(axis_name)
        h = self._trunk(params, tokens_local, pos_offset=r * s_local)
        for blk, pb in zip(self.blocks, params["blocks"]):
            x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
            o, _ = ring_mha.apply(pb["attn"], {}, x1)
            h = h + o
            x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
            m, _ = blk.mlp.apply(pb["mlp"], {}, x2)
            h = h + m
        h, _ = self.ln.apply(params["ln"], {}, h)
        return h @ params["embed"]["table"].T

    # ---- context-parallel decode (sequence-sharded prompt cache) -------

    def _project_qkv(self, attn_params, x, positions):
        """Fused-QKV projection + optional rope at GLOBAL ``positions``;
        x (b, s, d) -> q, k, v each (b, heads, s, head_dim)."""
        from tpu_dist.nn.attention import rope

        b, s, _ = x.shape
        hd = self.dim // self.heads
        qkv = (x @ attn_params["qkv"]["w"] + attn_params["qkv"]["b"]).reshape(
            b, s, 3, self.heads, hd
        )
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        if self.pos_embedding == "rope":
            q, k = rope(q, positions), rope(k, positions)
        return q, k, v

    def generate_seq_parallel(
        self,
        params,
        prompt_local,
        steps: int,
        axis_name,
        *,
        key=None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
    ):
        """Decode after a SEQUENCE-SHARDED prompt, for use INSIDE
        shard_map over ``axis_name`` — context-parallel serving: a
        prompt too long for one chip's KV cache is prefilled with ring
        attention and its K/V stay sharded, 1/n per rank, for the whole
        decode.

        Prefill: `apply_seq_parallel`'s block math, additionally saving
        each block's LOCAL K/V shard (the distributed prompt cache); the
        last global position's logits reach every rank with one psum.
        Decode: each new token is computed replicated; every rank scores
        it against its prompt-cache shard, and the per-rank partials
        merge EXACTLY via log-sum-exp (the flash/ring recombination) with
        a small replicated cache of the generated window.  Every rank
        samples the same token from the same key.  Token-exact vs the
        dense `generate` on the gathered prompt (tested; fused-QKV
        layout, learned or rope positions).

        ``prompt_local``: (b, s_p_local) — rank r holds global positions
        ``r*s_p_local ..``.  Returns (b, steps) sampled tokens
        (replicated).
        """
        self._require_no_window("generate_seq_parallel")
        from jax import lax

        if self.kv_heads != self.heads:
            raise ValueError(
                "generate_seq_parallel requires kv_heads == heads "
                "(fused-QKV layout)"
            )
        n = lax.axis_size(axis_name)
        r = lax.axis_index(axis_name)
        b, s_l = prompt_local.shape
        S = n * s_l  # global prompt length
        if S + steps > self.max_seq:
            raise ValueError(
                f"prompt {S} + steps {steps} exceeds max_seq {self.max_seq}"
            )
        if key is None:
            key = jax.random.key(0)
        sample = _make_sampler(temperature, top_k, top_p, prompt_local.dtype)
        from tpu_dist.parallel.ring_attention import ring_attention

        # --- prefill: ring attention, saving local K/V per block ---
        h = self._trunk(params, prompt_local, pos_offset=r * s_l)
        pos_local = r * s_l + jnp.arange(s_l)
        prompt_cache = []
        for blk, pb in zip(self.blocks, params["blocks"]):
            x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
            q, k, v = self._project_qkv(pb["attn"], x1, pos_local)
            o = ring_attention(q, k, v, axis_name, causal=True)
            o = jnp.moveaxis(o, 1, 2).reshape(b, s_l, self.dim)
            h = h + o @ pb["attn"]["out"]["w"] + pb["attn"]["out"]["b"]
            x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
            m, _ = blk.mlp.apply(pb["mlp"], {}, x2)
            h = h + m
            prompt_cache.append({"k": k, "v": v})  # (b, heads, s_l, hd)
        h, _ = self.ln.apply(params["ln"], {}, h)
        last_local = h[:, -1] @ params["embed"]["table"].T  # (b, V)
        # the last GLOBAL token lives on rank n-1; one psum replicates it
        last = lax.psum(
            jnp.where(r == n - 1, last_local, jnp.zeros_like(last_local)),
            axis_name,
        )

        # --- decode: replicated window cache + sharded prompt cache ---
        hd = self.dim // self.heads
        dt = params["embed"]["table"].dtype
        dec_cache = [
            {
                "k": jnp.zeros((b, self.heads, steps, hd), dt),
                "v": jnp.zeros((b, self.heads, steps, hd), dt),
            }
            for _ in self.blocks
        ]

        def decode_one(tok, dec_cache, t):
            """One replicated token at global position S + t."""
            pos = S + t
            hh = self._trunk(params, tok[:, None], pos_offset=pos)
            new_cache = []
            for blk, pb, pc, dc in zip(
                self.blocks, params["blocks"], prompt_cache, dec_cache
            ):
                x1, _ = blk.ln1.apply(pb["ln1"], {}, hh)
                q, k_new, v_new = self._project_qkv(
                    pb["attn"], x1, pos + jnp.arange(1)
                )
                dk = lax.dynamic_update_slice_in_dim(
                    dc["k"], k_new.astype(dt), t, axis=2
                )
                dv = lax.dynamic_update_slice_in_dim(
                    dc["v"], v_new.astype(dt), t, axis=2
                )
                scale = hd**-0.5
                qs = (q * scale).astype(jnp.float32)
                # partial attention over this rank's prompt shard
                lg_p = jnp.einsum(
                    "bhqd,bhkd->bhqk", qs, pc["k"].astype(jnp.float32)
                )
                m_p = lg_p.max(-1)
                p_p = jnp.exp(lg_p - m_p[..., None])
                l_p = p_p.sum(-1)
                out_p = jnp.einsum(
                    "bhqk,bhkd->bhqd", p_p, pc["v"].astype(jnp.float32)
                ) / l_p[..., None]
                lse_p = m_p + jnp.log(l_p)
                # replicated decode window (positions < t+1 valid)
                lg_d = jnp.einsum(
                    "bhqd,bhkd->bhqk", qs, dk.astype(jnp.float32)
                )
                valid = (jnp.arange(dk.shape[2]) <= t)[None, None, None, :]
                lg_d = jnp.where(valid, lg_d, -1e30)
                m_d = lg_d.max(-1)
                p_d = jnp.exp(lg_d - m_d[..., None])
                p_d = jnp.where(valid, p_d, 0.0)
                l_d = p_d.sum(-1)
                out_d = jnp.einsum(
                    "bhqk,bhkd->bhqd", p_d, dv.astype(jnp.float32)
                ) / jnp.maximum(l_d, 1e-30)[..., None]
                lse_d = m_d + jnp.log(jnp.maximum(l_d, 1e-30))
                # exact merge: psum the prompt partials, add the decode
                # part ONCE (it is identical on every rank)
                m_star = jnp.maximum(lax.pmax(lse_p, axis_name), lse_d)
                w_p = jnp.exp(lse_p - m_star)
                w_d = jnp.exp(lse_d - m_star)
                num = lax.psum(w_p[..., None] * out_p, axis_name) + (
                    w_d[..., None] * out_d
                )
                den = lax.psum(w_p, axis_name) + w_d
                o = (num / den[..., None]).astype(hh.dtype)
                o = jnp.moveaxis(o, 1, 2).reshape(b, 1, self.dim)
                hh = hh + o @ pb["attn"]["out"]["w"] + pb["attn"]["out"]["b"]
                x2, _ = blk.ln2.apply(pb["ln2"], {}, hh)
                mm, _ = blk.mlp.apply(pb["mlp"], {}, x2)
                hh = hh + mm
                new_cache.append({"k": dk, "v": dv})
            hh, _ = self.ln.apply(params["ln"], {}, hh)
            return hh[:, 0] @ params["embed"]["table"].T, new_cache

        def body(carry, kk):
            dec_cache, last, t = carry
            tok = sample(last, kk)
            logits, dec_cache = decode_one(tok, dec_cache, t)
            return (dec_cache, logits, t + 1), tok

        keys = jax.random.split(key, steps)
        _, toks = lax.scan(body, (dec_cache, last, jnp.int32(0)), keys)
        return jnp.moveaxis(toks, 0, 1)


def lm_loss(
    logits: jax.Array, tokens: jax.Array, *, mask: jax.Array | None = None
) -> jax.Array:
    """Next-token cross-entropy: predict tokens[:, 1:] from positions
    [:, :-1].

    ``mask``: optional ``(b, s)`` boolean of REAL (non-pad) tokens; a
    position's loss counts only when its target token is real, and the
    mean is over counted positions — pair with ``apply(attn_mask=...)``
    so padded batches train identically to trimmed ones (tested)."""
    b, s, V = logits.shape
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp, tokens[:, 1:, None], axis=-1
    )[..., 0]
    if mask is None:
        return -picked.mean()
    w = mask[:, 1:].astype(jnp.float32)
    return -(picked * w).sum() / jnp.maximum(w.sum(), 1.0)


def lm_loss_seq_parallel(
    logits_local: jax.Array, tokens_local: jax.Array, axis_name: str
) -> jax.Array:
    """Next-token loss over sequence shards, boundary-correct.

    Position ``t``'s target is token ``t+1`` — for the LAST position of
    each shard that token lives on the RIGHT neighbor, so targets are
    built by shifting in each right neighbor's first token via
    ``ppermute`` (one tiny collective).  The final global position has no
    target and is masked.  Averaged so that the mean over ranks equals
    the dense `lm_loss` on the gathered sequence (tests assert this),
    which makes it directly usable under a data-axis ``pmean``.
    """
    from jax import lax

    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    b, s_local, vocab = logits_local.shape
    # left neighbor -> me: I receive my RIGHT... ppermute ring sends
    # i -> i+1; to receive the right neighbor's first token, send each
    # shard's first token LEFT: perm (i -> i-1).
    first = tokens_local[:, :1]
    from_right = lax.ppermute(
        first, axis_name, [(i, (i - 1) % n) for i in range(n)]
    )
    targets = jnp.concatenate([tokens_local[:, 1:], from_right], axis=1)
    # f32 like lm_loss: bf16 log-softmax would make the TP trajectory
    # diverge from the dense one under compute_dtype='bfloat16'
    logp = jax.nn.log_softmax(logits_local.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    # mask the last global position (rank n-1's last token has no target)
    pos_valid = jnp.where(
        (r == n - 1)
        & (jnp.arange(s_local) == s_local - 1)[None, :].astype(bool),
        0.0,
        1.0,
    )
    # normalize so the pmean over ranks equals the dense mean over the
    # (S_global - 1) predicted positions
    total_positions = n * s_local - 1
    return -(picked * pos_valid).sum() / (b * total_positions / n)


def markov_table(vocab: int = 256, *, seed: int = 0):
    """The transition table behind `synthetic_tokens` (a seeded
    permutation): ``next_token = table[token]``.  Exposed so demos/tests
    can verify generated continuations against the chain without
    replaying the corpus RNG call order by hand."""
    import numpy as np

    return np.random.default_rng(seed).permutation(vocab)


def synthetic_tokens(
    n: int, seq: int, vocab: int = 256, *, seed: int = 0
) -> jax.Array:
    """Deterministic learnable token streams: a fixed random Markov chain
    (every next-token distribution is a delta on a seeded permutation —
    see `markov_table`), so a model that learns the transition table
    drives loss toward zero."""
    import numpy as np

    rng = np.random.default_rng(seed)
    table = rng.permutation(vocab)
    starts = rng.integers(0, vocab, size=n)
    out = np.empty((n, seq), np.int32)
    out[:, 0] = starts
    for t in range(1, seq):
        out[:, t] = table[out[:, t - 1]]
    return jnp.asarray(out)


def lm_perplexity(lm, params, tokens, *, batch: int = 64):
    """Token-weighted mean next-token loss and perplexity over a
    ``(N, S)`` token array (e.g. stacked `data.TextCorpus` windows).

    Batches are processed with at most two compiled shapes (full batches
    plus one tail batch); each window contributes ``S - 1`` predicted
    positions.  Returns ``(mean_loss, perplexity)`` — the reference-style
    scalar observable for the LM family (perplexity = exp(loss))."""
    import numpy as np

    n, s = tokens.shape
    if n == 0:
        raise ValueError("empty token array")

    @jax.jit
    def batch_loss(p, t):
        logits, _ = lm.apply(p, {}, t)
        return lm_loss(logits, t)

    total, weight = 0.0, 0
    for i in range(0, n, batch):
        chunk = tokens[i : i + batch]
        loss = float(batch_loss(params, jnp.asarray(np.asarray(chunk))))
        w = chunk.shape[0] * (s - 1)
        total += loss * w
        weight += w
    mean = total / weight
    return mean, float(jnp.exp(mean))
