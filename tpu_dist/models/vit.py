"""ViT-Tiny — extended config 5 (BASELINE.json: "ViT-Tiny / ImageNet-1k,
stress allreduce bandwidth at pod scale").

Standard ViT-Ti/16: dim 192, depth 12, heads 3, MLP ratio 4, learned
position embeddings, CLS token.  Built from `tpu_dist.nn` primitives; the
attention core is `tpu_dist.nn.dot_product_attention`, the same function
the sequence-parallel ring path shards (`tpu_dist.parallel.ring_attention`),
so single-device and ring-sharded execution are numerically comparable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_dist import nn
from tpu_dist.nn.core import Module


class MLP(Module):
    def __init__(self, dim: int, hidden: int):
        self.fc1 = nn.Dense(hidden)
        self.fc2 = nn.Dense(dim)

    def init(self, key, input_shape):
        k1, k2 = jax.random.split(key)
        p1, _ = self.fc1.init(k1, input_shape)
        p2, _ = self.fc2.init(k2, self.fc1.out_shape(input_shape))
        return {"fc1": p1, "fc2": p2}, {}

    def apply(self, params, state, x, *, train=False, key=None):
        h, _ = self.fc1.apply(params["fc1"], {}, x)
        h = jax.nn.gelu(h)
        h, _ = self.fc2.apply(params["fc2"], {}, h)
        return h, state


class EncoderBlock(Module):
    """Pre-norm transformer block: x + MHA(LN(x)); x + MLP(LN(x))."""

    def __init__(self, dim: int, heads: int, mlp_ratio: int = 4, *,
                 causal: bool = False, kv_heads: int | None = None,
                 use_rope: bool = False, sliding_window: int | None = None):
        self.ln1 = nn.LayerNorm()
        self.attn = nn.MultiHeadAttention(
            dim, heads, causal=causal, kv_heads=kv_heads, use_rope=use_rope,
            sliding_window=sliding_window,
        )
        self.ln2 = nn.LayerNorm()
        self.mlp = MLP(dim, dim * mlp_ratio)

    def init(self, key, input_shape):
        ks = jax.random.split(key, 4)
        pl1, _ = self.ln1.init(ks[0], input_shape)
        pa, _ = self.attn.init(ks[1], input_shape)
        pl2, _ = self.ln2.init(ks[2], input_shape)
        pm, _ = self.mlp.init(ks[3], input_shape)
        return {"ln1": pl1, "attn": pa, "ln2": pl2, "mlp": pm}, {}

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        h, _ = self.ln1.apply(params["ln1"], {}, x)
        h, _ = self.attn.apply(params["attn"], {}, h, mask=mask)
        x = x + h
        h, _ = self.ln2.apply(params["ln2"], {}, x)
        h, _ = self.mlp.apply(params["mlp"], {}, h)
        return x + h, state


class ViT(Module):
    def __init__(
        self,
        *,
        image_size: int = 224,
        patch: int = 16,
        dim: int = 192,
        depth: int = 12,
        heads: int = 3,
        num_classes: int = 1000,
        channels: int = 3,
    ):
        if image_size % patch:
            raise ValueError(f"image size {image_size} not divisible by patch {patch}")
        self.patch = patch
        self.dim = dim
        self.num_tokens = (image_size // patch) ** 2 + 1  # + CLS
        self.embed = nn.Conv2D(dim, patch, stride=patch)
        self.blocks = [EncoderBlock(dim, heads) for _ in range(depth)]
        self.ln = nn.LayerNorm()
        self.head = nn.Dense(num_classes)
        self.in_shape = (image_size, image_size, channels)

    def init(self, key, input_shape):
        ks = jax.random.split(key, len(self.blocks) + 4)
        pe, _ = self.embed.init(ks[0], input_shape)
        tok_shape = (self.num_tokens, self.dim)
        params = {
            "embed": pe,
            "cls": jnp.zeros((1, 1, self.dim)),
            "pos": jax.random.normal(ks[1], (1, self.num_tokens, self.dim)) * 0.02,
            "blocks": [],
            "ln": self.ln.init(ks[2], tok_shape)[0],
            "head": self.head.init(ks[3], tok_shape)[0],
        }
        for blk, k in zip(self.blocks, ks[4:]):
            pb, _ = blk.init(k, tok_shape)
            params["blocks"].append(pb)
        return params, {}

    def out_shape(self, input_shape):
        return (self.head.features,)

    def apply(self, params, state, x, *, train=False, key=None):
        b = x.shape[0]
        h, _ = self.embed.apply(params["embed"], {}, x)  # (b, H/p, W/p, dim)
        h = h.reshape(b, -1, self.dim)
        cls = jnp.broadcast_to(params["cls"], (b, 1, self.dim))
        h = jnp.concatenate([cls, h], axis=1) + params["pos"]
        for blk, pb in zip(self.blocks, params["blocks"]):
            h, _ = blk.apply(pb, {}, h, train=train)
        h, _ = self.ln.apply(params["ln"], {}, h)
        logits, _ = self.head.apply(params["head"], {}, h[:, 0])
        return logits, state


def vit_tiny(
    image_size: int = 224, patch: int = 16, num_classes: int = 1000
) -> ViT:
    return ViT(
        image_size=image_size, patch=patch, num_classes=num_classes
    )
