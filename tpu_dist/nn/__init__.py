"""`tpu_dist.nn` — minimal functional module system + layer library."""

from tpu_dist.nn.attention import (
    MultiHeadAttention,
    dot_product_attention,
    rope,
    segment_mask,
    sliding_window_mask,
)
from tpu_dist.nn.core import Lambda, Module, Sequential, fanin_uniform
from tpu_dist.nn.layers import (
    AvgPool2D,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Dropout2D,
    Embedding,
    GlobalAvgPool,
    LayerNorm,
    MaxPool2D,
    flatten,
    gelu,
    log_softmax,
    relu,
)
from tpu_dist.nn.losses import accuracy, cross_entropy, nll_loss

__all__ = [
    "AvgPool2D",
    "BatchNorm",
    "Conv2D",
    "Dense",
    "Dropout",
    "Dropout2D",
    "Embedding",
    "GlobalAvgPool",
    "Lambda",
    "LayerNorm",
    "MaxPool2D",
    "Module",
    "MultiHeadAttention",
    "rope",
    "segment_mask",
    "sliding_window_mask",
    "Sequential",
    "accuracy",
    "cross_entropy",
    "dot_product_attention",
    "fanin_uniform",
    "flatten",
    "gelu",
    "log_softmax",
    "nll_loss",
    "relu",
]
