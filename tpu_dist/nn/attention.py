"""Attention — functional core + module wrapper.

Not in the reference (its model is a 2-conv MNIST net, train_dist.py:53-71;
SURVEY.md §2d records sequence models as absent), but first-class here: the
ViT-Tiny extended config (BASELINE.json config 5) and the long-context
sequence-parallel path (`tpu_dist.parallel.ring_attention`) both build on
this exact function, so the single-device and ring-sharded paths are
numerically comparable by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_dist.nn.core import Module
from tpu_dist.nn.layers import Dense


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Softmax attention. Shapes: (..., heads, seq, head_dim).

    ``mask``: optional boolean array broadcastable to
    ``(..., heads, sq, sk)`` — True = attend.  Combined (AND) with the
    causal mask; use it for padding (keys of pad tokens False) or
    segment/block-diagonal masking of packed sequences.  Rows with no
    visible key produce zeros (softmax over an empty set is defined as
    0 here rather than NaN).

    ``causal`` with unequal query/key lengths uses BOTTOM-RIGHT (suffix)
    alignment: the queries are taken to be the last ``sq`` positions of
    the ``sk``-long key sequence (tril offset ``sk - sq``) — the
    decode-style convention flash-attention implementations use.  For any
    other cross-attention alignment, build the mask yourself.

    ``window=w`` restricts attention to the sliding band ``k > q - w``
    (Mistral-style local attention; combine with ``causal`` for the
    autoregressive band).  The flash kernel handles it NATIVELY —
    blocks outside the band are skipped, O(S·w) work — while the dense
    path materializes the band mask.

    With ``TPU_DIST_FLASH=1`` the blockwise Pallas kernel
    (`tpu_dist.ops.flash_attention`) takes over for sequences past its
    block size — no (S, S) materialization; numerics match to fp
    tolerance (differentiable either way)."""
    import os

    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if os.environ.get("TPU_DIST_FLASH", "0") == "1":
        S = q.shape[-2]
        bq = bk = min(256, S)
        eligible = (
            q.shape == k.shape == v.shape  # self-attention lengths only
            and S >= 128
            and S % bq == 0
            and mask is None  # kernel has no arbitrary-mask path
        )
        if eligible:
            from tpu_dist.ops.flash_attention import flash_attention

            interp = jax.default_backend() != "tpu"
            return flash_attention(
                q, k, v, causal=causal, bq=bq, bk=bk, interpret=interp,
                window=window,
            )
        # fall through to the dense path for shapes the kernel can't take
        # (cross-attention, indivisible block sizes, short sequences)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("...hqd,...hkd->...hqk", q * scale, k)
    sq, sk = logits.shape[-2], logits.shape[-1]
    visible = None
    if causal:
        visible = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
    if window is not None:
        # band over ABSOLUTE key positions; queries are the last sq of
        # the sk-long sequence (same alignment convention as causal)
        q_pos = jnp.arange(sq)[:, None] + (sk - sq)
        band = jnp.arange(sk)[None, :] > q_pos - window
        visible = band if visible is None else (visible & band)
    if mask is not None:
        m = jnp.broadcast_to(mask, logits.shape)
        visible = m if visible is None else (visible & m)
    if visible is not None:
        # -1e30 (not -inf) so a fully-masked row softmaxes to a uniform
        # garbage row we then zero explicitly, instead of NaN
        logits = jnp.where(visible, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    if visible is not None:
        weights = jnp.where(
            jnp.any(visible, axis=-1, keepdims=True), weights, 0.0
        )
    return jnp.einsum("...hqk,...hkd->...hqd", weights, v)


def rope(x: jax.Array, positions: jax.Array, *, base: float = 10000.0):
    """Rotary position embedding over ``(..., seq, head_dim)``.

    Rotates each (even, odd-half) feature pair by an angle proportional
    to the token's absolute position, so the q·k inner product depends
    only on RELATIVE distance (tested) — the modern long-context
    positional scheme (no learned table, extrapolates past training
    lengths).  ``positions``: ``(seq,)`` absolute indices (traced values
    fine, e.g. ``index + arange(s)`` during cached decode)."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"rope requires an even head_dim, got {d}")
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs  # (s, half)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


class MultiHeadAttention(Module):
    """Standard MHA block over (batch, seq, dim) inputs.

    ``kv_heads`` enables grouped-query attention (GQA): fewer key/value
    heads than query heads, each shared by ``heads // kv_heads`` query
    heads.  ``sliding_window=w`` restricts attention to the local band
    ``k > q - w`` in BOTH the parallel forward (flash kernel skips
    out-of-band blocks under TPU_DIST_FLASH=1) and cached decode.  The KV cache shrinks by the same factor — the reason GQA is
    the modern long-context inference layout (``kv_heads=1`` is
    multi-query attention).  With ``kv_heads == heads`` (default) the
    layer is exactly the classic fused-QKV MHA, param structure and all.
    """

    def __init__(
        self,
        dim: int,
        heads: int,
        *,
        causal: bool = False,
        kv_heads: int | None = None,
        use_rope: bool = False,
        sliding_window: int | None = None,
    ):
        if dim % heads:
            raise ValueError(f"dim {dim} not divisible by heads {heads}")
        self.dim = dim
        self.heads = heads
        self.head_dim = dim // heads
        self.causal = causal
        self.use_rope = use_rope
        if use_rope and self.head_dim % 2:
            raise ValueError(
                f"rope requires an even head_dim, got {self.head_dim}"
            )
        self.kv_heads = heads if kv_heads is None else kv_heads
        if self.kv_heads < 1 or heads % self.kv_heads:
            raise ValueError(
                f"heads {heads} not divisible by kv_heads {self.kv_heads}"
            )
        if sliding_window is not None and sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1, got {sliding_window}"
            )
        self.sliding_window = sliding_window
        self.group = heads // self.kv_heads
        if self.group == 1:
            self._qkv = Dense(3 * dim)
        else:
            self._q = Dense(dim)
            self._kv = Dense(2 * self.kv_heads * self.head_dim)
        self._out = Dense(dim)

    def init(self, key, input_shape):
        k1, k2, k3 = jax.random.split(key, 3)
        po, _ = self._out.init(k3, input_shape[:-1] + (self.dim,))
        if self.group == 1:
            pq, _ = self._qkv.init(k1, input_shape)
            return {"qkv": pq, "out": po}, {}
        pq, _ = self._q.init(k1, input_shape)
        pkv, _ = self._kv.init(k2, input_shape)
        return {"q": pq, "kv": pkv, "out": po}, {}

    def _project(self, params, x):
        """-> q (b, heads, s, hd), k/v (b, kv_heads, s, hd)."""
        b, s, _ = x.shape
        if self.group == 1:
            qkv, _ = self._qkv.apply(params["qkv"], {}, x)
            qkv = qkv.reshape(b, s, 3, self.heads, self.head_dim)
            q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
            return q, k, v
        q, _ = self._q.apply(params["q"], {}, x)
        q = jnp.moveaxis(q.reshape(b, s, self.heads, self.head_dim), 1, 2)
        kv, _ = self._kv.apply(params["kv"], {}, x)
        kv = kv.reshape(b, s, 2, self.kv_heads, self.head_dim)
        k, v = (jnp.moveaxis(kv[:, :, i], 1, 2) for i in range(2))
        return q, k, v

    def _expand_kv(self, t):
        """Repeat each kv head across its query-head group (XLA folds the
        broadcast into the batched matmul; nothing materializes in HBM)."""
        if self.group == 1:
            return t
        return jnp.repeat(t, self.group, axis=1)

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        """``mask``: optional boolean, either a key-padding mask
        ``(b, s)`` (True = real token; expanded to block attention TO
        pad keys) or a full ``(..., sq, sk)`` attention mask."""
        b, s, _ = x.shape
        q, k, v = self._project(params, x)
        if self.use_rope:
            pos = jnp.arange(s)
            q, k = rope(q, pos), rope(k, pos)
        if mask is not None and mask.ndim == 2:
            mask = mask[:, None, None, :]  # keys masked, all queries
        o = dot_product_attention(
            q, self._expand_kv(k), self._expand_kv(v),
            causal=self.causal, mask=mask, window=self.sliding_window,
        )
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, self.dim)
        y, _ = self._out.apply(params["out"], {}, o)
        return y, state

    def apply_cached(self, params, x, k_cache, v_cache, index):
        """Incremental (KV-cache) forward for autoregressive decode.

        ``x`` holds ``s`` NEW tokens whose global positions start at
        ``index`` (a traced scalar is fine); their keys/values are written
        into the static-shape caches ``(b, kv_heads, cache_len, head_dim)``
        with ``dynamic_update_slice`` and the queries attend over the
        whole cache under a position mask (``pos <= index + q_offset``) —
        static shapes throughout, so one compiled program serves every
        decode step.  Under GQA the cache carries only ``kv_heads`` heads
        (``heads / kv_heads``× less decode HBM traffic).  Returns
        ``(y, k_cache, v_cache)``.

        Only meaningful for causal self-attention (decode IS causal);
        raises otherwise to catch ViT-style misuse.
        """
        if not self.causal:
            raise ValueError("apply_cached requires causal=True attention")
        from jax import lax

        b, s, _ = x.shape
        q, k, v = self._project(params, x)
        if self.use_rope:
            # keys enter the cache already rotated (their rotation is a
            # pure function of their own absolute position)
            pos = index + jnp.arange(s)
            q, k = rope(q, pos), rope(k, pos)
        k_cache = lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), index, axis=2
        )
        v_cache = lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), index, axis=2
        )
        cache_len = k_cache.shape[2]
        scale = self.head_dim**-0.5
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q * scale,
            self._expand_kv(k_cache).astype(q.dtype),
        )
        pos = jnp.arange(cache_len)[None, :]
        qpos = index + jnp.arange(s)[:, None]
        visible = pos <= qpos
        if self.sliding_window is not None:
            # same band as the parallel forward: k > q - window, so
            # windowed decode matches windowed training exactly
            visible = visible & (pos > qpos - self.sliding_window)
        logits = jnp.where(visible, logits, -1e30)
        weights = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum(
            "bhqk,bhkd->bhqd",
            weights,
            self._expand_kv(v_cache).astype(q.dtype),
        )
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, self.dim)
        y, _ = self._out.apply(params["out"], {}, o)
        return y, k_cache, v_cache


def sliding_window_mask(seq: int, window: int) -> jax.Array:
    """Boolean ``(seq, seq)`` mask where query i sees keys
    ``i-window+1 .. i`` (AND it with causal via dot_product_attention's
    ``causal=True``, or use alone for bidirectional local attention:
    |i-j| < window).  The Mistral-style local-attention pattern."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    i = jnp.arange(seq)[:, None]
    j = jnp.arange(seq)[None, :]
    return jnp.abs(i - j) < window


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """Block-diagonal mask for PACKED sequences: ``segment_ids`` is
    ``(b, s)`` ints labeling which document each token belongs to;
    returns ``(b, 1, s, s)`` boolean allowing attention only within the
    same segment.  Combine with ``causal=True`` so packed training
    matches per-document training (tested)."""
    same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
    return same
