"""Attention — functional core + module wrapper.

Not in the reference (its model is a 2-conv MNIST net, train_dist.py:53-71;
SURVEY.md §2d records sequence models as absent), but first-class here: the
ViT-Tiny extended config (BASELINE.json config 5) and the long-context
sequence-parallel path (`tpu_dist.parallel.ring_attention`) both build on
this exact function, so the single-device and ring-sharded paths are
numerically comparable by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_dist.nn.core import Module
from tpu_dist.nn.layers import Dense


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
) -> jax.Array:
    """Softmax attention. Shapes: (..., heads, seq, head_dim).

    ``causal`` with unequal query/key lengths uses BOTTOM-RIGHT (suffix)
    alignment: the queries are taken to be the last ``sq`` positions of
    the ``sk``-long key sequence (tril offset ``sk - sq``) — the
    decode-style convention flash-attention implementations use.  For any
    other cross-attention alignment, build the mask yourself.

    With ``TPU_DIST_FLASH=1`` the blockwise Pallas kernel
    (`tpu_dist.ops.flash_attention`) takes over for sequences past its
    block size — no (S, S) materialization; numerics match to fp
    tolerance (differentiable either way)."""
    import os

    if os.environ.get("TPU_DIST_FLASH", "0") == "1":
        S = q.shape[-2]
        bq = bk = min(256, S)
        eligible = (
            q.shape == k.shape == v.shape  # self-attention lengths only
            and S >= 128
            and S % bq == 0
        )
        if eligible:
            from tpu_dist.ops.flash_attention import flash_attention

            interp = jax.default_backend() != "tpu"
            return flash_attention(
                q, k, v, causal=causal, bq=bq, bk=bk, interpret=interp
            )
        # fall through to the dense path for shapes the kernel can't take
        # (cross-attention, indivisible block sizes, short sequences)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("...hqd,...hkd->...hqk", q * scale, k)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...hqk,...hkd->...hqd", weights, v)


class MultiHeadAttention(Module):
    """Standard MHA block over (batch, seq, dim) inputs."""

    def __init__(self, dim: int, heads: int, *, causal: bool = False):
        if dim % heads:
            raise ValueError(f"dim {dim} not divisible by heads {heads}")
        self.dim = dim
        self.heads = heads
        self.head_dim = dim // heads
        self.causal = causal
        self._qkv = Dense(3 * dim)
        self._out = Dense(dim)

    def init(self, key, input_shape):
        k1, k2 = jax.random.split(key)
        pq, _ = self._qkv.init(k1, input_shape)
        po, _ = self._out.init(k2, input_shape[:-1] + (self.dim,))
        return {"qkv": pq, "out": po}, {}

    def apply(self, params, state, x, *, train=False, key=None):
        b, s, _ = x.shape
        qkv, _ = self._qkv.apply(params["qkv"], {}, x)
        qkv = qkv.reshape(b, s, 3, self.heads, self.head_dim)
        q, k, v = (
            jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3)
        )  # (b, h, s, hd)
        o = dot_product_attention(q, k, v, causal=self.causal)
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, self.dim)
        y, _ = self._out.apply(params["out"], {}, o)
        return y, state

    def apply_cached(self, params, x, k_cache, v_cache, index):
        """Incremental (KV-cache) forward for autoregressive decode.

        ``x`` holds ``s`` NEW tokens whose global positions start at
        ``index`` (a traced scalar is fine); their keys/values are written
        into the static-shape caches ``(b, heads, cache_len, head_dim)``
        with ``dynamic_update_slice`` and the queries attend over the
        whole cache under a position mask (``pos <= index + q_offset``) —
        static shapes throughout, so one compiled program serves every
        decode step.  Returns ``(y, k_cache, v_cache)``.

        Only meaningful for causal self-attention (decode IS causal);
        raises otherwise to catch ViT-style misuse.
        """
        if not self.causal:
            raise ValueError("apply_cached requires causal=True attention")
        from jax import lax

        b, s, _ = x.shape
        qkv, _ = self._qkv.apply(params["qkv"], {}, x)
        qkv = qkv.reshape(b, s, 3, self.heads, self.head_dim)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        k_cache = lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), index, axis=2
        )
        v_cache = lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), index, axis=2
        )
        cache_len = k_cache.shape[2]
        scale = self.head_dim**-0.5
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q * scale, k_cache.astype(q.dtype)
        )
        pos = jnp.arange(cache_len)[None, :]
        qpos = index + jnp.arange(s)[:, None]
        logits = jnp.where(pos <= qpos, logits, -1e30)
        weights = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", weights, v_cache.astype(q.dtype))
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, self.dim)
        y, _ = self._out.apply(params["out"], {}, o)
        return y, k_cache, v_cache
