"""Minimal functional module system.

The reference leans on ``torch.nn`` (external) for its model layer
(SURVEY.md §1 L3, train_dist.py:53-71).  Our equivalent is deliberately
tiny and pure-functional — parameters and mutable statistics are explicit
pytrees, so every model is directly jit/shard_map/grad-compatible and
replication across a mesh is just an `out_sharding`:

- ``Module.init(key, input_shape) -> (params, state)`` — shape-inferred
  analytically from the per-example input shape (no batch dim).
- ``Module.apply(params, state, x, *, train, key) -> (y, new_state)`` —
  ``x`` is batched; ``state`` carries e.g. batch-norm running statistics
  (returned unchanged by stateless layers).

Default initializers mirror torch's ``kaiming_uniform(a=sqrt(5))`` /
fan-in-uniform scheme so that the MNIST ConvNet here trains with the same
dynamics as the reference's ``Net`` under identical hyperparameters.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

Params = Any
State = Any
Shape = tuple[int, ...]


class Module:
    """Base class: stateless unless a subclass overrides."""

    def init(self, key: jax.Array, input_shape: Shape) -> tuple[Params, State]:
        del key, input_shape
        return {}, {}

    def out_shape(self, input_shape: Shape) -> Shape:
        return input_shape

    def apply(
        self,
        params: Params,
        state: State,
        x: jax.Array,
        *,
        train: bool = False,
        key: jax.Array | None = None,
    ) -> tuple[jax.Array, State]:
        raise NotImplementedError

    def __call__(self, params, state, x, *, train=False, key=None):
        return self.apply(params, state, x, train=train, key=key)


def fanin_uniform(key, shape, fan_in, dtype=jnp.float32):
    """torch's default weight/bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    (equivalent to kaiming_uniform with a=sqrt(5) for weights)."""
    bound = 1.0 / jnp.sqrt(jnp.maximum(fan_in, 1.0))
    return jax.random.uniform(key, shape, dtype, -1.0, 1.0) * bound


class Sequential(Module):
    """Composition with state threading and per-layer rng splitting."""

    def __init__(self, layers: Sequence[Module]):
        self.layers = list(layers)

    def init(self, key, input_shape):
        params, state = [], []
        shape = input_shape
        keys = jax.random.split(key, max(len(self.layers), 1))
        for k, layer in zip(keys, self.layers):
            p, s = layer.init(k, shape)
            shape = layer.out_shape(shape)
            params.append(p)
            state.append(s)
        return tuple(params), tuple(state)

    def out_shape(self, input_shape):
        shape = input_shape
        for layer in self.layers:
            shape = layer.out_shape(shape)
        return shape

    def apply(self, params, state, x, *, train=False, key=None):
        # zip would SILENTLY truncate on a mismatched tree (e.g. a bare
        # {} for state applies zero layers and returns x unchanged —
        # a confusing identity forward instead of an error)
        if len(params) != len(self.layers) or len(state) != len(self.layers):
            raise ValueError(
                f"Sequential.apply: {len(self.layers)} layers but "
                f"{len(params)} param entries / {len(state)} state "
                f"entries — pass the trees from init() unchanged"
            )
        keys = (
            jax.random.split(key, max(len(self.layers), 1))
            if key is not None
            else [None] * len(self.layers)
        )
        new_state = []
        for layer, p, s, k in zip(self.layers, params, state, keys):
            x, s2 = layer.apply(p, s, x, train=train, key=k)
            new_state.append(s2)
        return x, tuple(new_state)


class Lambda(Module):
    """Stateless elementwise/structural op (relu, flatten, ...)."""

    def __init__(self, fn: Callable[[jax.Array], jax.Array], shape_fn=None):
        self.fn = fn
        self.shape_fn = shape_fn

    def out_shape(self, input_shape):
        if self.shape_fn is not None:
            return self.shape_fn(input_shape)
        return input_shape

    def apply(self, params, state, x, *, train=False, key=None):
        return self.fn(x), state
