"""Layer library.

Everything the reference's model layer uses (conv, max-pool, dropout /
dropout2d, linear — train_dist.py:53-71) plus what the extended configs
need (batch-norm for ResNet-18, layer-norm / attention / embeddings for
ViT-Tiny and the long-context ring-attention path).

Layouts are TPU-native: images are NHWC (channels-last feeds the MXU's
preferred layouts; the reference's NCHW is a GPU/cuDNN convention),
convolution kernels are HWIO.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.nn.core import Lambda, Module, Params, Shape, State, fanin_uniform


class Dense(Module):
    """Affine layer — ``nn.Linear`` analog (train_dist.py:59-60)."""

    def __init__(self, features: int, *, use_bias: bool = True, dtype=jnp.float32):
        self.features = features
        self.use_bias = use_bias
        self.dtype = dtype

    def init(self, key, input_shape):
        in_f = input_shape[-1]
        kw, kb = jax.random.split(key)
        params = {"w": fanin_uniform(kw, (in_f, self.features), in_f, self.dtype)}
        if self.use_bias:
            params["b"] = fanin_uniform(kb, (self.features,), in_f, self.dtype)
        return params, {}

    def out_shape(self, input_shape):
        return input_shape[:-1] + (self.features,)

    def apply(self, params, state, x, *, train=False, key=None):
        # Optional hand-tuned path: fused pallas matmul (+bias) kernel for
        # 2-D activations (TPU_DIST_PALLAS_DENSE=1); default is XLA's dot,
        # which it tiles onto the MXU itself.
        from tpu_dist.ops.matmul import use_pallas_dense

        if self.use_bias and x.ndim == 2 and use_pallas_dense():
            import jax as _jax

            from tpu_dist.ops.matmul import matmul

            interp = _jax.default_backend() != "tpu"
            return matmul(x, params["w"], params["b"], interpret=interp), state
        y = x @ params["w"]
        if self.use_bias:
            y = y + params["b"]
        return y, state


class Conv2D(Module):
    """2-D convolution, NHWC/HWIO — ``nn.Conv2d`` analog
    (train_dist.py:57-58)."""

    def __init__(
        self,
        features: int,
        kernel: int | tuple[int, int],
        *,
        stride: int | tuple[int, int] = 1,
        padding: str | int = "VALID",
        use_bias: bool = True,
        dtype=jnp.float32,
    ):
        self.features = features
        self.kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        if isinstance(padding, int):
            padding = [(padding, padding), (padding, padding)]
        self.padding = padding
        self.use_bias = use_bias
        self.dtype = dtype

    def init(self, key, input_shape):
        c_in = input_shape[-1]
        fan_in = c_in * self.kernel[0] * self.kernel[1]
        kw, kb = jax.random.split(key)
        params = {
            "w": fanin_uniform(
                kw, self.kernel + (c_in, self.features), fan_in, self.dtype
            )
        }
        if self.use_bias:
            params["b"] = fanin_uniform(kb, (self.features,), fan_in, self.dtype)
        return params, {}

    def _spatial_out(self, hw):
        if isinstance(self.padding, str):
            if self.padding.upper() == "SAME":
                return tuple(
                    -(-d // s) for d, s in zip(hw, self.stride)
                )
            pads = [(0, 0), (0, 0)]
        else:
            pads = self.padding
        return tuple(
            (d + p[0] + p[1] - k) // s + 1
            for d, p, k, s in zip(hw, pads, self.kernel, self.stride)
        )

    def out_shape(self, input_shape):
        h, w = self._spatial_out(input_shape[:2])
        return (h, w, self.features)

    def apply(self, params, state, x, *, train=False, key=None):
        y = lax.conv_general_dilated(
            x,
            params["w"],
            window_strides=self.stride,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["b"]
        return y, state


class MaxPool2D(Module):
    """``F.max_pool2d`` analog (train_dist.py:65-66)."""

    def __init__(self, window: int = 2, stride: int | None = None):
        self.window = window
        self.stride = stride if stride is not None else window

    def out_shape(self, input_shape):
        h, w, c = input_shape
        return ((h - self.window) // self.stride + 1,
                (w - self.window) // self.stride + 1, c)

    def apply(self, params, state, x, *, train=False, key=None):
        y = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            (1, self.window, self.window, 1),
            (1, self.stride, self.stride, 1),
            "VALID",
        )
        return y, state


class AvgPool2D(Module):
    def __init__(self, window: int = 2, stride: int | None = None):
        self.window = window
        self.stride = stride if stride is not None else window

    def out_shape(self, input_shape):
        h, w, c = input_shape
        return ((h - self.window) // self.stride + 1,
                (w - self.window) // self.stride + 1, c)

    def apply(self, params, state, x, *, train=False, key=None):
        y = lax.reduce_window(
            x, 0.0, lax.add,
            (1, self.window, self.window, 1),
            (1, self.stride, self.stride, 1),
            "VALID",
        ) / (self.window * self.window)
        return y, state


class GlobalAvgPool(Module):
    """Mean over spatial dims (ResNet head)."""

    def out_shape(self, input_shape):
        return (input_shape[-1],)

    def apply(self, params, state, x, *, train=False, key=None):
        return x.mean(axis=(1, 2)), state


class Dropout(Module):
    """``F.dropout`` analog (train_dist.py:69): train-only, inverted
    scaling."""

    def __init__(self, rate: float = 0.5):
        self.rate = rate

    def apply(self, params, state, x, *, train=False, key=None):
        if not train or self.rate == 0.0:
            return x, state
        if key is None:
            raise ValueError("Dropout needs an rng key when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), state


class Dropout2D(Module):
    """``nn.Dropout2d`` analog (train_dist.py:58,66): drops whole feature
    maps (channels), NHWC mask shape (N, 1, 1, C)."""

    def __init__(self, rate: float = 0.5):
        self.rate = rate

    def apply(self, params, state, x, *, train=False, key=None):
        if not train or self.rate == 0.0:
            return x, state
        if key is None:
            raise ValueError("Dropout2D needs an rng key when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(
            key, keep, (x.shape[0], 1, 1, x.shape[-1])
        )
        return jnp.where(mask, x / keep, 0.0), state


class BatchNorm(Module):
    """Batch normalization with running statistics carried in ``state``
    (ResNet-18 needs it; the reference's MNIST net does not use BN).

    ``momentum`` is the DECAY of the running average (Flax convention):
    ``running = momentum * running + (1 - momentum) * batch_stat``.
    torch's ``nn.BatchNorm2d(momentum=m)`` corresponds to ``1 - m`` here —
    torch's default 0.1 equals this default of 0.9; do not pass torch's
    value through unchanged."""

    def __init__(self, momentum: float = 0.9, eps: float = 1e-5):
        self.momentum = momentum
        self.eps = eps

    def init(self, key, input_shape):
        c = input_shape[-1]
        params = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
        state = {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}
        return params, state

    def apply(self, params, state, x, *, train=False, key=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            mean = x.mean(axis=reduce_axes)
            var = x.var(axis=reduce_axes)
            new_state = {
                "mean": self.momentum * state["mean"] + (1 - self.momentum) * mean,
                "var": self.momentum * state["var"] + (1 - self.momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        y = (x - mean) * lax.rsqrt(var + self.eps)
        return y * params["scale"] + params["bias"], new_state


class LayerNorm(Module):
    def __init__(self, eps: float = 1e-6):
        self.eps = eps

    def init(self, key, input_shape):
        d = input_shape[-1]
        return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}, {}

    def apply(self, params, state, x, *, train=False, key=None):
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        return y * params["scale"] + params["bias"], state


class Embedding(Module):
    def __init__(self, vocab: int, features: int):
        self.vocab = vocab
        self.features = features

    def init(self, key, input_shape):
        return {
            "table": jax.random.normal(key, (self.vocab, self.features)) * 0.02
        }, {}

    def out_shape(self, input_shape):
        return input_shape + (self.features,)

    def apply(self, params, state, x, *, train=False, key=None):
        return params["table"][x], state


def relu() -> Lambda:
    return Lambda(jax.nn.relu)


def gelu() -> Lambda:
    return Lambda(jax.nn.gelu)


def log_softmax() -> Lambda:
    """``F.log_softmax(x)`` head (train_dist.py:71)."""
    return Lambda(lambda x: jax.nn.log_softmax(x, axis=-1))


def flatten() -> Lambda:
    """``x.view(-1, 320)`` analog (train_dist.py:67)."""
    return Lambda(
        lambda x: x.reshape(x.shape[0], -1),
        shape_fn=lambda s: (int(math.prod(s)),),
    )
