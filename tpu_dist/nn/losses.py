"""Loss functions — ``F.nll_loss`` analog (train_dist.py:120) and friends."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def nll_loss(log_probs: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean negative log likelihood over the batch, given log-probabilities
    (the reference pairs ``log_softmax`` output with ``F.nll_loss``,
    train_dist.py:71,120)."""
    picked = jnp.take_along_axis(log_probs, targets[:, None], axis=-1)[:, 0]
    return -picked.mean()


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Softmax cross-entropy from raw logits (ResNet/ViT heads)."""
    return nll_loss(jax.nn.log_softmax(logits, axis=-1), targets)


def accuracy(scores: jax.Array, targets: jax.Array) -> jax.Array:
    return (scores.argmax(-1) == targets).mean()
