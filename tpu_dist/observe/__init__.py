"""`tpu_dist.observe` — the unified telemetry subsystem.

The reference's observability story is per-rank ``print`` (SURVEY.md §5);
before this package ours was scattered timing helpers (`train.metrics`),
a stderr watchdog (`utils.debug`), and interleaved stdout.  This package
is the measurement substrate the ROADMAP's perf PRs cite:

- `events`    — per-rank structured JSONL event log (manifest + step /
                epoch / checkpoint / retry / chaos / stall records),
                opt-in via ``TPU_DIST_TELEMETRY=<dir>``
- `registry`  — counters / gauges / histograms with a Prometheus
                text-exposition endpoint (``TPU_DIST_METRICS_PORT``)
- `spans`     — host-side span tracing emitted as Chrome-trace JSON,
                correlated with `jax.profiler` device traces by step id
- `heartbeat` — per-rank progress heartbeats, stall attribution
                ("rank N is K seconds behind"), and goodput accounting
- `flightrec` — always-on per-rank ring buffer of step/phase/collective
                records, dumped on watchdog fire / signals / chaos kill /
                crashes; ``python -m tpu_dist.observe.flightrec merge``
                clock-aligns the dumps and names the divergent rank
- `memory`    — live memory snapshots (HBM, host-RSS fallback on
                CPU-sim), phase-bucketed watermark accounting, and OOM
                forensics (`record_oom` → flight dump + ``oom`` event)
- `results`   — the shared loader for the persisted
                ``benchmarks/results/*.jsonl`` records (metric-series /
                platform-provenance filtering) that `regress`, the
                attribution row gates, and `analysis.costmodel` all
                route through
- `regress`   — trailing-median regression checker over the persisted
                bench trajectory (``python -m tpu_dist.observe.regress``;
                a ``-m`` CLI like flightrec's merge — import it
                explicitly, it is not re-exported here)

Everything here is stdlib-only and import-light: these modules are
imported from bootstrap paths (`comm.launch._child`,
`resilience.chaos`) that run before JAX backends initialize.  The one
exception is `observe.attribution` (plan-vs-measured cost attribution —
it EXECUTES compiled programs, so it needs jax); import it explicitly.
"""

from tpu_dist.observe import (
    events,
    flightrec,
    heartbeat,
    memory,
    registry,
    results,
    spans,
)

__all__ = [
    "events", "flightrec", "heartbeat", "memory", "registry",
    "results", "spans",
]
