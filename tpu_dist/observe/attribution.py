"""Plan-vs-measured cost attribution — joining the analyzer and the clock.

The analyzer (`tpu_dist.analysis`) knows every collective a compiled
program SHOULD run — kind, mesh axes, per-participant payload bytes —
and telemetry knows how long each STEP took; neither can say which
collective a slow step spent its time in, or what wire bandwidth the
run actually achieved against the plan.  This module joins the two:

- `attribute_program(program)` takes an `analysis.AnalysisProgram`
  (engine / pipeline / serve — anything with a `CollectivePlan`),
  measures the real step wall time, and measures each (kind, axes,
  dtype) collective CLASS by replaying it on the same mesh with the
  plan's exact per-participant payloads (a `shard_map` microprogram per
  class).  The report buckets step time into compute vs each class and
  computes achieved wire GB/s from the plan's payload bytes — so the
  per-class BYTES in the report are the analyzer's numbers to the byte,
  and the TIMES are measured, never estimated.
- `measure_stage_costs` produces the measured per-pipeline-stage
  forward/backward cost tables (`benchmarks/results/stage_costs.jsonl`)
  that ROADMAP item 4's cost-weighted schedule generator consumes,
  via the `parallel.pipeline.stage_cost_programs` hook.
- `emit_report` publishes a report as the required ``attribution``
  telemetry event plus Prometheus gauges
  (``tpu_dist_attr_step_seconds``, ``tpu_dist_attr_collective_seconds``,
  ``tpu_dist_attr_achieved_gbps``); `tools/tpu_top.py` renders the
  latest event as the `attr` line.

Methodology caveats (documented in docs/observability.md): replay
timing includes one dispatch per class program, and CPU-sim collective
times are memcpy numbers — treat achieved-GB/s as a regression guard
on CPU and a bandwidth number only on real chips.  Unlike the rest of
`tpu_dist.observe` this module NEEDS jax (it executes programs) and is
therefore not imported by ``tpu_dist.observe.__init__``.

``make attribute`` / ``make attribute-smoke`` drive this end to end
(`benchmarks/attribute.py`).
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import asdict, dataclass, field

from tpu_dist.observe import results as results_mod

REPORT_VERSION = 1

# HLO element type -> a jnp dtype the replay collectives can carry.
# ``pred`` rides int8 (same itemsize; psum of bool is not defined).
_REPLAY_DTYPES = {
    "f32": "float32", "f64": "float64", "f16": "float16",
    "bf16": "bfloat16", "s8": "int8", "u8": "uint8", "pred": "int8",
    "s16": "int16", "u16": "uint16", "s32": "int32", "u32": "uint32",
    "s64": "int64", "u64": "uint64",
}
_ITEMSIZE_FALLBACK = {1: "int8", 2: "int16", 4: "int32", 8: "int64"}


def program_fingerprint(payload) -> str:
    """Short stable hash of a program/model spec (canonical-JSON
    sha256, 12 hex chars).  Stamped onto persisted attribution and
    stage-cost rows so calibration consumers (`analysis.costmodel`)
    only fit rows recorded for the SAME program shape — a row measured
    before a model was widened must not calibrate the widened one."""
    text = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:12]


@dataclass
class ClassCost:
    """One (kind, axes, dtype) collective class of a program: the plan's
    payload joined with its measured replay time."""

    kind: str
    axes: list | None
    dtype: str
    count: int
    payload_bytes: int
    max_elems: int
    measured_s: float | None = None
    achieved_gbps: float | None = None
    share: float | None = None  # fraction of the measured step time

    @property
    def label(self) -> str:
        axes = "x".join(self.axes) if self.axes else "?"
        return f"{self.kind}:{axes}:{self.dtype}"


@dataclass
class AttributionReport:
    """Plan-vs-measured attribution for one compiled program."""

    program: str
    mesh_axes: dict = field(default_factory=dict)
    classes: list = field(default_factory=list)
    step_time_s: float | None = None
    collective_s: float | None = None
    compute_s: float | None = None
    iters: int = 0
    golden: str | None = None   # golden-gate status when checked
    # program provenance: spec hash over the plan rows + mesh shape
    # (`program_fingerprint`), so calibration only consumes rows
    # recorded for THIS program shape; flops = XLA cost analysis of the
    # compiled step (the cost model's compute-term input)
    spec_hash: str | None = None
    flops: float | None = None
    version: int = REPORT_VERSION

    def rows(self) -> list[dict]:
        """The plan-comparable rows — same key/fields as
        `analysis.plan.CollectivePlan.rows()`, so a report can be
        checked byte-for-byte against a blessed golden."""
        return [
            {
                "kind": c.kind,
                "axes": list(c.axes) if c.axes is not None else None,
                "dtype": c.dtype,
                "count": c.count,
                "bytes": c.payload_bytes,
                "max_elems": c.max_elems,
            }
            for c in sorted(
                self.classes,
                key=lambda c: (c.kind, c.axes or ["~"], c.dtype),
            )
        ]

    def to_dict(self) -> dict:
        d = asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "AttributionReport":
        classes = [ClassCost(**c) for c in d.get("classes", [])]
        return cls(
            program=d.get("program", ""),
            mesh_axes=d.get("mesh_axes", {}),
            classes=classes,
            step_time_s=d.get("step_time_s"),
            collective_s=d.get("collective_s"),
            compute_s=d.get("compute_s"),
            iters=d.get("iters", 0),
            golden=d.get("golden"),
            spec_hash=d.get("spec_hash"),
            flops=d.get("flops"),
            version=d.get("version", REPORT_VERSION),
        )

    def validate(self) -> list[str]:
        """Structural errors (empty list = a well-formed report)."""
        errors = []
        if not self.program:
            errors.append("report has no program name")
        for c in self.classes:
            if c.count <= 0:
                errors.append(f"{c.label}: non-positive count {c.count}")
            if c.payload_bytes < 0:
                errors.append(f"{c.label}: negative payload bytes")
            if c.measured_s is not None:
                if c.measured_s <= 0:
                    errors.append(
                        f"{c.label}: non-positive measured time "
                        f"{c.measured_s}"
                    )
                if c.payload_bytes > 0 and c.achieved_gbps is None:
                    errors.append(f"{c.label}: measured but no achieved GB/s")
        if self.step_time_s is not None and self.step_time_s <= 0:
            errors.append(f"non-positive step time {self.step_time_s}")
        if self.compute_s is not None and self.compute_s < 0:
            errors.append(f"negative compute time {self.compute_s}")
        return errors

    def summary_lines(self) -> list[str]:
        """Human rendering (the `make attribute` table)."""
        lines = [
            f"attribution: {self.program}  mesh "
            + ",".join(f"{k}={v}" for k, v in self.mesh_axes.items())
        ]
        if self.step_time_s is not None:
            comp = (
                f"  compute {self.compute_s * 1e3:.2f}ms "
                f"({self.compute_s / self.step_time_s:.0%})"
                if self.compute_s is not None else ""
            )
            lines.append(
                f"  step {self.step_time_s * 1e3:.2f}ms"
                f"  collectives {(self.collective_s or 0) * 1e3:.2f}ms"
                + comp
            )
        for c in sorted(
            self.classes, key=lambda c: -(c.measured_s or 0.0)
        ):
            t = (
                f"{c.measured_s * 1e3:8.3f}ms" if c.measured_s is not None
                else "   (unmeasured)"
            )
            g = (
                f"{c.achieved_gbps:8.3f} GB/s"
                if c.achieved_gbps is not None else ""
            )
            share = f" {c.share:5.1%}" if c.share is not None else ""
            lines.append(
                f"  {c.label:<40} x{c.count:<3} "
                f"{c.payload_bytes:>10,} B  {t}{share}  {g}"
            )
        return lines


# ------------------------------------------------------------- measurement


def _block(tree):
    import jax

    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return tree


def _time_fn(fn, args: tuple, *, iters: int, warmup: int) -> float:
    """Mean wall time per call, readback-closed."""
    for _ in range(max(warmup, 1)):
        _block(fn(*args))
    t0 = time.perf_counter()
    for _ in range(max(iters, 1)):
        out = fn(*args)
    _block(out)
    return (time.perf_counter() - t0) / max(iters, 1)


def _replay_dtype(name: str):
    import jax.numpy as jnp

    from tpu_dist.analysis import plan as plan_mod

    key = _REPLAY_DTYPES.get(name)
    if key is None:
        key = _ITEMSIZE_FALLBACK.get(plan_mod.itemsize(name), "int32")
    return jnp.dtype(key)


def _class_replay(ops, axes, mesh, inner: int = 8):
    """One jitted `shard_map` microprogram replaying every op of a
    class: each operand becomes a flat per-participant array of the
    op's exact payload (so bytes moved == the plan's bytes), the
    collective runs over the class's mesh axes, and a scalar reduction
    of every output keeps XLA from dropping any of them.

    The whole pass repeats ``inner`` times inside ONE program (a
    `fori_loop` whose carry perturbs every operand, so the collectives
    are loop-variant and can't be hoisted): per-pass time is the wall
    time over ``inner``, which amortizes the per-dispatch overhead that
    would otherwise swamp small payloads.  Returns ``(fn, args,
    inner)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    names = tuple(axes) if axes else tuple(str(n) for n in mesh.axis_names)
    sizes = dict(zip((str(n) for n in mesh.axis_names),
                     (int(s) for s in mesh.devices.shape)))
    group = 1
    for n in names:
        group *= sizes.get(n, 1)
    axis_arg = names if len(names) > 1 else names[0]

    specs = []  # (kind, operand index) — static replay plan
    arrays = []
    for op in ops:
        for dt, shape in zip(op.dtypes, op.shapes):
            elems = 1
            for d in shape:
                elems *= int(d)
            elems = max(elems, 1)
            if op.kind == "all-to-all" and elems % group:
                elems += group - elems % group  # pad to a splittable length
            dtype = _replay_dtype(dt)
            arrays.append(jnp.zeros((elems,), dtype))
            specs.append((op.kind, len(arrays) - 1))

    def one_pass(xs, carry):
        acc = carry
        for kind, i in specs:
            # carry-dependent perturbation: keeps each iteration's
            # collectives live inside the repeat loop
            x = xs[i] + acc.astype(jnp.float32).astype(xs[i].dtype)
            if kind in ("all-reduce", "reduce-scatter"):
                # one reduce class: XLA lowers a logical reduce-scatter
                # as all-reduce(+slice) on CPU anyway (analysis.plan)
                y = lax.psum(x, axis_arg)
            elif kind == "all-gather":
                y = lax.all_gather(x, axis_arg)
            elif kind == "all-to-all":
                y = lax.all_to_all(
                    x.reshape(group, -1), axis_arg,
                    split_axis=0, concat_axis=0,
                )
            elif kind == "collective-permute":
                k = sizes.get(names[0], 1)
                perm = [(j, (j + 1) % k) for j in range(k)]
                y = lax.ppermute(x, names[0], perm)
            else:
                y = x
            # tiny but NONZERO weight: the sum must stay live (a 0.0
            # weight would let XLA fold it away and drop the collective)
            acc = acc + jnp.sum(y).astype(jnp.float32) * jnp.float32(1e-9)
        return acc

    def body(*xs):
        return lax.fori_loop(
            0, inner, lambda i, acc: one_pass(xs, acc) + jnp.float32(1.0),
            jnp.float32(0.0),
        )

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=tuple(P() for _ in arrays),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(mapped), tuple(arrays), inner


def _concrete_args(args) -> bool:
    import jax

    return all(
        not isinstance(leaf, jax.ShapeDtypeStruct)
        for leaf in jax.tree.leaves(args)
    )


def _time_step(program, *, iters: int, warmup: int) -> float | None:
    """Measured wall time of the REAL program.  Engine train steps
    donate (params, opt_state) — outputs are threaded back as inputs, so
    pass a FRESH program (`analysis.programs.fresh_program`), never the
    shared canonical cache, when measuring a donating step."""
    if not _concrete_args(program.args):
        return None
    if program.built is not None:
        p, o, *rest = program.args
        for _ in range(max(warmup, 1)):
            p, o, loss, _ = program.fn(p, o, *rest)
        _block(loss)
        t0 = time.perf_counter()
        for _ in range(max(iters, 1)):
            p, o, loss, _ = program.fn(p, o, *rest)
        _block(loss)  # the p/o chain serializes the iterations
        return (time.perf_counter() - t0) / max(iters, 1)
    return _time_fn(program.fn, program.args, iters=iters, warmup=warmup)


def attribute_program(
    program,
    *,
    iters: int = 5,
    warmup: int = 2,
    measure_step: bool = True,
) -> AttributionReport:
    """The plan-vs-measured report for one `analysis.AnalysisProgram`.

    Per-class payload bytes come straight from the program's
    `CollectivePlan` (and therefore match the blessed golden when the
    plan does); per-class times come from replaying the class on the
    program's mesh; ``compute_s`` is the measured step time minus the
    summed collective time (clamped at 0 — replay includes dispatch
    overhead the fused program doesn't pay twice).

    ``measure_step=False`` skips executing the real program (use for
    cached/donating programs or ShapeDtypeStruct args); the per-class
    replay measurement still runs whenever the program has a mesh."""
    from tpu_dist.observe import flightrec

    plan = program.plan
    groups: dict[tuple, list] = {}
    for c in plan.collectives:
        groups.setdefault((c.kind, c.axes, c.dtype_key), []).append(c)
    classes = []
    for (kind, axes, dtype), ops in sorted(
        groups.items(), key=lambda kv: (kv[0][0], kv[0][1] or ("~",),
                                        kv[0][2])
    ):
        payload = sum(op.bytes for op in ops)
        max_elems = max(op.max_elems for op in ops)
        measured = gbps = None
        if program.mesh is not None:
            fn, args, inner = _class_replay(ops, axes, program.mesh)
            flightrec.get().record(
                "collective", what=f"replay:{kind}",
                axes=list(axes) if axes else None, dtype=dtype,
            )
            measured = _time_fn(fn, args, iters=iters, warmup=warmup) / inner
            if payload > 0 and measured > 0:
                gbps = payload / measured / 1e9
        classes.append(ClassCost(
            kind=kind,
            axes=list(axes) if axes is not None else None,
            dtype=dtype,
            count=len(ops),
            payload_bytes=payload,
            max_elems=max_elems,
            measured_s=measured,
            achieved_gbps=gbps,
        ))
    step_s = (
        _time_step(program, iters=iters, warmup=warmup)
        if measure_step else None
    )
    flops = None
    try:
        from tpu_dist.train import flops as flops_mod

        flops = flops_mod.xla_flops(program.fn, *program.args)
    except Exception:
        pass
    coll_s = (
        sum(c.measured_s for c in classes if c.measured_s is not None)
        if classes else 0.0
    )
    compute_s = None
    if step_s is not None:
        compute_s = max(step_s - (coll_s or 0.0), 0.0)
        for c in classes:
            if c.measured_s is not None and step_s > 0:
                c.share = min(c.measured_s / step_s, 1.0)
    return AttributionReport(
        program=plan.name or getattr(program, "name", ""),
        mesh_axes=dict(plan.mesh_axes),
        classes=classes,
        step_time_s=step_s,
        collective_s=coll_s if classes else None,
        compute_s=compute_s,
        iters=iters,
        spec_hash=plan_spec_hash(plan),
        flops=flops,
    )


def plan_spec_hash(plan) -> str:
    """The provenance fingerprint of one `CollectivePlan`: program name
    + mesh shape + aggregated collective rows — changes whenever the
    program's wire structure (and therefore its cost profile) does."""
    return program_fingerprint({
        "program": plan.name,
        "mesh_axes": dict(plan.mesh_axes),
        "rows": plan.rows(),
    })


def check_against_golden(report: AttributionReport,
                         goldens_dir: str) -> list[str]:
    """Row-exact comparison of the report's per-class payload bytes /
    counts against the program's blessed golden plan.  Sets
    ``report.golden`` to ``ok`` / ``skew`` (different jax — counts are a
    lowering artifact, compare waived) / ``missing`` / ``diff`` and
    returns the row diffs."""
    from tpu_dist.analysis import plan as plan_mod

    golden = plan_mod.load_golden(goldens_dir, report.program)
    if golden is None:
        report.golden = "missing"
        return [f"no blessed golden for {report.program!r}"]
    if plan_mod.golden_version_skew(golden):
        report.golden = "skew"
        return []

    def key(row):
        axes = row["axes"]
        return (row["kind"], tuple(axes) if axes is not None else None,
                row["dtype"])

    live = {key(r): r for r in report.rows()}
    gold = {key(r): r for r in golden.get("rows", [])}
    diffs = []
    for k in sorted(set(gold) - set(live), key=repr):
        diffs.append(f"class gone vs golden: {k}")
    for k in sorted(set(live) - set(gold), key=repr):
        diffs.append(f"class not in golden: {k}")
    for k in sorted(set(live) & set(gold), key=repr):
        # same fields the analyzer's own golden gate compares
        # (plan.compare_to_golden): count, bytes, AND max_elems
        for f in ("count", "bytes", "max_elems"):
            if gold[k].get(f) is not None and live[k][f] != gold[k][f]:
                diffs.append(
                    f"{k}: {f} {gold[k][f]} (golden) != {live[k][f]} "
                    f"(measured report)"
                )
    report.golden = "ok" if not diffs else "diff"
    return diffs


# ------------------------------------------------------- stage cost tables


def measure_stage_costs(
    stage_fns: list,
    stage_params: list,
    x0,
    *,
    iters: int = 5,
    warmup: int = 2,
    model: str = "pipeline",
) -> list[dict]:
    """Measured per-pipeline-stage forward/backward cost rows — the
    tables ROADMAP item 4's cost-weighted schedule generator consumes.

    ``stage_fns[s]`` is ``(params, x) -> y`` (the LAST stage returns the
    scalar microbatch loss); stages may be heterogeneous — that is the
    point: an embedding-heavy stage 0 and a vocab-head-heavy stage n−1
    produce visibly unbalanced rows.  Uses the
    `parallel.pipeline.stage_cost_programs` hook for the per-stage
    jitted F/B programs, then times each with a readback-closed loop."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.parallel import pipeline as pipe_mod

    progs, inputs, outputs = pipe_mod.stage_cost_programs(
        stage_fns, stage_params, x0
    )
    rows = []
    for s, pr in enumerate(progs):
        p, x, y = stage_params[s], inputs[s], outputs[s]
        fwd_s = _time_fn(pr["fwd"], (p, x), iters=iters, warmup=warmup)
        g = jax.tree.map(jnp.ones_like, y)
        bwd_s = _time_fn(pr["bwd"], (p, x, g), iters=iters, warmup=warmup)
        rows.append({
            "model": model,
            "stage": s,
            "n_stages": len(progs),
            "fwd_s": fwd_s,
            "bwd_s": bwd_s,
            "params_bytes": int(sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(p)
            )),
            "in_shape": list(getattr(x, "shape", ())),
            "out_shape": list(getattr(y, "shape", ())),
        })
    # Program provenance (same discipline as `AttributionReport`): the
    # spec hash covers the whole pipeline's stage structure, so every
    # stage row of one measurement run carries the SAME hash and a
    # calibration consumer can select a complete, self-consistent table.
    spec_hash = program_fingerprint({
        "model": model,
        "stages": [
            {k: r[k] for k in
             ("stage", "n_stages", "params_bytes", "in_shape", "out_shape")}
            for r in rows
        ],
    })
    mesh_shape = {"pipe": len(progs)}
    for r in rows:
        r["spec_hash"] = spec_hash
        r["mesh_shape"] = mesh_shape
    return rows


def persist_stage_costs(rows: list[dict], *, root: str | None = None) -> str:
    """Append measured stage rows to
    ``benchmarks/results/stage_costs.jsonl`` (one JSONL row per stage,
    provenance-stamped via `bench.persist_event`)."""
    import bench

    path = None
    for row in rows:
        path = bench.persist_event(
            {"metric": "stage_cost", **row},
            root=root, out_name="stage_costs.jsonl",
        )
    return path


# ------------------------------------------------------- persisted rows


def load_attribution_rows(
    path: str | None = None,
    *,
    program: str | None = None,
    platform: str | None = None,
    spec_hash: str | None = None,
) -> list[dict]:
    """The persisted ``attribution.jsonl`` rows (`make attribute`), in
    recording order, via the shared `observe.results` loader.  Filters:
    ``program`` name, ``platform`` provenance, and ``spec_hash`` (only
    rows measured for that exact program shape)."""
    path = path or results_mod.results_path("attribution.jsonl")
    rows = results_mod.load_rows(
        path, series="attribution", platform=platform,
        require=("program", "classes"),
    )
    if program is not None:
        rows = [r for r in rows if r.get("program") == program]
    if spec_hash is not None:
        rows = [r for r in rows if r.get("spec_hash") == spec_hash]
    return rows


def load_stage_cost_rows(
    path: str | None = None,
    *,
    model: str | None = None,
    platform: str | None = None,
    spec_hash: str | None = None,
) -> list[dict]:
    """The persisted ``stage_costs.jsonl`` rows (`make attribute`), in
    recording order, via the shared `observe.results` loader — the
    measured F/B cost tables `analysis.costmodel.predict_bubble_fraction`
    and ROADMAP item 4's schedule generator consume."""
    path = path or results_mod.results_path("stage_costs.jsonl")
    rows = results_mod.load_rows(
        path, series="stage_cost", platform=platform,
        require=("model", "stage", "n_stages", "fwd_s", "bwd_s"),
    )
    if model is not None:
        rows = [r for r in rows if r.get("model") == model]
    if spec_hash is not None:
        rows = [r for r in rows if r.get("spec_hash") == spec_hash]
    return rows


# ------------------------------------------------------------- publication


def emit_report(report: AttributionReport, *, events_logger=None,
                registry=None) -> dict | None:
    """Publish a report: the ``attribution`` telemetry event (required
    schema — `observe.events`) plus the Prometheus attribution gauges.
    Returns the emitted record (None when telemetry is off)."""
    from tpu_dist.observe import events as ev_mod
    from tpu_dist.observe import registry as reg_mod

    reg = registry if registry is not None else reg_mod.REGISTRY
    step_g = reg.gauge(
        "tpu_dist_attr_step_seconds",
        "attribution: measured program step wall time",
    )
    compute_g = reg.gauge(
        "tpu_dist_attr_compute_seconds",
        "attribution: step time not attributed to any collective class",
    )
    coll_g = reg.gauge(
        "tpu_dist_attr_collective_seconds",
        "attribution: measured replay time per collective class",
    )
    gbps_g = reg.gauge(
        "tpu_dist_attr_achieved_gbps",
        "attribution: achieved wire GB/s per collective class "
        "(plan payload bytes / measured time)",
    )
    if report.step_time_s is not None:
        step_g.set(report.step_time_s, program=report.program)
    if report.compute_s is not None:
        compute_g.set(report.compute_s, program=report.program)
    for c in report.classes:
        if c.measured_s is not None:
            coll_g.set(c.measured_s, program=report.program, cls=c.label)
        if c.achieved_gbps is not None:
            gbps_g.set(c.achieved_gbps, program=report.program, cls=c.label)
    logger = events_logger if events_logger is not None else ev_mod.from_env()
    return logger.emit(
        "attribution",
        program=report.program,
        step_time=report.step_time_s,
        compute_seconds=report.compute_s,
        collective_seconds=report.collective_s,
        classes=[asdict(c) for c in report.classes],
        mesh_axes=report.mesh_axes,
        golden=report.golden,
        spec_hash=report.spec_hash,
        flops=report.flops,
    )


def save_report(report: AttributionReport, path: str) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report.to_dict(), fh, indent=2, sort_keys=True)
    return path
