"""Per-rank structured JSONL event log.

One line per event, one file per rank, under the directory named by
``TPU_DIST_TELEMETRY`` (unset = telemetry off, every emit is a no-op).
Rank 0 writes ``events.jsonl``; rank r > 0 writes ``events_rank<r>.jsonl``;
the gang supervisor writes ``events_supervisor.jsonl``.  The first record
of a run is a ``manifest`` carrying config / mesh / platform provenance;
after that, step / epoch / checkpoint / retry / chaos / stall / preempt
records carry the numbers an operator (or `tools/tpu_top.py`) needs to
judge a run's health without grepping interleaved prints.

Stdlib-only by design: this module is imported from bootstrap paths
(`comm.launch._child`, `resilience.chaos`, `resilience.retry`) that run
before JAX backends initialize.  `platform_provenance` imports jax
lazily and degrades gracefully when it is absent.

Env knobs:

    TPU_DIST_TELEMETRY        event/heartbeat/span output directory
    TPU_DIST_TELEMETRY_RANK   this process's rank (set by comm.launch;
                              falls back to RANK, then 0)
    TPU_DIST_TELEMETRY_EVERY  emit every Nth step record (default 1)
    TPU_DIST_RUN_ID           shared run id (set by the first logger and
                              inherited by spawned children)
"""

from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
import uuid

ENV_DIR = "TPU_DIST_TELEMETRY"
ENV_RANK = "TPU_DIST_TELEMETRY_RANK"
ENV_EVERY = "TPU_DIST_TELEMETRY_EVERY"
ENV_RUN_ID = "TPU_DIST_RUN_ID"

# Envelope keys present on EVERY record.
ENVELOPE = ("event", "time", "rank", "run_id")

# Per-event required payload keys (the documented schema —
# docs/observability.md).  Values may be null where a backend doesn't
# track them (e.g. mfu/hbm on CPU-sim, bad_steps with the guard off);
# the KEYS must be present so consumers never need hasattr-style probing.
STEP_REQUIRED = (
    "step",
    "epoch",
    "loss",
    "step_time",
    "samples_per_sec_per_chip",
    "mfu",
    "bad_steps",
    "loss_scale",
    "hbm",
    # pipeline-parallel runs: measured schedule-table idle fraction
    # (null when the step is not pipeline-scheduled)
    "bubble_fraction",
)
SCHEMA: dict[str, tuple[str, ...]] = {
    "manifest": ("world", "platform", "mesh", "config"),
    "step": STEP_REQUIRED,
    # "mesh" = partition provenance: {"axes": {name: size}, "rules":
    # <active partition rule-set name or null>} — WHAT sharded the run
    "epoch": (
        "epoch", "mean_loss", "seconds", "goodput", "bubble_fraction",
        "mesh",
    ),
    "checkpoint": ("path", "epoch", "seconds"),
    "retry": ("what", "attempt", "max_attempts", "error"),
    "chaos": ("clause",),
    "stall": ("what", "timeout_s", "ranks_behind"),
    "preempt": ("signal", "epoch", "step"),
    "warning": ("reason",),
    "print": ("text",),
    "spmd_result": ("spmd_rank", "summary"),
    "bench": ("metric", "value"),
    "heartbeat": ("step",),
    "compile_cache": ("outcome",),  # "hit" | "miss" (comm.init cache)
    # compressed gradient sync (comm.compress): per-epoch wire accounting
    "compress": ("wire", "bytes_on_wire", "bytes_saved", "compression_error"),
    # serving request lifecycle (tpu_dist.serve.ServeEngine):
    # admission -> chunked prefill -> sampled decode_step (engine-health
    # snapshot, emitted every decode_event_every steps) -> finish
    "request_admit": (
        "request_id", "prompt_tokens", "max_new_tokens", "queue_depth",
    ),
    "prefill": ("request_id", "chunk", "tokens", "done"),
    "decode_step": (
        "step", "occupancy", "queue_depth", "kv_blocks_used",
        "kv_block_utilization",
    ),
    "request_finish": (
        "request_id", "emitted", "finish_reason", "ttft", "tpot_mean",
    ),
    # static analyzer summary (python -m tpu_dist.analysis / make
    # analyze): programs analyzed, findings per lint rule, golden-plan
    # gate status ("ok" | "stale" | "missing" | "blessed" | null)
    "analysis": ("programs", "findings", "golden"),
    # flight-recorder dumps gathered (observe.flightrec): the comm.launch
    # supervisor on gang failure/relaunch ("gang_failure"), or any local
    # dump trigger that records one; `dir` is where the per-rank
    # flightrec_rank<r>.json files landed, `ranks` which ranks dumped
    "flight_dump": ("reason", "ranks", "dir"),
    # plan-vs-measured cost attribution (observe.attribution / make
    # attribute): measured step time bucketed into compute vs each
    # (kind, axes, dtype) collective class, with plan payload bytes and
    # achieved wire GB/s per class
    "attribution": ("program", "step_time", "compute_seconds", "classes"),
    # live memory accounting (observe.memory.WatermarkSampler): the
    # latest snapshot (source "hbm" on tracked backends, "rss" on the
    # CPU-sim host fallback) plus per-phase watermark-delta buckets
    "memory": (
        "source", "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
        "phases",
    ),
    # elastic resume (train.reshard.redistribute): one record per
    # redistribution — source/target partition provenance, bytes this
    # rank streamed off disk, the transient staging peak the memory
    # bound was asserted on (observe.memory.TransientMeter), wall time,
    # and "ok" | "failed"
    "reshard": (
        "source", "target", "bytes_moved", "peak_bytes", "seconds",
        "status",
    ),
    # OOM forensics (observe.memory.record_oom): RESOURCE_EXHAUSTED on
    # a step path — the failing phase, the headroom at failure, and the
    # largest resident class; the full report rides the flight dump
    "oom": ("phase", "headroom_bytes", "top_class"),
    # static memory-plan gate (python -m tpu_dist.analysis.memory /
    # make memcheck): programs checked + golden gate status
    "memcheck": ("programs", "golden"),
    # auto-sharding advisor (python -m tpu_dist.analysis.advise / make
    # advise): ranked candidate configurations — "best" is the
    # top-ranked {spec, compress, predicted_step_s, ...} summary (null
    # when nothing survived pruning), "ranking" the full ordered list
    "advice": ("model", "chips", "best", "ranking"),
    # cost-model calibration gate (make costcheck): predicted-vs-
    # measured step time per program with attribution rows; status
    # "ok" | "violation" | "skew" (rows from a different jax, gate
    # waived) | "no-rows"
    "costcheck": ("programs", "tolerance", "status"),
}


def _json_default(obj):
    """Last-resort serializer: telemetry must never crash the run over an
    exotic leaf (dtype objects, device arrays, callables).  Non-finite
    numerics (e.g. a numpy NaN scalar) come out as their string names so
    the emitted line stays RFC-8259 parseable under allow_nan=False."""
    try:
        f = float(obj)
    except (TypeError, ValueError):
        return repr(obj)
    return f if math.isfinite(f) else str(f)


def _sanitize_nonfinite(obj):
    """Replace non-finite floats with their string names ('nan', 'inf',
    '-inf'): bare NaN/Infinity tokens are valid only to Python's lenient
    parser, and the log must stay RFC-8259 parseable for jq/scrapers."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, dict):
        return {k: _sanitize_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_nonfinite(v) for v in obj]
    return obj


def _run_id_for(dirpath: str) -> str:
    """One run id per telemetry dir.  The first dir seen in a process
    adopts an inherited ``TPU_DIST_RUN_ID`` (set by the launching
    parent); later, different dirs get fresh ids (a second fit in the
    same process is a new run, not the stale first one).  The current
    id is always (re)published to the environment so children spawned
    during THIS run inherit it."""
    rid = _run_ids.get(dirpath)
    if rid is None:
        inherited = os.environ.get(ENV_RUN_ID)
        rid = inherited if (inherited and not _run_ids) else uuid.uuid4().hex[:12]
        _run_ids[dirpath] = rid
    os.environ[ENV_RUN_ID] = rid
    return rid


class EventLogger:
    """Append-only JSONL writer for one rank.  Thread-safe; every emit
    is flushed so a killed process loses at most the in-flight line."""

    enabled = True

    def __init__(self, dirpath: str, rank: int = 0, *, role: str | None = None):
        self.dir = str(dirpath)
        self.rank = int(rank)
        os.makedirs(self.dir, exist_ok=True)
        self.run_id = _run_id_for(self.dir)
        if role is not None:
            name = f"events_{role}.jsonl"
        elif self.rank == 0:
            name = "events.jsonl"
        else:
            name = f"events_rank{self.rank}.jsonl"
        self.path = os.path.join(self.dir, name)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8")

    def emit(self, event: str, **fields) -> dict | None:
        rec = {
            "event": event,
            "time": time.time(),
            "rank": self.rank,
            "run_id": self.run_id,
            **fields,
        }
        try:
            line = json.dumps(rec, default=_json_default, allow_nan=False)
        except ValueError:  # a non-finite float somewhere in the payload
            rec = _sanitize_nonfinite(rec)
            try:
                line = json.dumps(rec, default=_json_default, allow_nan=False)
            except ValueError:  # never crash the run over a payload
                rec = {k: rec[k] for k in ENVELOPE if k in rec}
                rec["error"] = "unserializable payload"
                line = json.dumps(rec, allow_nan=False)
        with self._lock:
            if self._fh.closed:
                return None
            self._fh.write(line + "\n")
            self._fh.flush()
        return rec

    def manifest(self, *, world: int, config=None, mesh=None,
                 platform=None, **extra) -> dict | None:
        """The run-open record: everything needed to interpret the step
        stream (and to reproduce the run)."""
        return self.emit(
            "manifest",
            world=world,
            config=config_summary(config) if config is not None else {},
            mesh=mesh_summary(mesh) if mesh is not None else {},
            platform=platform if platform is not None else platform_provenance(),
            **extra,
        )

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


class NullLogger:
    """Telemetry-off stand-in: same surface, every call a no-op."""

    enabled = False
    path = None
    rank = 0
    run_id = None

    def emit(self, event: str, **fields):
        return None

    def manifest(self, **kw):
        return None

    def close(self) -> None:
        pass


NULL = NullLogger()
_cache: dict[tuple[str, int | str], EventLogger] = {}
_cache_lock = threading.Lock()
_run_ids: dict[str, str] = {}


def env_rank(rank: int | None = None) -> int:
    """Resolve this process's telemetry rank without importing jax:
    explicit > TPU_DIST_TELEMETRY_RANK (set by `comm.launch`) > RANK > 0."""
    if rank is not None:
        return int(rank)
    for var in (ENV_RANK, "RANK"):
        raw = os.environ.get(var)
        if raw is not None:
            try:
                return int(raw)
            except ValueError:
                pass
    return 0


def from_env(rank: int | None = None, *, role: str | None = None):
    """The process's logger for the ``TPU_DIST_TELEMETRY`` directory, or
    the NULL logger when the env var is unset.  Cached per (dir, rank) so
    every subsystem appends to one file."""
    dirpath = os.environ.get(ENV_DIR)
    if not dirpath:
        return NULL
    return for_dir(dirpath, rank=rank, role=role)


def for_dir(dirpath: str, rank: int | None = None, *,
            role: str | None = None) -> EventLogger:
    """A (cached) logger for an EXPLICIT directory — for callers like
    `utils.collective_watchdog` that accept a telemetry dir parameter
    independent of the environment."""
    r = env_rank(rank)
    key = (str(dirpath), role if role is not None else r)
    with _cache_lock:
        logger = _cache.get(key)
        if logger is None or logger._fh.closed:
            logger = EventLogger(dirpath, r, role=role)
            _cache[key] = logger
        return logger


def step_every() -> int:
    """Step-record sampling stride (``TPU_DIST_TELEMETRY_EVERY``)."""
    try:
        return max(1, int(os.environ.get(ENV_EVERY, "1")))
    except ValueError:
        return 1


# ---------------------------------------------------------------- summaries


def platform_provenance() -> dict:
    """Where this run actually executed — the record that distinguishes a
    TPU number from a CPU-fallback one long after stderr is gone."""
    info: dict = {"hostname": socket.gethostname(), "pid": os.getpid()}
    try:
        import jax

        devs = jax.devices()
        info.update(
            backend=devs[0].platform if devs else None,
            device_kind=getattr(devs[0], "device_kind", "") if devs else "",
            device_count=len(devs),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            jax_version=jax.__version__,
        )
    except Exception as e:  # jax absent or backend init failed
        info["backend"] = None
        info["error"] = f"{type(e).__name__}: {e}"
    return info


def mesh_summary(mesh) -> dict:
    """JSON-able summary of a `jax.sharding.Mesh` (duck-typed so this
    module stays importable without jax)."""
    try:
        return {
            "axis_names": list(mesh.axis_names),
            "shape": {str(k): int(v) for k, v in dict(mesh.shape).items()},
            "devices": int(mesh.devices.size),
        }
    except Exception:
        return {"repr": repr(mesh)}


def config_summary(config) -> dict:
    """Config dataclass/dict → JSON-able dict (callables like ``log``
    dropped; exotic values fall back to repr via the emit serializer)."""
    if config is None:
        return {}
    items = config if isinstance(config, dict) else vars(config)
    return {k: v for k, v in items.items() if not callable(v)}


# --------------------------------------------------------------- validation


def validate_record(rec: dict) -> list[str]:
    """Schema errors for one parsed record (empty list = valid).  Unknown
    event types are fine (the schema is open); known types must carry
    their required keys plus the envelope."""
    errors = []
    if not isinstance(rec, dict):
        return [f"record is not an object: {rec!r}"]
    for key in ENVELOPE:
        if key not in rec:
            errors.append(f"missing envelope key {key!r}")
    required = SCHEMA.get(rec.get("event", ""))
    if required:
        for key in required:
            if key not in rec:
                errors.append(
                    f"{rec.get('event')} record missing key {key!r}"
                )
    return errors


def validate_file(path: str) -> tuple[int, list[str]]:
    """Parse + schema-check one JSONL file.  Returns (record count,
    errors); errors are prefixed with the 1-based line number."""
    count, errors = 0, []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: invalid JSON ({e})")
                continue
            count += 1
            errors.extend(f"line {lineno}: {e}" for e in validate_record(rec))
    return count, errors


def event_files(dirpath: str) -> list[str]:
    """All event files of a telemetry dir (rank 0 first)."""
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return []
    return [
        os.path.join(dirpath, n)
        for n in names
        if n.startswith("events") and n.endswith(".jsonl")
    ]


def validate_dir(dirpath: str) -> tuple[int, list[str]]:
    """Validate every event file under ``dirpath``."""
    total, errors = 0, []
    files = event_files(dirpath)
    if not files:
        return 0, [f"no events*.jsonl files under {dirpath}"]
    for path in files:
        n, errs = validate_file(path)
        total += n
        errors.extend(f"{os.path.basename(path)}: {e}" for e in errs)
    return total, errors


def read_events(dirpath: str) -> list[dict]:
    """Every parseable record from every event file, oldest first."""
    records = []
    for path in event_files(dirpath):
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    records.sort(key=lambda r: r.get("time", 0.0))
    return records
