"""Collective flight recorder — the NCCL-flight-recorder shape, for XLA.

When a gang hangs or a rank dies, the question is always the same: which
rank desynced, at which step, doing what?  Until now the answer was one
watchdog warning line on stderr and nothing durable.  This module keeps a
cheap ALWAYS-ON per-rank ring buffer (a fixed-size `collections.deque` —
no I/O, no locks on the hot path) of the last N step / phase /
collective / heartbeat records, and dumps it to
``flightrec_rank<r>.json`` when something goes wrong:

- `utils.debug.collective_watchdog` fire (the dump path rides the
  ``stall`` event),
- SIGTERM / SIGINT and unhandled exceptions (chained handlers installed
  by `get` when a dump directory is resolvable),
- `resilience.chaos` kill clauses (the injected hard-exit dumps first),
- NaN-guard poison streaks (`train.metrics.TrainTelemetry`),
- trainer preemption (`TrainTelemetry.preempted`).

The `comm.launch` gang supervisor gathers the per-rank dumps into
``<telemetry-dir>/flight/attempt<k>/`` on every gang failure/relaunch
and records a ``flight_dump`` event.  The merge CLI

    python -m tpu_dist.observe.flightrec merge <dir>

clock-aligns the per-rank dumps (matching step records estimate each
rank's wall-clock offset against a reference rank), renders a unified
timeline, and names the divergent rank and the last step the whole gang
completed.  Stdlib-only, like the rest of `tpu_dist.observe` — the CLI
runs on a login host with no JAX installed.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import statistics
import sys
import threading
import time

from tpu_dist.observe import events as _events

ENV_CAPACITY = "TPU_DIST_FLIGHTREC"      # ring size; "0"/"off" disables
ENV_DIR = "TPU_DIST_FLIGHTREC_DIR"       # dump dir when telemetry is off
DEFAULT_CAPACITY = 512

# Record kinds (free-form strings; these are the conventional ones):
#   step        — one training/serve step boundary ({step, phase, ...})
#   phase       — a host phase transition (checkpoint, eval, drain)
#   collective  — a device program / collective the host is waiting on
#   heartbeat   — a heartbeat file write went through
#   mark        — one-shot annotations (fit_start, preempt, chaos_kill)


def dump_path_for(dirpath: str, rank: int) -> str:
    return os.path.join(dirpath, f"flightrec_rank{rank}.json")


class FlightRecorder:
    """Fixed-size in-memory ring of (wall-time, kind, fields) records.

    ``record`` is the hot-path call: one deque append (the GIL makes it
    atomic — no lock), a dict allocation, one ``time.time()``.  All I/O
    happens in `dump`, which is only called when something already went
    wrong."""

    enabled = True

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self.total = 0  # lifetime records (ring overwrites don't decrement)

    def record(self, kind: str, **fields) -> None:
        self.total += 1
        self._buf.append((time.time(), kind, fields))

    def __len__(self) -> int:
        return len(self._buf)

    def snapshot(self) -> list[dict]:
        return [
            {"t": t, "kind": kind, **fields}
            for t, kind, fields in list(self._buf)
        ]

    def resolve_dir(self, dirpath: str | None = None) -> str | None:
        """Where a dump would land: explicit > ``TPU_DIST_TELEMETRY`` >
        ``TPU_DIST_FLIGHTREC_DIR`` > nowhere (None — no unsolicited
        files in the cwd)."""
        return (
            dirpath
            or os.environ.get(_events.ENV_DIR)
            or os.environ.get(ENV_DIR)
            or None
        )

    def dump(self, reason: str = "manual", *,
             dirpath: str | None = None) -> str | None:
        """Write the ring to ``flightrec_rank<r>.json`` (atomic rename;
        newest dump per rank wins — it holds the longest history).
        Returns the path, or None when no dump directory is resolvable.
        Never raises: the dump runs on crash paths."""
        try:
            dirpath = self.resolve_dir(dirpath)
            if dirpath is None:
                return None
            rank = _events.env_rank()
            os.makedirs(dirpath, exist_ok=True)
            path = dump_path_for(dirpath, rank)
            world = None
            try:
                world = int(os.environ.get("WORLD_SIZE", ""))
            except ValueError:
                pass
            doc = {
                "rank": rank,
                "world": world,
                "pid": os.getpid(),
                "run_id": os.environ.get(_events.ENV_RUN_ID),
                "reason": reason,
                "dumped_at": time.time(),
                "capacity": self.capacity,
                "total_records": self.total,
                "records": self.snapshot(),
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, default=_events._json_default)
            os.replace(tmp, path)
            return path
        except Exception:
            return None


class NullFlightRecorder:
    """``TPU_DIST_FLIGHTREC=off`` stand-in: same surface, zero cost."""

    enabled = False
    capacity = 0
    total = 0

    def record(self, kind: str, **fields) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def snapshot(self) -> list:
        return []

    def resolve_dir(self, dirpath=None):
        return None

    def dump(self, reason: str = "manual", *, dirpath=None):
        return None


NULL = NullFlightRecorder()
_recorder = None
_lock = threading.Lock()
_crash_callbacks: list = []
_excepthook_installed = False
_signals_installed = False


def _capacity_from_env() -> int:
    raw = (os.environ.get(ENV_CAPACITY) or "").strip().lower()
    if raw in ("0", "off", "false"):
        return 0
    try:
        return max(int(raw), 1)
    except ValueError:
        return DEFAULT_CAPACITY


def get():
    """The process's flight recorder (created on first use; ring always
    on unless ``TPU_DIST_FLIGHTREC`` disables it).  Creation installs
    the crash hooks when a dump directory is resolvable — with nowhere
    to dump, the process's signal/excepthook state is left alone.

    Steady state is LOCK-FREE (double-checked read of the singleton):
    `crash_dump` runs inside signal handlers, and a handler landing
    while this thread already held a non-reentrant lock would deadlock
    the dying process instead of dumping."""
    rec = _recorder
    if rec is None:
        with _lock:
            rec = _recorder
            if rec is None:
                cap = _capacity_from_env()
                rec = FlightRecorder(cap) if cap else NULL
                _set_recorder(rec)
    if rec.enabled and rec.resolve_dir() is not None:
        install_hooks()
    return rec


def _set_recorder(rec) -> None:
    global _recorder
    _recorder = rec


def _reset_for_tests() -> None:
    """Drop the singleton so the next `get` re-reads the environment
    (crash hooks, once installed, stay installed — they chain)."""
    global _recorder
    with _lock:
        _recorder = None


def register_crash_callback(fn) -> None:
    """Run ``fn()`` on every crash dump (watchdog / signal / exception /
    chaos kill) — `observe.spans` registers its trace flush here so
    Chrome traces survive crashes too.  Callbacks must not raise (they
    are wrapped anyway)."""
    if fn not in _crash_callbacks:
        _crash_callbacks.append(fn)


def crash_dump(reason: str, *, dirpath: str | None = None) -> str | None:
    """Dump the ring AND run the registered crash callbacks (span trace
    flush, ...).  The one entry point every dump trigger calls."""
    path = get().dump(reason, dirpath=dirpath)
    for cb in list(_crash_callbacks):
        try:
            cb()
        except Exception:
            pass
    return path


def install_hooks() -> None:
    """Chain the unhandled-exception hook and SIGTERM/SIGINT handlers to
    `crash_dump` (previous behavior preserved — handlers are chained,
    never replaced outright).  Idempotent PER PART: signal handlers can
    only install from the main thread, so a first call from a worker
    thread (a watchdog, a server thread) must not latch them out — the
    signal half retries on the next main-thread call."""
    global _excepthook_installed, _signals_installed
    if not _excepthook_installed:
        _excepthook_installed = True
        prev_hook = sys.excepthook

        def _excepthook(tp, val, tb):
            crash_dump("exception")
            prev_hook(tp, val, tb)

        sys.excepthook = _excepthook

    if (_signals_installed
            or threading.current_thread() is not threading.main_thread()):
        return
    _signals_installed = True
    for signum, name in ((signal.SIGTERM, "sigterm"),
                         (signal.SIGINT, "sigint")):
        try:
            prev = signal.getsignal(signum)

            def _handler(sig, frame, prev=prev, name=name):
                crash_dump(name)
                if callable(prev):
                    prev(sig, frame)
                else:
                    # SIG_DFL / SIG_IGN: restore and re-deliver so the
                    # process dies the way it would have without us.
                    signal.signal(sig, prev if prev is not None
                                  else signal.SIG_DFL)
                    os.kill(os.getpid(), sig)

            signal.signal(signum, _handler)
        except (ValueError, OSError):
            pass  # non-main thread race / exotic platform


# -------------------------------------------------- dump discovery / merge


def _dump_files_in(dirpath: str) -> list[str]:
    try:
        return [
            os.path.join(dirpath, n)
            for n in sorted(os.listdir(dirpath))
            if n.startswith("flightrec_rank") and n.endswith(".json")
        ]
    except OSError:
        return []


def scan_dump_scopes(dirpath: str) -> list[tuple[str, list[str]]]:
    """Flight dumps under ``dirpath``, grouped by INCARNATION: the dir
    root (the current/ungathered attempt) plus each of the supervisor's
    ``flight/attempt<k>/`` gather dirs, newest scope first.  Dumps from
    different attempts must never be compared against each other — a
    relaunch's step counters restart, so mixing scopes would blame the
    wrong rank."""
    scopes: list[tuple[str, list[str]]] = []
    root = _dump_files_in(dirpath)
    if root:
        scopes.append(("root", root))
    flight = os.path.join(dirpath, "flight")
    attempts = []
    try:
        for name in os.listdir(flight):
            if name.startswith("attempt"):
                try:
                    attempts.append((int(name[len("attempt"):]), name))
                except ValueError:
                    continue
    except OSError:
        pass
    for _, name in sorted(attempts, reverse=True):
        files = _dump_files_in(os.path.join(flight, name))
        if files:
            scopes.append((name, files))
    return scopes


def scan_dumps(dirpath: str) -> list[str]:
    """Every flight dump under ``dirpath`` across all scopes (root plus
    gathered attempts).  For divergence analysis use `merge`, which
    restricts itself to the NEWEST scope."""
    return [p for _, files in scan_dump_scopes(dirpath) for p in files]


def load_dump(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or "records" not in doc:
        return None
    doc["path"] = path
    return doc


def _newest_per_rank(dumps: list[dict]) -> dict[int, dict]:
    by_rank: dict[int, dict] = {}
    for d in dumps:
        r = int(d.get("rank", 0))
        cur = by_rank.get(r)
        if cur is None or d.get("dumped_at", 0) > cur.get("dumped_at", 0):
            by_rank[r] = d
    return by_rank


def _step_times(dump: dict) -> dict:
    """(step, phase) -> wall time, for clock alignment."""
    out = {}
    for rec in dump.get("records", []):
        if rec.get("kind") == "step" and rec.get("step") is not None:
            out[(rec["step"], rec.get("phase"))] = rec["t"]
    return out


def clock_offsets(by_rank: dict[int, dict]) -> dict[int, float]:
    """Per-rank wall-clock offset onto the reference rank (the lowest
    rank with step records): the median difference of same-(step, phase)
    record times.  Ranks with no overlap get offset 0 — on one host the
    wall clocks already agree; across hosts this is the skew estimate."""
    ranks = sorted(by_rank)
    ref = next(
        (r for r in ranks if _step_times(by_rank[r])), ranks[0] if ranks else 0
    )
    ref_times = _step_times(by_rank.get(ref, {}))
    offsets = {}
    for r in ranks:
        if r == ref:
            offsets[r] = 0.0
            continue
        times = _step_times(by_rank[r])
        deltas = [
            ref_times[k] - times[k] for k in times if k in ref_times
        ]
        offsets[r] = statistics.median(deltas) if deltas else 0.0
    return offsets


def _last_completed_step(dump: dict) -> int | None:
    """The last step this rank finished: the max ``step`` record with
    phase ``readback`` (a dispatched-but-unread step does not count)."""
    best = None
    for rec in dump.get("records", []):
        if (rec.get("kind") == "step" and rec.get("phase") == "readback"
                and rec.get("step") is not None):
            s = int(rec["step"])
            best = s if best is None else max(best, s)
    return best


def merge(dirpath: str, *, limit: int = 40) -> dict:
    """Clock-align every rank's newest dump of the NEWEST incarnation
    under ``dirpath`` (the root scope when ungathered dumps exist, else
    the highest ``flight/attempt<k>/`` — attempts restart their step
    counters, so cross-attempt comparison would blame the wrong rank)
    and reduce them to the incident story: per-rank last-completed
    steps, the divergent rank(s), missing ranks, a unified timeline.

    Returns a JSON-able dict; `describe` renders it for humans."""
    scopes = scan_dump_scopes(dirpath)
    scope, paths = scopes[0] if scopes else (None, [])
    dumps = [d for d in (load_dump(p) for p in paths) if d is not None]
    by_rank = _newest_per_rank(dumps)
    if not by_rank:
        return {"dir": dirpath, "scope": scope, "n_dumps": 0, "ranks": {},
                "divergent": [], "missing": [], "last_common_step": None,
                "last_gang_step": None, "timeline": []}
    offsets = clock_offsets(by_rank)
    ranks: dict[int, dict] = {}
    timeline = []
    t_min = None
    for r, d in sorted(by_rank.items()):
        off = offsets.get(r, 0.0)
        recs = d.get("records", [])
        last = recs[-1] if recs else None
        last_step = _last_completed_step(d)
        ranks[r] = {
            "path": d.get("path"),
            "reason": d.get("reason"),
            "run_id": d.get("run_id"),
            "n_records": len(recs),
            "last_completed_step": last_step,
            "last_record": last,
            "clock_offset_s": round(off, 6),
        }
        for rec in recs:
            t = rec.get("t", 0.0) + off
            t_min = t if t_min is None else min(t_min, t)
            timeline.append((t, r, rec))
    timeline.sort(key=lambda e: e[0])
    steps = [v["last_completed_step"] for v in ranks.values()]
    known = [s for s in steps if s is not None]
    last_gang = max(known) if known else None
    last_common = min(known) if known and len(known) == len(steps) else None
    # Divergent = behind the furthest rank (or recorded nothing while
    # others progressed), most-behind first.
    divergent = []
    if last_gang is not None:
        for r, v in ranks.items():
            s = v["last_completed_step"]
            if s is None or s < last_gang:
                divergent.append({
                    "rank": r,
                    "last_completed_step": s,
                    "behind_steps": (last_gang - s) if s is not None else None,
                    "reason": v["reason"],
                })
        divergent.sort(
            key=lambda e: (e["behind_steps"] is None,
                           -(e["behind_steps"] or 0), e["rank"])
        )
    worlds = [d.get("world") for d in by_rank.values() if d.get("world")]
    missing = []
    if worlds:
        missing = [r for r in range(max(worlds)) if r not in ranks]
    return {
        "dir": dirpath,
        "scope": scope,
        "n_dumps": len(dumps),
        "ranks": ranks,
        "divergent": divergent,
        "missing": missing,
        "last_common_step": last_common,
        "last_gang_step": last_gang,
        "timeline": [
            {
                "t_rel": round(t - (t_min or 0.0), 6), "rank": r,
                **{k: v for k, v in rec.items() if k != "t"},
            }
            for t, r, rec in (timeline[-limit:] if limit > 0 else [])
        ],
    }


def describe(result: dict, *, timeline: int = 20) -> str:
    """The operator-facing rendering of a `merge` result."""
    lines = []
    if not result["ranks"]:
        return f"no flight-recorder dumps under {result['dir']}"
    scope = result.get("scope")
    lines.append(
        f"flight merge: {result['n_dumps']} dump(s), "
        f"{len(result['ranks'])} rank(s) under {result['dir']}"
        + (f" (scope {scope})" if scope and scope != "root" else "")
    )
    for r in sorted(result["ranks"]):
        v = result["ranks"][r]
        last = v["last_record"] or {}
        what = last.get("kind", "--")
        if last.get("step") is not None:
            what += f" step={last['step']}"
        if last.get("phase"):
            what += f" phase={last['phase']}"
        lines.append(
            f"  rank {r}: {v['n_records']} records, last completed step "
            f"{v['last_completed_step']}, last record [{what}], "
            f"dump reason {v['reason']!r}"
        )
    for r in result["missing"]:
        lines.append(f"  rank {r}: NO DUMP (dead before recording, or "
                     f"never launched)")
    if result["last_gang_step"] is not None:
        lines.append(
            f"last step completed by the furthest rank: "
            f"{result['last_gang_step']}"
            + (f"; by every dumped rank: {result['last_common_step']}"
               if result["last_common_step"] is not None else "")
        )
    if result["divergent"]:
        e = result["divergent"][0]
        where = (
            f"last completed step {e['last_completed_step']}"
            if e["last_completed_step"] is not None
            else "no completed step on record"
        )
        lines.append(
            f"DIVERGENT rank {e['rank']}: {where} "
            f"(gang reached {result['last_gang_step']})"
        )
        for e in result["divergent"][1:]:
            lines.append(
                f"  also behind: rank {e['rank']} "
                f"(last completed step {e['last_completed_step']})"
            )
    elif result["missing"]:
        lines.append(
            f"DIVERGENT rank {result['missing'][0]}: no dump at all"
        )
    else:
        lines.append("no divergence: every rank reached the same step")
    tail = result["timeline"][-timeline:]
    if tail:
        lines.append(f"timeline (last {len(tail)} records, clock-aligned):")
        for rec in tail:
            extra = {
                k: v for k, v in rec.items()
                if k not in ("t_rel", "rank", "kind")
            }
            body = "  ".join(f"{k}={v}" for k, v in extra.items())
            lines.append(
                f"  +{rec['t_rel']:9.3f}s rank {rec['rank']} "
                f"{rec['kind']:<10} {body[:100]}"
            )
    return "\n".join(lines)


# -------------------------------------------------- supervisor gather


def gather_dumps(dirpath: str, attempt: int = 0) -> tuple[list[int], str | None]:
    """Move the per-rank dumps at ``dirpath``'s root into
    ``flight/attempt<k>/`` — the `comm.launch` supervisor calls this on
    every gang failure so a relaunch's fresh dumps can't overwrite the
    forensic state of the attempt that died.  Returns (ranks moved,
    destination dir or None when there was nothing to gather)."""
    ranks = []
    dest = os.path.join(dirpath, "flight", f"attempt{attempt}")
    for path in _dump_files_in(dirpath):
        doc = load_dump(path)
        if doc is None:
            continue
        try:
            os.makedirs(dest, exist_ok=True)
            os.replace(path, os.path.join(dest, os.path.basename(path)))
            ranks.append(int(doc.get("rank", 0)))
        except OSError:
            continue
    return sorted(ranks), (dest if ranks else None)


# ---------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.observe.flightrec",
        description="merge + analyze per-rank flight-recorder dumps",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="clock-align dumps, name the "
                        "divergent rank and last completed step")
    mp.add_argument("dir", help="telemetry dir (or a flight/attemptN dir)")
    mp.add_argument("--json", action="store_true",
                    help="machine-readable merge result")
    mp.add_argument("--limit", type=int, default=40,
                    help="timeline records to keep")
    args = ap.parse_args(argv)

    result = merge(args.dir, limit=args.limit)
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(describe(result))
    # Span traces alongside the dumps merge into one perfetto file with
    # per-rank process lanes (observe.spans.merge_traces).
    try:
        from tpu_dist.observe import spans as spans_mod

        trace_paths = [
            os.path.join(args.dir, n)
            for n in sorted(os.listdir(args.dir))
            if n.startswith("spans_rank") and n.endswith(".trace.json")
        ]
        if trace_paths:
            out = os.path.join(args.dir, "spans_merged.trace.json")
            spans_mod.merge_traces(trace_paths, out_path=out)
            print(f"merged {len(trace_paths)} span trace(s) -> {out}",
                  file=sys.stderr)
    except Exception:
        pass
    return 0 if result["ranks"] else 1


if __name__ == "__main__":
    sys.exit(main())
