"""Per-rank heartbeats, stall attribution, and goodput accounting.

A heartbeat here means "this rank made PROGRESS", not "this process is
alive": `HeartbeatWriter.beat` is called from the training loop (one
beat per step), so a rank stuck in a collective stops beating and its
file goes stale.  Rank 0 (or `utils.debug.collective_watchdog`, or
`tools/tpu_top.py`) aggregates the files with `read` /
`attribute_stall`, upgrading "something stalled" to "rank N is K
seconds behind (step S, phase P)".

Files are ``heartbeat_rank<r>.json`` under the ``TPU_DIST_TELEMETRY``
dir, written atomically (tmp + rename) so readers never see a torn
record.  Stdlib-only.

`GoodputMeter` is the other half of the accounting: wall-clock time
bucketed into productive / compile / checkpoint / restart / other, and
``goodput`` = productive / total — the number that says how much of the
run the hardware spent training (vs. recovering, compiling, writing).
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import time

from tpu_dist.observe import events as _events

_FILE_RE = re.compile(r"^heartbeat_rank(\d+)\.json$")


class HeartbeatWriter:
    """Writes this rank's progress record.  ``beat`` is rate-limited to
    one write per ``min_interval_s`` unless the step or phase changed —
    cheap enough to call every training step."""

    def __init__(self, dirpath: str, rank: int = 0, *,
                 min_interval_s: float = 0.25):
        self.dir = str(dirpath)
        self.rank = int(rank)
        self.min_interval_s = float(min_interval_s)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, f"heartbeat_rank{self.rank}.json")
        # Stamped into every beat so a reused telemetry dir can't blame
        # phantom ranks from a previous run's stale files.
        self.run_id = _events._run_id_for(self.dir)
        self._last_write = 0.0
        self._last_state: tuple = ()
        self.beat(step=None, phase="start")

    def beat(self, step: int | None = None, phase: str | None = None) -> None:
        now = time.time()
        state = (step, phase)
        if (
            now - self._last_write < self.min_interval_s
            and state == self._last_state
        ):
            return
        rec = {
            "rank": self.rank,
            "time": now,
            "step": step,
            "phase": phase,
            "pid": os.getpid(),
            "run_id": self.run_id,
        }
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(rec, fh)
            os.replace(tmp, self.path)
        except OSError:
            return  # a full disk must not kill the training loop
        self._last_write = now
        self._last_state = state
        # Every beat that reached disk also lands in the flight ring, so
        # a post-mortem dump shows the progress cadence alongside the
        # step/phase records (one deque append — no extra I/O).
        from tpu_dist.observe import flightrec as _flightrec

        _flightrec.get().record("heartbeat", step=step, phase=phase)

    def close(self, phase: str = "done") -> None:
        step = self._last_state[0] if self._last_state else None
        self._last_write = 0.0  # force the final write through
        self._last_state = ()
        self.beat(step=step, phase=phase)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def from_env(rank: int | None = None) -> HeartbeatWriter | None:
    """A writer under ``TPU_DIST_TELEMETRY`` for this process's rank, or
    None when telemetry is off.  NOT cached: each fit() owns its writer
    lifecycle (start marker through done marker)."""
    dirpath = os.environ.get(_events.ENV_DIR)
    if not dirpath:
        return None
    return HeartbeatWriter(dirpath, _events.env_rank(rank))


def read(dirpath: str, run_id: str | None = None) -> dict[int, dict]:
    """All ranks' latest heartbeat records, keyed by rank.  With
    ``run_id``, records stamped with a DIFFERENT id are dropped (stale
    files from a previous run sharing the telemetry dir); unstamped
    records are kept (hand-written/legacy)."""
    beats: dict[int, dict] = {}
    try:
        names = os.listdir(dirpath)
    except OSError:
        return beats
    for name in names:
        m = _FILE_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(dirpath, name), encoding="utf-8") as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if run_id and rec.get("run_id") and rec["run_id"] != run_id:
            continue
        beats[int(m.group(1))] = rec
    return beats


def attribute_stall(
    dirpath: str,
    *,
    stale_after_s: float,
    expected_world: int | None = None,
    now: float | None = None,
    run_id: str | None = None,
) -> list[dict]:
    """Which ranks are behind, and by how much.

    A rank is BEHIND when its last progress beat is older than
    ``stale_after_s`` (it stopped advancing) while at least one rank is
    fresh — if every rank is stale the hang is global (all are
    reported, so the caller still learns it's not single-rank).  A rank
    closed as ``done`` is never behind; one closed as ``crashed`` (a fit
    that raised) stays attributable.  With
    ``expected_world``, ranks that never wrote a heartbeat are reported
    too (``missing: true`` — they died or never reached init).  Result
    is sorted most-behind-first; each entry carries rank / behind_s /
    step / phase for the "rank N is K seconds behind" message.

    ``run_id`` scopes the attribution to one run's heartbeats (default:
    this process's current run id, so stale files from a previous run
    in a reused dir are never blamed).
    """
    now = time.time() if now is None else now
    if run_id is None:
        run_id = os.environ.get(_events.ENV_RUN_ID)
    beats = read(dirpath, run_id=run_id)
    behind = []
    for rank, rec in beats.items():
        lag = now - float(rec.get("time", 0.0))
        if lag > stale_after_s and rec.get("phase") != "done":
            behind.append(
                {
                    "rank": rank,
                    "behind_s": round(lag, 3),
                    "step": rec.get("step"),
                    "phase": rec.get("phase"),
                    "missing": False,
                }
            )
    if expected_world is not None:
        for rank in range(expected_world):
            if rank not in beats:
                behind.append(
                    {
                        "rank": rank,
                        "behind_s": None,
                        "step": None,
                        "phase": None,
                        "missing": True,
                    }
                )
    behind.sort(
        key=lambda e: (not e["missing"], -(e["behind_s"] or 0.0), e["rank"])
    )
    return behind


def describe_stall(behind: list[dict]) -> str:
    """The operator-facing one-liner for an `attribute_stall` result."""
    if not behind:
        return "no per-rank heartbeat attribution available"
    parts = []
    for e in behind:
        if e["missing"]:
            parts.append(f"rank {e['rank']} has no heartbeat (dead or never initialized)")
        else:
            where = f"step {e['step']}" if e["step"] is not None else f"phase {e['phase']}"
            parts.append(f"rank {e['rank']} is {e['behind_s']:.1f}s behind ({where})")
    return "; ".join(parts)


class Measured:
    """Yielded by `GoodputMeter.measure`; ``seconds`` is set on exit."""

    seconds: float = 0.0


class GoodputMeter:
    """Wall-clock accounting: productive vs. everything else.

    Categories are free-form strings; the conventional ones are
    ``productive`` (timed train steps), ``compile`` (first-step tracing/
    compilation), ``checkpoint``, ``restart``, ``eval``.  ``goodput`` =
    productive / total accounted time.

    Phases are a second, overlapping axis: under step pipelining the
    productive interval of step N contains a host ``dispatch`` slice and
    (K steps later) a ``readback`` slice.  `account_phase` tracks those
    WITHOUT entering the category total — they decompose productive
    time, they don't compete with it — and `summary` reports them under
    ``phases`` so an operator can see how much of the loop the host
    spent dispatching vs blocked on results."""

    PRODUCTIVE = "productive"

    def __init__(self):
        self.seconds: dict[str, float] = {}
        self.phase_seconds: dict[str, float] = {}
        self.bubble_fraction: float | None = None

    def set_bubble_fraction(self, fraction: float | None) -> None:
        """Attach the pipeline schedule's measured idle fraction — a
        THIRD axis like phases: the bubble decomposes productive time
        (devices idle inside a scheduled step), it does not compete with
        the category total.  None = not a pipeline run."""
        self.bubble_fraction = (
            None if fraction is None else float(fraction)
        )

    def account(self, category: str, seconds: float) -> None:
        self.seconds[category] = self.seconds.get(category, 0.0) + float(seconds)

    def account_phase(self, phase: str, seconds: float) -> None:
        """Host-phase accounting (``dispatch`` / ``readback``): kept OUT
        of the category total — phases overlap the productive intervals
        they decompose, so adding them would double-count wall time."""
        self.phase_seconds[phase] = (
            self.phase_seconds.get(phase, 0.0) + float(seconds)
        )

    @contextlib.contextmanager
    def measure(self, category: str):
        m = Measured()
        t0 = time.perf_counter()
        try:
            yield m
        finally:
            m.seconds = time.perf_counter() - t0
            self.account(category, m.seconds)

    def total(self) -> float:
        return sum(self.seconds.values())

    def goodput(self) -> float | None:
        total = self.total()
        if total <= 0:
            return None
        return self.seconds.get(self.PRODUCTIVE, 0.0) / total

    def summary(self) -> dict:
        g = self.goodput()
        out = {
            "seconds": {k: round(v, 4) for k, v in sorted(self.seconds.items())},
            "phases": {
                k: round(v, 4) for k, v in sorted(self.phase_seconds.items())
            },
            "total_s": round(self.total(), 4),
            "goodput": round(g, 4) if g is not None else None,
        }
        if self.bubble_fraction is not None:
            out["bubble_fraction"] = round(self.bubble_fraction, 6)
        return out
