"""Live memory accounting + OOM forensics — the memory twin of spans.

The analyzer's `analysis.memory` knows what a compiled program SHOULD
keep resident (the static `MemoryPlan`); this module is the live half:

- `memory_snapshot()` — one ``{source, bytes_in_use, peak_bytes_in_use,
  bytes_limit}`` reading.  On backends that track HBM
  (``device.memory_stats``) the source is ``"hbm"``; on CPU-sim — where
  `train.metrics.device_memory_stats` has returned None since PR 3 and
  the step event's ``hbm`` field has been null in every CI run — it
  falls back to host RSS (``/proc/self/statm`` + ``getrusage``),
  labeled ``source: "rss"`` so a dashboard can never mistake a host
  number for a chip number.  The telemetry is therefore EXERCISED (and
  testable) on the CPU mesh.
- `WatermarkSampler` — per-rank phase-bucketed peak accounting: each
  `sample(phase)` reads the watermark and attributes the delta since
  the previous sample to that phase (``data`` / ``dispatch`` /
  ``readback`` / ``checkpoint`` / ``prefill`` / ``decode`` — the
  existing span-phase vocabulary).  Publishes the
  ``tpu_dist_hbm_{in_use,peak,limit}_bytes`` gauges, appends a
  ``memory`` record to the flight ring whenever the watermark moves
  (so a post-mortem merge shows the memory trajectory per rank), and
  emits the required ``memory`` telemetry event via `emit`.
- OOM forensics — `is_resource_exhausted(exc)` recognizes XLA's
  ``RESOURCE_EXHAUSTED`` on any step path; `record_oom` builds the
  plan-vs-live report (the failing PHASE, the HEADROOM at failure, the
  top RESIDENT classes — params/opt/EF/KV/temp) and routes it through
  `flightrec.crash_dump("oom")`, so the `comm.launch` supervisor
  gathers it like any flight dump and the merge CLI renders it.

Like the rest of `tpu_dist.observe` this module is stdlib-only at
import time (jax is probed lazily inside `memory_snapshot`), so it is
importable from bootstrap paths and usable on a login host.
"""

from __future__ import annotations

import os
import re as _re
import time

from tpu_dist.observe import events as _events
from tpu_dist.observe import flightrec as _flightrec

# The phase vocabulary the sampler buckets watermark deltas into — the
# union of the trainer span phases, the serve engine's step halves, and
# the elastic-resume redistribution (`train.reshard`).
PHASES = (
    "data", "dispatch", "readback", "checkpoint", "prefill", "decode",
    "reshard",
)

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def host_rss_bytes() -> int | None:
    """Current resident-set size of this process (bytes).  Linux
    ``/proc/self/statm`` first (live number), `getrusage` peak as the
    fallback so the function still answers off-Linux."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    return host_peak_rss_bytes()


def host_peak_rss_bytes() -> int | None:
    """Peak RSS of this process (bytes) — ``ru_maxrss`` is kilobytes on
    Linux, bytes on macOS."""
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak) if sys.platform == "darwin" else int(peak) * 1024
    except Exception:
        return None


def memory_snapshot(device=None) -> dict:
    """One live memory reading: ``{source, bytes_in_use,
    peak_bytes_in_use, bytes_limit}``.

    ``source`` is ``"hbm"`` when the backend tracks device memory
    (real chips), ``"rss"`` for the host-RSS fallback (CPU-sim —
    ``bytes_limit`` is None there: the host has no HBM budget).  Keys
    are always present so consumers never probe."""
    stats = None
    if device is not None or _jax_available():
        try:
            import jax

            dev = device if device is not None else jax.devices()[0]
            stats = getattr(dev, "memory_stats", lambda: None)()
        except Exception:
            stats = None
    if stats:
        return {
            "source": "hbm",
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
    return {
        "source": "rss",
        "bytes_in_use": host_rss_bytes(),
        "peak_bytes_in_use": host_peak_rss_bytes(),
        "bytes_limit": None,
    }


def _jax_available() -> bool:
    """True when jax is importable AND a backend already initialized —
    a telemetry read must never be the thing that first initializes a
    (possibly tunneled, possibly hanging) backend."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return jax._src.xla_bridge._backends != {}  # noqa: SLF001
    except Exception:
        pass
    try:
        return bool(jax._src.xla_bridge.backends_are_initialized())
    except Exception:
        # both probes are private and may move across jax versions;
        # when neither answers, say NO — degrading to the labeled RSS
        # fallback is recoverable, a tunneled backend init that hangs
        # inside a watermark sample is not
        return False


def publish_gauges(snapshot: dict, registry=None) -> None:
    """Set the ``tpu_dist_hbm_{in_use,peak,limit}_bytes`` gauges from
    one snapshot (labeled with its source, so an RSS fallback never
    masquerades as a chip reading in a scrape)."""
    from tpu_dist.observe import registry as reg_mod

    reg = registry if registry is not None else reg_mod.REGISTRY
    src = snapshot.get("source", "?")
    for key, name, help_ in (
        ("bytes_in_use", "tpu_dist_hbm_in_use_bytes",
         "live device-memory (or host-RSS fallback) bytes in use"),
        ("peak_bytes_in_use", "tpu_dist_hbm_peak_bytes",
         "peak device-memory (or host-RSS fallback) bytes"),
        ("bytes_limit", "tpu_dist_hbm_limit_bytes",
         "device-memory capacity (absent on the RSS fallback)"),
    ):
        value = snapshot.get(key)
        if value is not None:
            reg.gauge(name, help_).set(value, source=src)


class WatermarkSampler:
    """Phase-bucketed peak-memory accounting for one rank.

    Each `sample(phase)` takes a snapshot and attributes the watermark
    delta (``peak_bytes_in_use`` growth since the previous sample) to
    ``phase``; per-phase buckets accumulate ``{samples, delta_bytes,
    peak_bytes}``.  The watermark only ever rises, so the sum of the
    per-phase deltas is the run's total peak growth and the phase with
    the largest delta is where the footprint was built.  Every rise
    also lands one ``memory`` record in the flight ring — the per-rank
    memory trajectory a post-mortem merge renders."""

    def __init__(self, device=None, *, flight=None, registry=None):
        self.device = device
        self.flight = flight if flight is not None else _flightrec.get()
        self.registry = registry
        self.phases: dict[str, dict] = {}
        self.last: dict | None = None
        self._last_peak: int | None = None
        self.last_phase: str | None = None

    def snapshot(self) -> dict:
        """The most recent sample (a fresh unbucketed reading when
        never sampled — probing must not invent a phase delta)."""
        if self.last is None:
            return memory_snapshot(self.device)
        return dict(self.last)

    def sample(self, phase: str) -> dict:
        snap = memory_snapshot(self.device)
        peak = snap.get("peak_bytes_in_use")
        bucket = self.phases.setdefault(
            phase, {"samples": 0, "delta_bytes": 0, "peak_bytes": None}
        )
        bucket["samples"] += 1
        if peak is not None:
            delta = peak - self._last_peak if self._last_peak is not None else 0
            if delta > 0:
                bucket["delta_bytes"] += int(delta)
                # ring record only when the watermark MOVED: a steady-
                # state step adds nothing, so the ring keeps its step
                # history instead of drowning in flat memory lines
                self.flight.record(
                    "memory", phase=phase, peak_bytes=int(peak),
                    delta_bytes=int(delta), source=snap.get("source"),
                )
            bucket["peak_bytes"] = int(peak)
            self._last_peak = int(peak)
        self.last = snap
        self.last_phase = phase
        publish_gauges(snap, self.registry)
        return snap

    def summary(self) -> dict:
        """The ``memory`` event payload: the latest snapshot plus the
        per-phase watermark attribution."""
        snap = self.last or memory_snapshot(self.device)
        return {
            "source": snap.get("source"),
            "bytes_in_use": snap.get("bytes_in_use"),
            "peak_bytes_in_use": snap.get("peak_bytes_in_use"),
            "bytes_limit": snap.get("bytes_limit"),
            "phases": {k: dict(v) for k, v in self.phases.items()},
        }

    def emit(self, logger=None) -> dict | None:
        """Emit the required ``memory`` telemetry event."""
        log = logger if logger is not None else _events.from_env()
        return log.emit("memory", **self.summary())


class MemoryBoundExceeded(RuntimeError):
    """An explicitly-accounted transient exceeded its configured bound —
    a broken streaming plan (a bug), not an organic OOM."""


class TransientMeter:
    """Exact accounting of TRANSIENT host bytes for a bounded streaming
    operation (the elastic-resume redistribution, `train.reshard`).

    RSS cannot isolate transient overhead on the CPU-sim: the target
    device buffers land in the same process RSS as the staging buffers,
    so "never materialize a full replica" must be asserted on an
    explicit counter — `hold` on staging-buffer allocation, `release`
    after hand-off to the device.  With ``limit_bytes`` set, crossing
    the bound raises `MemoryBoundExceeded` at the exact allocation that
    broke it.  Pair with a `WatermarkSampler` for the ambient watermark
    (the `reshard` event reports both)."""

    def __init__(self, limit_bytes: int | None = None, *,
                 what: str = "reshard"):
        self.limit_bytes = limit_bytes
        self.what = what
        self.current = 0
        self.peak = 0

    def hold(self, nbytes: int) -> None:
        self.current += int(nbytes)
        if self.current > self.peak:
            self.peak = self.current
        if self.limit_bytes is not None and self.current > self.limit_bytes:
            raise MemoryBoundExceeded(
                f"{self.what}: transient host bytes ({self.current}) "
                f"exceed the configured bound ({self.limit_bytes}) — the "
                "streaming bucket plan is broken"
            )

    def release(self, nbytes: int) -> None:
        self.current = max(0, self.current - int(nbytes))


# ------------------------------------------------------------ OOM forensics


# Substrings that mark an allocation failure on the step path: XLA
# surfaces RESOURCE_EXHAUSTED through XlaRuntimeError (and sometimes a
# bare "out of memory" on CPU allocators / MemoryError).
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource exhausted", "out of memory",
               "Out of memory")
# bare "OOM" only as a whole word — a substring match would flag
# unrelated text like "BLOOM" and pollute the forensics with spurious
# flight dumps
_OOM_WORD = _re.compile(r"\bOOM\b")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when ``exc`` is an allocation failure worth a memory
    post-mortem: a `MemoryError`, or any exception whose message (or
    type name) carries an OOM marker — XLA's ``RESOURCE_EXHAUSTED``
    status rides `XlaRuntimeError` text, not a dedicated type."""
    if isinstance(exc, MemoryError):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return (any(marker in text for marker in OOM_MARKERS)
            or bool(_OOM_WORD.search(text)))


def oom_report(*, phase: str | None, snapshot: dict | None = None,
               resident: list | None = None, plan: dict | None = None,
               error: str | None = None) -> dict:
    """The plan-vs-live OOM story: which PHASE was executing, how much
    HEADROOM the device had (``bytes_limit - bytes_in_use``; None on
    the RSS fallback, which has no budget), the top RESIDENT classes
    (params / opt / ef_residual / kv_pool / weights / batch — whatever
    the caller can attribute), and the static plan's numbers when one
    is on hand, so "live exceeded plan" is readable from the dump."""
    snap = snapshot or memory_snapshot()
    limit = snap.get("bytes_limit")
    in_use = snap.get("bytes_in_use")
    headroom = (
        int(limit) - int(in_use)
        if limit is not None and in_use is not None else None
    )
    rows = sorted(
        (dict(r) for r in (resident or []) if r.get("bytes") is not None),
        key=lambda r: -int(r["bytes"]),
    )
    return {
        "phase": phase,
        "source": snap.get("source"),
        "bytes_in_use": in_use,
        "peak_bytes_in_use": snap.get("peak_bytes_in_use"),
        "bytes_limit": limit,
        "headroom_bytes": headroom,
        "resident": rows,
        "top_class": rows[0]["class"] if rows else None,
        "plan": plan,
        "error": error,
    }


def record_oom(exc: BaseException, *, phase: str | None = None,
               sampler: WatermarkSampler | None = None,
               resident: list | None = None, plan: dict | None = None,
               events_logger=None, dirpath: str | None = None) -> dict:
    """The one OOM entry point every step path calls: build the
    plan-vs-live report, append it to the flight ring as a ``mark``
    (``what: "oom"``), dump the ring via `flightrec.crash_dump("oom")`
    — the supervisor gathers it like any flight dump — and emit an
    ``oom`` telemetry event.  Never raises (it runs on a crash path);
    returns the report."""
    try:
        snap = None
        if sampler is not None:
            # a FRESH reading at failure time — the sampler's last
            # sample predates the failing allocation, so its in-use
            # number would overstate the headroom.  Exception: a
            # tracked (hbm) snapshot the live probe cannot reproduce
            # stays authoritative — that is the documented fake-
            # bytes_limit test hook on backends with no tracked HBM.
            snap = memory_snapshot(sampler.device)
            last = sampler.last
            if (last is not None and last.get("source") == "hbm"
                    and snap.get("source") != "hbm"):
                snap = dict(last)
        if phase is None and sampler is not None:
            phase = sampler.last_phase
        report = oom_report(
            phase=phase, snapshot=snap, resident=resident, plan=plan,
            error=f"{type(exc).__name__}: {str(exc)[:500]}",
        )
    except Exception:
        report = {"phase": phase, "error": repr(exc), "headroom_bytes": None,
                  "top_class": None}
    try:
        _flightrec.get().record("mark", what="oom", t_mark=time.time(),
                                **report)
    except Exception:
        pass
    try:
        _flightrec.crash_dump("oom", dirpath=dirpath)
    except Exception:
        pass
    try:
        log = events_logger if events_logger is not None else _events.from_env()
        log.emit(
            "oom",
            phase=report.get("phase"),
            headroom_bytes=report.get("headroom_bytes"),
            top_class=report.get("top_class"),
            source=report.get("source"),
            bytes_in_use=report.get("bytes_in_use"),
            bytes_limit=report.get("bytes_limit"),
            resident=report.get("resident"),
            error=report.get("error"),
        )
    except Exception:
        pass
    return report
