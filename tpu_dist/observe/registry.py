"""Metrics registry: counters / gauges / histograms + Prometheus scrape.

In-process, label-aware, stdlib-only.  `MetricsRegistry.render` emits
the Prometheus text exposition format (version 0.0.4); `serve` exposes
it on ``/metrics`` from a daemon thread, and `maybe_serve_from_env`
turns it on when ``TPU_DIST_METRICS_PORT`` is set (port 0 = ephemeral,
for tests).  The trainers publish into the module-level ``REGISTRY`` so
one scrape shows the whole process.
"""

from __future__ import annotations

import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ENV_PORT = "TPU_DIST_METRICS_PORT"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# step-time-shaped default buckets (seconds), 1ms..10s
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _check_name(name: str, what: str = "metric") -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid {what} name {name!r}")
    return name


def _labels_key(labels: dict) -> tuple:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _labels_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count (increments must be >= 0)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(f"{self.name}{_labels_str(key)} {v}")
        # No fabricated 0.0 sample before the first observation: a scrape
        # must not show a measured-looking zero (Prometheus convention:
        # omit a series until it has a value).
        return lines


class Gauge:
    """A value that goes up and down (loss, loss scale, queue depth)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(f"{self.name}{_labels_str(key)} {v}")
        # No fabricated 0.0 sample before the first observation: a scrape
        # must not show a measured-looking zero (Prometheus convention:
        # omit a series until it has a value).
        return lines


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations <= its bound, plus ``+Inf``/sum/count)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = _check_name(name)
        self.help = help
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        # label-key -> [bucket counts..., +Inf count, sum]
        self._values: dict[tuple, list[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            row = self._values.setdefault(
                key, [0.0] * (len(self.buckets) + 2)
            )
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    row[i] += 1
            row[-2] += 1  # +Inf
            row[-1] += value  # sum

    def count(self, **labels) -> float:
        row = self._values.get(_labels_key(labels))
        return row[-2] if row else 0.0

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            for key, row in sorted(self._values.items()):
                for i, bound in enumerate(self.buckets):
                    labels = tuple(sorted(key + (("le", str(bound)),)))
                    lines.append(
                        f"{self.name}_bucket{_labels_str(labels)} {row[i]}"
                    )
                inf = key + (("le", "+Inf"),)
                lines.append(
                    f"{self.name}_bucket{_labels_str(tuple(sorted(inf)))} {row[-2]}"
                )
                lines.append(f"{self.name}_sum{_labels_str(key)} {row[-1]}")
                lines.append(f"{self.name}_count{_labels_str(key)} {row[-2]}")
        return lines


class MetricsRegistry:
    """Named metrics + the text exposition.  ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent across call sites);
    re-registering a name as a different kind raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}"
                    )
                return existing
            metric = cls(name, help, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def render(self) -> str:
        out = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, metric in metrics:
            if metric.help:
                out.append(f"# HELP {name} {metric.help}")
            out.append(f"# TYPE {name} {metric.kind}")
            out.extend(metric.render())
        return "\n".join(out) + "\n"

    def serve(self, port: int = 0, addr: str = "127.0.0.1") -> "MetricsServer":
        return MetricsServer(self, port=port, addr=addr)


class MetricsServer:
    """``/metrics`` on a daemon thread.  ``.port`` is the bound port
    (useful with port 0); ``.close()`` shuts it down."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 addr: str = "127.0.0.1"):
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = reg.render().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stdout
                pass

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpu-dist-metrics",
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# The process-wide registry (what the trainers/bench publish into) and
# its lazily-started server.
REGISTRY = MetricsRegistry()
_server: MetricsServer | None = None
_server_lock = threading.Lock()


def maybe_serve_from_env(registry: MetricsRegistry = REGISTRY):
    """Start (once) the ``/metrics`` endpoint on ``TPU_DIST_METRICS_PORT``
    if set; returns the server or None.  Bind failures are downgraded to
    a warning — metrics export must never kill a training run."""
    raw = os.environ.get(ENV_PORT)
    if raw is None:
        return None
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        try:
            _server = registry.serve(port=int(raw))
        except (OSError, ValueError) as e:
            import warnings

            warnings.warn(
                f"could not serve metrics on {ENV_PORT}={raw!r}: {e}",
                RuntimeWarning,
                stacklevel=2,
            )
            return None
        return _server
