"""Trailing-median regression checker over the persisted bench trajectory.

`bench.persist_event` has been appending every benchmark invocation —
throughput, wire bytes, attribution, and (since this PR) peak memory —
to ``benchmarks/results/bench_runs.jsonl``, but nothing ever READ the
trajectory: a silent 2× throughput drop or footprint blow-up only
surfaced when a human happened to diff two JSON lines.  This module is
the automated reader:

    python -m tpu_dist.observe.regress                 # default file
    python -m tpu_dist.observe.regress --threshold 0.3 --window 8

For every metric series in the file it compares the LATEST row against
the TRAILING MEDIAN of the preceding window and exits nonzero when the
deviation crosses the threshold in the metric's bad direction:

- ``value`` fields are throughput-shaped (higher is better) — a latest
  reading below ``median * (1 - threshold)`` fails;
- byte-shaped fields (``peak_memory_bytes``, ``grad_bytes_on_wire``,
  any field with a ``bytes`` component) are lower-better — a latest
  reading above ``median * (1 + threshold)`` fails.

Series are keyed by ``(metric, memory_source/platform provenance)`` so
a CPU-fallback round is never judged against a TPU median — the
trajectory's known failure mode (ROADMAP: "TPU probe falls back every
round").  Series with fewer than ``--min-history`` prior rows are
reported as ``new`` and never fail.  Stdlib-only, like the rest of
`tpu_dist.observe`.
"""

from __future__ import annotations

import json
import os
import statistics
import sys

from tpu_dist.observe import results as results_mod

DEFAULT_THRESHOLD = 0.5
DEFAULT_WINDOW = 8
DEFAULT_MIN_HISTORY = 3

def field_direction(field: str) -> str | None:
    """The bad direction of one row field, or None when the field is
    not a checked metric: ``value`` is throughput-shaped (higher is
    better); any byte-shaped field (``peak_memory_bytes``,
    ``grad_bytes_on_wire``, ...) is a footprint — growth is the
    regression."""
    if field == "value":
        return "higher"
    if "bytes" in field.split("_"):
        return "lower"
    return None


def checked_fields(rec: dict) -> list[tuple[str, str]]:
    """The ``(field, direction)`` pairs to gate on one row: ``value``
    plus every top-level numeric byte-shaped field the row carries."""
    out = []
    for key, val in rec.items():
        direction = field_direction(key)
        if direction is not None and isinstance(val, (int, float)):
            out.append((key, direction))
    return out


def default_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "benchmarks", "results", "bench_runs.jsonl")


def load_rows(path: str) -> list[dict]:
    """Every parseable JSON row of one JSONL file, file order (=
    chronological: the file is append-only) — the shared
    `observe.results.load_rows` parser."""
    return results_mod.load_rows(path)


def _series_key(rec: dict, field: str) -> tuple | None:
    metric = rec.get("metric")
    if not metric or not isinstance(rec.get(field), (int, float)):
        return None
    # provenance split: CPU-fallback rounds must not be judged against
    # a TPU median (or vice versa)
    platform = results_mod.row_platform(rec)
    if platform is None and rec.get("memory_source") == "hbm":
        platform = "tpu"  # an HBM reading implies a tracked accelerator
    # sub-series discriminators some benches carry (one metric, many
    # configurations — e.g. mesh_rule_set × compress)
    sub = tuple(
        str(rec[k]) for k in ("rule_set", "compress", "bench", "unit")
        if rec.get(k) is not None
    )
    return (str(metric), field, str(platform)) + sub


def check(
    path: str,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    window: int = DEFAULT_WINDOW,
    min_history: int = DEFAULT_MIN_HISTORY,
    skip: tuple = (),
) -> list[dict]:
    """One verdict row per metric series: ``{series, field, direction,
    latest, median, n_history, delta, status}`` with status ``ok`` /
    ``regressed`` / ``new`` (not enough history to judge) /
    ``acknowledged`` (would have regressed, but the series matches a
    ``skip`` substring — the way to accept a known drop without
    rewriting the append-only record)."""
    series: dict[tuple, list[float]] = {}
    for rec in load_rows(path):
        for field, _ in checked_fields(rec):
            key = _series_key(rec, field)
            if key is not None:
                series.setdefault(key, []).append(float(rec[field]))
    out = []
    for key in sorted(series, key=repr):
        values = series[key]
        field = key[1]
        direction = field_direction(field) or "higher"
        latest, history = values[-1], values[:-1][-window:]
        row = {
            "series": ":".join(str(k) for k in (key[0], *key[2:])),
            "field": field,
            "direction": direction,
            "latest": latest,
            "n_history": len(history),
            "median": None,
            "delta": None,
            "status": "new",
        }
        if len(history) >= min_history:
            med = statistics.median(history)
            row["median"] = med
            if med != 0:
                delta = (latest - med) / abs(med)
                row["delta"] = round(delta, 4)
                bad = (
                    delta < -threshold if direction == "higher"
                    else delta > threshold
                )
                row["status"] = "regressed" if bad else "ok"
                if bad and any(s in row["series"] for s in skip):
                    row["status"] = "acknowledged"
            else:
                row["status"] = "ok"
        out.append(row)
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.observe.regress",
        description="latest-vs-trailing-median check over bench_runs.jsonl",
    )
    ap.add_argument("path", nargs="?", default=default_path(),
                    help="JSONL bench record (default: "
                    "benchmarks/results/bench_runs.jsonl)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative deviation that fails (default 0.5 — "
                    "CPU-sim benches are noisy; tighten on real chips)")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing rows the median is taken over")
    ap.add_argument("--min-history", type=int, default=DEFAULT_MIN_HISTORY,
                    help="prior rows required before a series can fail")
    ap.add_argument("--skip", default="",
                    help="comma-separated series substrings whose "
                    "regressions are acknowledged (reported, exit 0)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdict rows")
    args = ap.parse_args(argv)

    rows = check(
        args.path, threshold=args.threshold, window=args.window,
        min_history=args.min_history,
        skip=tuple(s.strip() for s in args.skip.split(",") if s.strip()),
    )
    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        if not rows:
            print(f"no metric series under {args.path}")
        for r in rows:
            med = f"{r['median']:,.1f}" if r["median"] is not None else "--"
            delta = f"{r['delta']:+.1%}" if r["delta"] is not None else "--"
            flag = "REGRESSED" if r["status"] == "regressed" else r["status"]
            print(
                f"{flag:>9}  {r['series']:<60} {r['field']:<18}"
                f" latest {r['latest']:,.1f}  median[{r['n_history']}] {med}"
                f"  delta {delta}"
            )
    regressed = [r for r in rows if r["status"] == "regressed"]
    if regressed:
        print(f"{len(regressed)} series regressed past "
              f"±{args.threshold:.0%} of the trailing median",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
