"""Shared loader for the persisted ``benchmarks/results/*.jsonl`` records.

Three consumers grew their own hand-rolled JSONL parsing of the bench
trajectory — `observe.regress` (trailing-median regression checks over
``bench_runs.jsonl``), the attribution row-parse gate
(``benchmarks/attribute.py`` over ``stage_costs.jsonl``), and now the
static cost model (`analysis.costmodel` over ``attribution.jsonl``).
This module is the one parser they all route through:

- `load_rows(path, series=..., platform=..., require=...)` — every
  parseable JSON object row of one append-only JSONL file, in file
  (= chronological) order, optionally filtered by metric series,
  platform provenance, and required keys;
- `row_platform(rec)` — the backend a row was measured on, read from
  its ``platform`` field or `bench.persist_event` provenance (the
  split that keeps a CPU-fallback round from being judged against a
  TPU median);
- `latest_by(rows, key)` — the newest row per key (file order wins),
  for "latest reading per program/series" consumers.

Stdlib-only, like the rest of `tpu_dist.observe`.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable


def results_dir() -> str:
    """The repo's ``benchmarks/results/`` directory."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "benchmarks", "results")


def results_path(name: str) -> str:
    """``benchmarks/results/<name>`` (e.g. ``attribution.jsonl``)."""
    return os.path.join(results_dir(), name)


def row_platform(rec: dict) -> str | None:
    """The backend one persisted row was measured on: the explicit
    ``platform`` field when present, else `bench.persist_event`'s
    ``provenance.backend``, else None (unattributable)."""
    platform = rec.get("platform")
    if platform is None:
        prov = rec.get("provenance")
        if isinstance(prov, dict):
            platform = prov.get("backend")
    return str(platform) if platform is not None else None


def row_jax_version(rec: dict) -> str | None:
    """The jax version a row was recorded under (provenance), or None."""
    prov = rec.get("provenance")
    if isinstance(prov, dict) and prov.get("jax_version") is not None:
        return str(prov["jax_version"])
    return None


def load_rows(
    path: str,
    *,
    series: str | Iterable[str] | None = None,
    platform: str | None = None,
    require: Iterable[str] = (),
) -> list[dict]:
    """Every parseable JSON object row of one JSONL file, in file order
    (= chronological: the results files are append-only).  Unparseable
    and non-object lines are skipped, a missing file is an empty list —
    the consumers are all "judge whatever trajectory exists" tools.

    ``series`` keeps only rows whose ``metric`` field matches (a string
    or an iterable of strings); ``platform`` keeps only rows whose
    `row_platform` provenance matches (rows with NO provenance are kept
    — old records must not vanish from a filtered view just because
    they predate provenance stamping); ``require`` lists keys every
    returned row must carry."""
    if series is not None and isinstance(series, str):
        series = (series,)
    wanted = set(series) if series is not None else None
    required = tuple(require)
    rows = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if wanted is not None and rec.get("metric") not in wanted:
                    continue
                if platform is not None:
                    p = row_platform(rec)
                    if p is not None and p != platform:
                        continue
                if any(k not in rec for k in required):
                    continue
                rows.append(rec)
    except OSError:
        return []
    return rows


def latest_by(rows: Iterable[dict], key: Callable[[dict], object]) -> dict:
    """The newest row per ``key(row)`` (later file position wins — the
    files are append-only, so file order is recording order).  Rows
    whose key is None are dropped."""
    out: dict = {}
    for rec in rows:
        k = key(rec)
        if k is not None:
            out[k] = rec
    return out
