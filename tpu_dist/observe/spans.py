"""Host-side span tracing — Chrome-trace/perfetto JSON.

`jax.profiler` traces show the DEVICE timeline; what it cannot show is
where the HOST spent its time between dispatches — data loading, batch
sharding, loss readback, checkpoint writes, rendezvous.  `SpanRecorder`
captures those as Chrome-trace "complete" events (``ph: "X"``) that
load in ``chrome://tracing`` / https://ui.perfetto.dev next to the
device trace.

Correlation contract: every span carries ``args.step`` (the global step
id) when the caller provides one, and the trainers run `jax.profiler`
device traces with the SAME step ids (`jax.profiler.StepTraceAnnotation`
naming convention) — load both files in perfetto and match on step.

Opt-in via ``TPU_DIST_TELEMETRY=<dir>``: `from_env` records to
``<dir>/spans_rank<r>.trace.json`` (saved on `save`, which the trainers
call at fit-exit).  Stdlib-only.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time

from tpu_dist.observe import events as _events


class SpanRecorder:
    """Collects Chrome-trace events in memory; `save` writes the JSON
    object format (``{"traceEvents": [...]}``).  Thread-safe."""

    enabled = True

    # Memory bound for multi-day runs: ~3 spans/step accumulate in
    # memory until save(); past this cap new spans are counted, not
    # stored (the count lands in the saved file's otherData).
    MAX_EVENTS = 200_000

    def __init__(self, path: str | None = None, rank: int = 0,
                 max_events: int | None = None):
        self.path = path
        self.rank = int(rank)
        self.max_events = self.MAX_EVENTS if max_events is None else max_events
        self.dropped = 0
        self._lock = threading.Lock()
        self._trace_events: list[dict] = []

    @contextlib.contextmanager
    def span(self, name: str, step: int | None = None, **args):
        """Time a host-side region.  ``step`` is the device-trace
        correlation key; extra kwargs land in the event's ``args``."""
        wall0 = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self._append(
                {
                    "name": name,
                    "ph": "X",
                    "ts": wall0 * 1e6,  # microseconds, trace convention
                    "dur": dur * 1e6,
                    "pid": self.rank,
                    "tid": threading.get_ident() & 0xFFFFFF,
                    "args": self._args(step, args),
                }
            )

    def instant(self, name: str, step: int | None = None, **args) -> None:
        """A zero-duration marker (preemption signal, chaos injection)."""
        self._append(
            {
                "name": name,
                "ph": "i",
                "s": "p",  # process-scoped instant
                "ts": time.time() * 1e6,
                "pid": self.rank,
                "tid": threading.get_ident() & 0xFFFFFF,
                "args": self._args(step, args),
            }
        )

    @staticmethod
    def _args(step, args) -> dict:
        out = dict(args)
        if step is not None:
            out["step"] = int(step)
        return out

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._trace_events) >= self.max_events:
                self.dropped += 1
                return
            self._trace_events.append(ev)

    def __len__(self) -> int:
        return len(self._trace_events)

    def save(self, path: str | None = None) -> str | None:
        """Write the Chrome-trace JSON; returns the path (None if this
        recorder has nowhere to write).  Idempotent — call at every
        fit-exit; later spans simply extend the file on the next save."""
        path = path or self.path
        if path is None:
            return None
        with self._lock:
            doc = {
                "traceEvents": list(self._trace_events),
                "displayTimeUnit": "ms",
                "otherData": {
                    "producer": "tpu_dist.observe.spans",
                    "rank": self.rank,
                    "dropped_events": self.dropped,
                },
            }
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
        return path


class NullRecorder:
    """Telemetry-off stand-in (same surface, zero cost)."""

    enabled = False
    path = None

    @contextlib.contextmanager
    def span(self, name, step=None, **args):
        yield

    def instant(self, name, step=None, **args):
        pass

    def save(self, path=None):
        return None

    def __len__(self):
        return 0


NULL = NullRecorder()
_cache: dict[tuple[str, int], SpanRecorder] = {}
_cache_lock = threading.Lock()
_flush_installed = False


def flush_all() -> None:
    """Save every cached recorder.  Best-effort and LOCK-FREE: this runs
    inside signal handlers (a flight-recorder crash callback), where
    acquiring ``_cache_lock`` could deadlock against the interrupted
    thread already holding it — a racing ``from_env`` insert at worst
    costs this flush one recorder, not the process."""
    try:
        recs = list(_cache.values())
    except RuntimeError:  # dict mutated mid-iteration by a live insert
        recs = []
    for rec in recs:
        try:
            rec.save()
        except Exception:
            pass


def _install_flush_hooks() -> None:
    """`save` is otherwise only called at fit-exit, so a crash between
    fits (or mid-fit before the finally) would lose the whole trace:
    register the flush at interpreter exit AND on the flight recorder's
    crash paths (watchdog fire, SIGTERM/SIGINT, unhandled exception,
    chaos kill) so Chrome traces survive crashes."""
    global _flush_installed
    if not _flush_installed:
        _flush_installed = True
        atexit.register(flush_all)
    # (Re-)register with the flight recorder on every new recorder:
    # registration de-dupes, and this heals the hook if someone reset
    # the crash-callback list.
    try:
        from tpu_dist.observe import flightrec as _flightrec

        _flightrec.register_crash_callback(flush_all)
    except Exception:
        pass


def from_env(rank: int | None = None):
    """This process's recorder under ``TPU_DIST_TELEMETRY`` (cached per
    dir+rank), or the NULL recorder when telemetry is off."""
    dirpath = os.environ.get(_events.ENV_DIR)
    if not dirpath:
        return NULL
    r = _events.env_rank(rank)
    key = (dirpath, r)
    with _cache_lock:
        rec = _cache.get(key)
        if rec is None:
            os.makedirs(dirpath, exist_ok=True)
            rec = SpanRecorder(
                os.path.join(dirpath, f"spans_rank{r}.trace.json"), rank=r
            )
            _cache[key] = rec
    _install_flush_hooks()
    return rec


def merge_traces(paths, out_path: str | None = None) -> dict:
    """Merge per-rank Chrome-trace files into ONE trace with a process
    lane per rank: every event's ``pid`` becomes its rank (taken from
    the file's ``otherData.rank``, falling back to the recorded pid) and
    a ``process_name`` metadata event labels each lane ``rank <r>``, so
    perfetto shows the gang side by side.  Used by the flight-recorder
    merge CLI; returns the merged trace document (written to
    ``out_path`` when given)."""
    events: list[dict] = []
    dropped = 0
    for i, path in enumerate(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        other = doc.get("otherData", {}) or {}
        rank = other.get("rank", i)
        dropped += int(other.get("dropped_events", 0) or 0)
        events.append({
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "ts": 0, "args": {"name": f"rank {rank}"},
        })
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = rank
            events.append(ev)
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "tpu_dist.observe.spans.merge_traces",
            "sources": len(paths),
            "dropped_events": dropped,
        },
    }
    if out_path is not None:
        tmp = f"{out_path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(merged, fh)
        os.replace(tmp, out_path)
    return merged
