"""`tpu_dist.ops` — see package modules."""
