"""`tpu_dist.ops` — Pallas TPU kernels (the hot-op / native-kernel layer).

- `matmul`: tiled MXU matmul with fused bias+activation epilogue
  (interpret-mode testable on CPU).
- `ring_all_reduce_pallas`: the hand-rolled ring allreduce at the RDMA
  level (the reference's allreduce.py exercise at its native depth);
  TPU-only, ppermute fallback elsewhere.
"""

from tpu_dist.ops.flash_attention import (
    flash_attention,
    flash_attention_lse,
)
from tpu_dist.ops.matmul import matmul, use_pallas_dense
from tpu_dist.ops.pallas_ring import ring_all_reduce_pallas

__all__ = [
    "flash_attention",
    "flash_attention_lse",
    "matmul",
    "ring_all_reduce_pallas",
    "use_pallas_dense",
]
