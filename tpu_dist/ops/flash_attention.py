"""Blockwise (flash-style) attention as a Pallas kernel.

The single-device counterpart of `tpu_dist.parallel.ring_attention`: the
same streaming-softmax recurrence (running max / denominator / numerator
in f32), but blocked over the KEY dimension inside one chip's VMEM instead
of over ring hops between chips — the (S, S) score matrix is never
materialized in HBM.  Grid: one program per (batch·head, query-block);
each program scans key/value blocks with ``lax.fori_loop``.

Interpret-mode tested against `tpu_dist.nn.dot_product_attention` on CPU
(values and gradients); compiled on TPU.  Differentiable END TO END in
Pallas: the forward kernel emits per-row LSE, and the custom VJP runs
TWO backward kernels — `_dkv_kernel` (one program per key block, scanning
query blocks for dK/dV) and `_dq_kernel` (one program per query block,
scanning key blocks for dQ) — so the (S, S) score matrix is never
materialized on either pass and ~2/3 of a train step's attention FLOPs
run through hand-written kernels (benchmarks/kernels.py measures fwd and
fwd+bwd against dense XLA).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _band_mask(i, j, bq, bk, causal, window):
    """The visibility mask for (query block i, key block j): causal
    lower-triangle, optionally intersected with the sliding-window band
    ``k > q - window`` (the Mistral-style local-attention pattern)."""
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = q_pos >= k_pos if causal else None
    if window is not None:
        band = k_pos > q_pos - window
        mask = band if mask is None else (mask & band)
    return mask


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bk: int,
                  causal: bool, window: int | None):
    q = q_ref[0].astype(jnp.float32)  # (bq, d)
    bq, d = q.shape
    S = k_ref.shape[1]
    scale = d**-0.5
    qs = q * scale
    i = pl.program_id(1)
    nblocks = S // bk
    masked = causal or window is not None

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        logits = jnp.dot(qs, k_blk.T, preferred_element_type=jnp.float32)
        if masked:
            mask = _band_mask(i, j, bq, bk, causal, window)
            logits = jnp.where(mask, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[:, None])
        if masked:
            p = jnp.where(mask, p, 0.0)
        l_new = l * correction + p.sum(-1)
        acc_new = acc * correction[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # Skip fully-masked key blocks past the diagonal: query block i
        # only attends to keys < (i+1)*bq — roughly halves causal FLOPs.
        hi = lax.min(nblocks, ((i + 1) * bq + bk - 1) // bk)
    else:
        hi = nblocks
    if window is not None:
        # ...and key blocks wholly BEFORE the window: the earliest key
        # this query block can see is i*bq - window + 1, so work is
        # O(S·window) instead of O(S²) — the sliding-window payoff.
        lo = lax.max(0, (i * bq - window + 1) // bk)
    else:
        lo = 0
    m, l, acc = lax.fori_loop(lo, hi, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # log-sum-exp per query row (saved for the backward pass).  lse is
    # carried as (bh, S, 1) — the trailing singleton makes every block
    # (1, bq, 1), satisfying the TPU rule that a block's last two dims
    # divide (8, 128) or equal the array's ((1, bq) blocks on a (bh, S)
    # array violate it whenever bh > 1 and refuse to lower).
    lse_ref[0] = (m + jnp.log(l))[:, None]


def _flash_forward(q3, k3, v3, causal, bq, bk, interpret, window=None):
    bh, S, d = q3.shape
    kernel = functools.partial(
        _flash_kernel, bk=bk, causal=causal, window=window
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, S // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, S, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, S, 1), jnp.float32),
        ],
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    bq: int = 256,
    bk: int = 256,
    interpret: bool = False,
    window: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """`flash_attention` that ALSO returns the per-row log-sum-exp
    ``(..., S)`` the kernel already computes for its backward pass.

    The lse is what makes flash blocks composable: partial attentions
    over disjoint key sets recombine exactly via
    ``out = Σ exp(lse_b - m*) out_b / Σ exp(lse_b - m*)`` — the
    ring-attention composition (`parallel.ring_attention_flash`).
    Forward-only (no VJP); compositions define their own backward.
    """
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    *lead, S, d = q.shape
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    bq = min(bq, S)
    bk = min(bk, S)
    if S % bq or S % bk:
        raise ValueError(f"seq {S} not divisible by blocks ({bq}, {bk})")
    bh = 1
    for x in lead:
        bh *= x
    out, lse = _flash_forward(
        q.reshape(bh, S, d), k.reshape(bh, S, d), v.reshape(bh, S, d),
        causal, bq, bk, interpret, window,
    )
    return out.reshape(q.shape), lse[..., 0].reshape(*lead, S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, causal, bq, bk, interpret, window):
    out, _ = _flash_forward(q3, k3, v3, causal, bq, bk, interpret, window)
    return out


def _flash_fwd(q3, k3, v3, causal, bq, bk, interpret, window):
    out, lse = _flash_forward(q3, k3, v3, causal, bq, bk, interpret, window)
    return out, (q3, k3, v3, out, lse)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref, dk_ref, dv_ref,
                *, bq: int, causal: bool, window: int | None):
    """Backward kernel A: one program per (batch·head, KEY block);
    scans query blocks accumulating dK, dV for this key block in f32."""
    ks = k_ref[0].astype(jnp.float32)  # (bk, d)
    vs = v_ref[0].astype(jnp.float32)
    bk_, d = ks.shape
    S = q_ref.shape[1]
    scale = d**-0.5
    j = pl.program_id(1)
    nq = S // bq
    masked = causal or window is not None

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * bq, bq), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * bq, bq), 0]
        dd = d_ref[0, pl.ds(qi * bq, bq), 0]
        logits = jnp.dot(q * scale, ks.T, preferred_element_type=jnp.float32)
        if masked:
            mask = _band_mask(qi, j, bq, bk_, causal, window)
            logits = jnp.where(mask, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])  # (bq, bk)
        if masked:
            p = jnp.where(mask, p, 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vs.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale
        return dk, dv

    if causal:
        # query blocks before this key block's diagonal are fully masked
        lo = (j * bk_) // bq
    else:
        lo = 0
    if window is not None:
        # the LAST query that can see key block j is (j+1)*bk-1+window-1
        hi = lax.min(nq, ((j + 1) * bk_ - 1 + window - 1) // bq + 1)
    else:
        hi = nq
    dk0 = jnp.zeros((bk_, d), jnp.float32)
    dv0 = jnp.zeros((bk_, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, hi, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref, dq_ref,
               *, bk: int, causal: bool, window: int | None):
    """Backward kernel B: one program per (batch·head, QUERY block);
    scans key blocks accumulating dQ in f32."""
    q = q_ref[0].astype(jnp.float32)  # (bq, d)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]  # (bh, S, 1) carry, see _flash_kernel
    dd = d_ref[0, :, 0]
    bq_, d = q.shape
    S = k_ref.shape[1]
    scale = d**-0.5
    i = pl.program_id(1)
    nk = S // bk
    masked = causal or window is not None

    def body(j, dq):
        ks = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        logits = jnp.dot(q * scale, ks.T, preferred_element_type=jnp.float32)
        if masked:
            mask = _band_mask(i, j, bq_, bk, causal, window)
            logits = jnp.where(mask, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        if masked:
            p = jnp.where(mask, p, 0.0)
        dp = jnp.dot(do, vs.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])
        return dq + jnp.dot(ds, ks, preferred_element_type=jnp.float32) * scale

    hi = lax.min(nk, ((i + 1) * bq_ + bk - 1) // bk) if causal else nk
    lo = (
        lax.max(0, (i * bq_ - window + 1) // bk)
        if window is not None
        else 0
    )
    dq = lax.fori_loop(lo, hi, body, jnp.zeros((bq_, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(causal, bq, bk, interpret, window, res, g):
    """Backward via two Pallas kernels (dK/dV by key block, dQ by query
    block) — the (S, S) score matrix is never formed on either pass.
    Standard flash recurrence: with P = exp(logits - lse) and
    D = rowsum(dO ∘ O),  dV_j = Pᵀ dO,  dS = P ∘ (dO Vᵀ − D),
    dQ += dS K_j · scale,  dK_j = dSᵀ Q · scale."""
    q3, k3, v3, out, lse = res
    bh, S, d = q3.shape
    go = g.astype(q3.dtype)
    D = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # (bh, S, 1) f32 — same trailing-singleton carry as lse

    full = pl.BlockSpec((1, S, d), lambda b, i: (b, 0, 0))
    row_full = pl.BlockSpec((1, S, 1), lambda b, i: (b, 0, 0))
    params = (
        None
        if interpret
        else pltpu.CompilerParams(dimension_semantics=("parallel", "parallel"))
    )
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, causal=causal, window=window),
        grid=(bh, S // bk),
        in_specs=[full, pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
                  full, row_full, row_full],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, S, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, S, d), v3.dtype),
        ],
        compiler_params=params,
        interpret=interpret,
    )(q3, k3, v3, go, lse, D)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bk=bk, causal=causal, window=window),
        grid=(bh, S // bq),
        in_specs=[pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
                  full, full,
                  pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0))],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, S, d), q3.dtype),
        compiler_params=params,
        interpret=interpret,
    )(q3, k3, v3, go, lse, D)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit, static_argnames=("causal", "bq", "bk", "interpret", "window")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    bq: int = 256,
    bk: int = 256,
    interpret: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Attention over (..., heads, S, d) without materializing (S, S).

    Block sizes clamp to the sequence length for small inputs; S must be
    divisible by the (clamped) block sizes.  Differentiable: the custom
    VJP runs the standard flash backward blockwise (peak intermediate
    (S, bk)), using the LSE saved by the forward kernel.

    ``window=w`` adds the LOWER band bound ``k > q - w``; with
    ``causal=True`` that is the sliding-window (Mistral-style)
    autoregressive band ``(q - w, q]``, and forward + both backward
    kernels skip out-of-band blocks — O(S·w) work instead of O(S²).
    Without ``causal`` the bound is one-sided (queries still see all
    FUTURE keys, and the past-side skip is the only saving); for
    symmetric bidirectional local attention use the dense path with
    `nn.sliding_window_mask`.
    """
    *lead, S, d = q.shape
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    bq = min(bq, S)
    bk = min(bk, S)
    if S % bq or S % bk:
        raise ValueError(f"seq {S} not divisible by blocks ({bq}, {bk})")
    bh = 1
    for x in lead:
        bh *= x
    q3 = q.reshape(bh, S, d)
    k3 = k.reshape(bh, S, d)
    v3 = v.reshape(bh, S, d)
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    out = _flash(q3, k3, v3, causal, bq, bk, interpret, window)
    return out.reshape(q.shape)
