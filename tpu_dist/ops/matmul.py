"""Pallas tiled matmul with fused epilogue — the framework's hot-op kernel.

The reference's FLOPs all live in external cuDNN/BLAS (torch.nn conv/linear,
train_dist.py:57-60); on TPU the analog is the MXU, normally driven by XLA.
This kernel is the hand-tuned path for the cases XLA's fusion doesn't own:
matmul + bias + activation in ONE VMEM round-trip (the HBM-bandwidth rule:
fuse elementwise ops into the matmul's epilogue rather than re-reading the
output).

Grid is (M/bm, N/bn, K/bk) with a float32 VMEM accumulator carried across
the K dimension ("arbitrary" semantics — K iterations revisit the same
output tile); inputs may be bf16 (MXU-native) while accumulation stays f32.
Used by `tpu_dist.nn.Dense` when ``TPU_DIST_PALLAS_DENSE=1``; always
available directly as `matmul`.  Tested against jnp.dot in interpret mode
on CPU and compiled on real TPU.
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

_EPILOGUES: dict[str, Callable] = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
}


def _matmul_kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, epilogue: str, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(k == nk - 1)
    def _finish():
        # b_ref is (1, bn): 1-D operands get Mosaic/XLA layout-mismatched
        # tilings on real TPU (bf16[n] refuses to compile) — rank-2 rows
        # are the native layout, and broadcasting handles the rest.
        out = acc_ref[:] + b_ref[:].astype(jnp.float32)
        o_ref[:] = _EPILOGUES[epilogue](out).astype(o_ref.dtype)


_VMEM_BUDGET = 8 * 1024 * 1024  # ~half of a core's ~16MB VMEM

_TUNED_CACHE: dict | None = None


def _tuned_table() -> dict:
    """Measured block winners from ``benchmarks/kernels.py --tune``,
    keyed "MxNxK" per device kind.  Looked up before the `_auto_blocks`
    heuristic so a committed hardware sweep re-tunes the defaults from
    data (the profile -> iterate loop).  Source: the path in
    ``TPU_DIST_TUNED_BLOCKS``, else
    ``benchmarks/results/tuned_blocks_<device_kind>.json`` in the repo;
    absent/unreadable -> empty (heuristic only)."""
    global _TUNED_CACHE
    if _TUNED_CACHE is not None:
        return _TUNED_CACHE
    import json
    from pathlib import Path

    path = os.environ.get("TPU_DIST_TUNED_BLOCKS")
    if not path:
        try:
            import jax

            kind = (
                jax.devices()[0].device_kind.replace(" ", "_").replace("/", "_")
            )
        except Exception:
            kind = "unknown"
        path = str(
            Path(__file__).resolve().parents[2]
            / "benchmarks" / "results" / f"tuned_blocks_{kind}.json"
        )
    try:
        _TUNED_CACHE = {
            key: tuple(int(b) for b in blocks)
            for key, blocks in json.loads(Path(path).read_text()).items()
        }
    except (OSError, ValueError):
        _TUNED_CACHE = {}
    return _TUNED_CACHE


def _resolve_blocks(
    m: int, n: int, k: int, bm, bn, bk
) -> tuple[int, int, int]:
    """Final block sizes: explicit args win, then a measured tuned-table
    entry for this exact shape, then the `_auto_blocks` heuristic."""
    if bm is None or bn is None or bk is None:
        tuned = _tuned_table().get(f"{m}x{n}x{k}")
        abm, abn, abk = tuned if tuned is not None else _auto_blocks(m, n, k)
        bm, bn, bk = bm or abm, bn or abn, bk or abk
    return bm, bn, bk


def _vmem_bytes(bm: int, bn: int, bk: int) -> int:
    """Working set: 2 copies (double buffer) of the input blocks + the
    f32 accumulator + the output block."""
    x_b = bm * bk * 4
    w_b = bk * bn * 4
    acc_b = bm * bn * 4
    return 2 * (x_b + w_b) + 2 * acc_b


def _pick_block(dim: int, target: int) -> int:
    """Largest power-of-two block <= target that divides dim (falls back
    to the full dimension for sizes nothing divides — tiny/odd shapes
    become a single block)."""
    t = target
    while t >= 128:
        if dim % t == 0:
            return t
        t //= 2
    return dim


def _auto_blocks(m: int, n: int, k: int) -> tuple[int, int, int]:
    """Shape-aware default tiling.

    The round-2 hardware run showed 256x256x512 blocks reaching only
    ~40 TF/s at 1024^3 vs XLA's ~116: the working set (~1 MB) leaves
    VMEM (~16 MB/core) idle and re-fetches the operands N/bn + M/bm
    times.  Total HBM traffic is ~ M*K*N/bn + K*N*M/bm, so grow bm/bn
    first (512 each → 4x fewer operand passes than 256), then take bk
    as large as the VMEM budget allows: x(bm,bk) + w(bk,bn) double-
    buffered + f32 acc(bm,bn) + out within ~half of VMEM."""
    bm = _pick_block(m, 512)
    bn = _pick_block(n, 512)
    for bk_target in (2048, 1024, 512, 256, 128):
        bk = _pick_block(k, bk_target)
        if _vmem_bytes(bm, bn, bk) <= _VMEM_BUDGET:
            return bm, bn, bk
    # Nothing fit: only reachable when _pick_block returned a full
    # dimension (nothing >=128 divides it) and that block blows the
    # budget.  Callers pad to 128-multiples before block selection, so
    # this is a guard for explicit odd shapes: shrink the largest block
    # until the working set fits (full-dim blocks cannot shrink — warn).
    bk = _pick_block(k, 128)
    if _vmem_bytes(bm, bn, bk) > _VMEM_BUDGET:
        warnings.warn(
            f"pallas matmul blocks ({bm},{bn},{bk}) for shape "
            f"({m},{n},{k}) exceed the ~{_VMEM_BUDGET >> 20}MB VMEM "
            "budget (no power-of-two >=128 divides the dimensions); "
            "pass bm/bn/bk explicitly or pad the operands",
            stacklevel=3,
        )
    return bm, bn, bk


def _matmul_impl(x, w, b, epilogue, bm, bn, bk, interpret):
    m, k = x.shape
    _, n = w.shape
    # Pad dims that no viable block divides up to the next 128-multiple
    # (k-padding contributes zeros; m/n padding is sliced off) so block
    # selection never degenerates to a full — possibly VMEM-busting —
    # dimension.  A dim's viability is judged against the block the
    # caller actually requested (an explicit bm=500 that divides m=3000
    # must be honored, not padded away); shapes already served by one
    # block (dim <= 256, the pad-unit x2) skip padding: a single small
    # block is cheaper than a copy.
    def _pad_amount(d: int, t: int | None) -> int:
        if d <= 256 or _pick_block(d, t or 512) != d:
            return 0  # a single small block, or a dividing block exists
        padded = d + ((-d) % 128)
        # Pad only when it buys a dividing block: an explicit block that
        # divides neither d nor the 128-multiple (e.g. bm=3000, m=70000)
        # would still degenerate to a full-dim block — after paying for
        # the pad copy.
        return padded - d if _pick_block(padded, t or 512) != padded else 0

    pads = [_pad_amount(d, t) for d, t in zip((m, n, k), (bm, bn, bk))]
    if any(pads):
        pm, pn, pk = pads
        x = jnp.pad(x, ((0, pm), (0, pk)))
        w = jnp.pad(w, ((0, pk), (0, pn)))
        b = jnp.pad(b, ((0, 0), (0, pn)))
        out = _matmul_impl(x, w, b, epilogue, bm, bn, bk, interpret)
        return out[:m, :n]
    bm, bn, bk = _resolve_blocks(m, n, k, bm, bn, bk)
    bm_, bn_, bk_ = _pick_block(m, bm), _pick_block(n, bn), _pick_block(k, bk)
    if not interpret and _vmem_bytes(bm_, bn_, bk_) > _VMEM_BUDGET:
        # explicit blocks bypass _auto_blocks' budget loop (and padding
        # cannot rescue a block that divides nothing) — never silent
        warnings.warn(
            f"pallas matmul blocks ({bm_},{bn_},{bk_}) for shape "
            f"({m},{n},{k}) exceed the ~{_VMEM_BUDGET >> 20}MB VMEM "
            "budget; expect Mosaic failure or HBM spills — pass smaller "
            "bm/bn/bk or pad the operands",
            stacklevel=3,
        )
    nk = k // bk_
    grid = (m // bm_, n // bn_, nk)
    kernel = functools.partial(_matmul_kernel, epilogue=epilogue, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm_, bk_), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk_, bn_), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn_), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm_, bn_), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm_, bn_), jnp.float32)],
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(x, w, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _matmul_core(x, w, b, epilogue, bm, bn, bk, interpret):
    return _matmul_impl(x, w, b, epilogue, bm, bn, bk, interpret)


def _matmul_fwd(x, w, b, epilogue, bm, bn, bk, interpret):
    out = _matmul_impl(x, w, b, epilogue, bm, bn, bk, interpret)
    return out, (x, w, b)


def _matmul_bwd(epilogue, bm, bn, bk, interpret, res, g):
    # Backward = two plain matmuls + a reduction; XLA owns those (they
    # have no fusable epilogue).  The kernel's value-add — the fused
    # forward epilogue — needs the pre-activation recomputed here for
    # non-trivial epilogues (cheaper than saving an (M, N) residual).
    x, w, b = res
    if epilogue == "none":
        d_pre = g
    else:
        pre = _matmul_impl(x, w, b, "none", bm, bn, bk, interpret)
        _, act_vjp = jax.vjp(_EPILOGUES[epilogue], pre)
        (d_pre,) = act_vjp(g)
    dx = d_pre @ w.T
    dw = x.T @ d_pre
    db = d_pre.sum(0, keepdims=True)  # b is (1, N) inside the core
    return dx.astype(x.dtype), dw.astype(w.dtype), db.astype(b.dtype)


_matmul_core.defvjp(_matmul_fwd, _matmul_bwd)


@functools.partial(
    jax.jit, static_argnames=("epilogue", "bm", "bn", "bk", "interpret")
)
def matmul(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    *,
    epilogue: str = "none",
    bm: int | None = None,
    bn: int | None = None,
    bk: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """``epilogue(x @ w + b)`` in one kernel.  x: (M, K), w: (K, N),
    b: (N,) or None.  Block sizes default to a shape-aware pick
    (`_auto_blocks`: fill VMEM, minimize operand re-fetches) and fall
    back to the full dimension when nothing divides evenly (tiny shapes
    just become a single block); pass bm/bn/bk to override.
    Differentiable: a custom VJP computes dx/dw/db with plain XLA matmuls
    (recomputing the pre-activation for fused epilogues), so the kernel is
    safe inside `jax.grad`/train steps."""
    if epilogue not in _EPILOGUES:
        raise ValueError(f"unknown epilogue {epilogue!r}; one of {list(_EPILOGUES)}")
    m, k = x.shape
    k2, n = w.shape
    if k != k2:
        raise ValueError(f"inner dims mismatch: {x.shape} @ {w.shape}")
    if b is None:
        b = jnp.zeros((n,), x.dtype)
    # (1, N) internally — see _matmul_kernel's layout note.
    return _matmul_core(x, w, b.reshape(1, n), epilogue, bm, bn, bk, interpret)


def use_pallas_dense() -> bool:
    """Feature flag: route `tpu_dist.nn.Dense` through this kernel."""
    return os.environ.get("TPU_DIST_PALLAS_DENSE", "0") == "1"
