"""Hand-rolled ring all-reduce as a Pallas TPU kernel with explicit
inter-chip RDMA — the true native analog of the reference's exercise.

The reference hand-implements DeepSpeech's ring allreduce over p2p
send/recv (allreduce.py:8-34, tuto.md:322-354) on top of THD's C++
transport.  `tpu_dist.parallel.ring_all_reduce` re-expresses that with
XLA-level `ppermute`; THIS module goes one level lower — the level the
reference's Gloo/NCCL kernels live at: a Pallas kernel issuing its own
inter-chip DMAs (`make_async_remote_copy` over ICI), with neighbor
barriers and double-buffered communication slots, per the TPU kernel
playbook (/opt/skills/guides/pallas_guide.md, "Ring Collectives").

COMPILED execution needs ≥2 real TPU chips (those tests carry the
``tpu`` marker; on other platforms `ring_all_reduce_pallas` falls back
to the ppermute ring so callers can use one entry point).  The kernel
itself, though, is exercised EVERYWHERE: Pallas's TPU interpret mode
(`pltpu.InterpretParams`) simulates the DMA semaphores and remote copies
across the CPU-sim mesh, so the un-gated tests run the real kernel body
— barriers, double buffering, RDMA ordering — and cross-check it against
``lax.psum`` (tests/test_ops.py::TestPallasRing).
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_dist.comm.mesh import DEFAULT_AXIS
from tpu_dist.parallel.ring import ring_all_reduce_chunked


def _ring_kernel(x_ref, o_ref, comm_buf, send_sem, recv_sem, *, axis_name):
    """Naive ring: n-1 hops of the full buffer, accumulate on arrival.

    comm_buf: VMEM (2, *x.shape) — slot s holds the buffer being sent
    (s = step % 2) while slot 1-s receives the neighbor's.
    """
    n = lax.axis_size(axis_name)
    my_id = lax.axis_index(axis_name)
    right = lax.rem(my_id + 1, n)
    left = lax.rem(my_id - 1 + n, n)
    barrier = pltpu.get_barrier_semaphore()

    o_ref[:] = x_ref[:]
    comm_buf[0] = x_ref[:]

    def step_body(step, _):
        send_slot = lax.rem(step, 2)
        recv_slot = 1 - send_slot
        # Backpressure: at step s we write the RIGHT neighbor's slot
        # (1 - s%2), the very slot it sends from at step s-1.  A
        # neighborhood barrier at the top of every step guarantees both
        # neighbors have finished their previous step's send+recv+
        # accumulate (and, at step 0, have entered the kernel and
        # allocated comm_buf) before any RDMA lands in their buffers —
        # without it a fast sender could overwrite a slot still being
        # sent from, silently corrupting the sum for n >= 3.
        pltpu.semaphore_signal(barrier, inc=1, device_id=(left,))
        pltpu.semaphore_signal(barrier, inc=1, device_id=(right,))
        pltpu.semaphore_wait(barrier, 2)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[send_slot],
            dst_ref=comm_buf.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=right,  # LOGICAL ids are scalars (tuples are MESH coords)
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        o_ref[:] += comm_buf[recv_slot]
        return _

    lax.fori_loop(0, n - 1, step_body, None)


def tpu_interpret_supported() -> bool:
    """Whether this jax ships Pallas's TPU interpret simulator
    (`pltpu.InterpretParams`, jax >= 0.5) — the mode that simulates DMA
    semaphores and remote copies on CPU devices.  Older jax only has the
    generic HLO interpreter, which cannot execute the inter-chip RDMA
    primitives this kernel is made of."""
    return hasattr(pltpu, "InterpretParams")


def _pallas_ring(
    x: jax.Array, axis_name: str, collective_id: int, *,
    interpret: bool = False,
) -> jax.Array:
    """``interpret=True`` runs the kernel under Pallas's TPU interpret
    mode (`pltpu.InterpretParams`), which SIMULATES the semaphores and
    inter-chip RDMAs on CPU devices — the same kernel body, exercised
    without hardware (tests/test_ops.py runs it on the CPU-sim mesh and
    cross-checks against psum).  Raises `NotImplementedError` on jax
    builds without the simulator (see `tpu_interpret_supported`) rather
    than tripping an AttributeError mid-trace."""
    if interpret and not tpu_interpret_supported():
        raise NotImplementedError(
            "Pallas TPU interpret mode (pltpu.InterpretParams) is not "
            f"available in jax {jax.__version__}; the RDMA ring kernel "
            "can only be simulated on jax >= 0.5 (compiled execution "
            "still needs >= 2 real TPU chips)"
        )
    return pl.pallas_call(
        functools.partial(_ring_kernel, axis_name=axis_name),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(collective_id=collective_id),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x)


def ring_all_reduce_pallas(
    x: jax.Array,
    axis_name: str = DEFAULT_AXIS,
    *,
    collective_id: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Ring all-reduce via explicit RDMA when running on ≥2 TPU chips;
    falls back to the ppermute ring elsewhere (CPU execution has no real
    inter-chip DMA).  The fallback WARNS loudly so a benchmark or test
    can never silently report "RDMA kernel" numbers that ran the
    ppermute path instead.  Call inside shard_map over ``axis_name``
    (which must be the mesh's only axis for LOGICAL device ids to equal
    ring positions).

    ``interpret=True`` runs the ACTUAL kernel (semaphores, remote
    copies) under Pallas's TPU interpret simulator on any platform — no
    fallback, no warning; how the kernel is exercised without hardware.
    """
    import warnings

    if interpret:
        return _pallas_ring(x, axis_name, collective_id, interpret=True)
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover
        platform = "cpu"
    if platform != "tpu":
        warnings.warn(
            f"ring_all_reduce_pallas: not on TPU (platform={platform!r}) — "
            f"falling back to the ppermute ring; any numbers produced are "
            f"NOT RDMA-kernel numbers",
            RuntimeWarning,
            stacklevel=2,
        )
        return ring_all_reduce_chunked(x, axis_name)
    return _pallas_ring(x, axis_name, collective_id)
