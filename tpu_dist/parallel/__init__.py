"""`tpu_dist.parallel` — parallelism strategies (SURVEY.md §2d).

Data parallelism (the reference's centerpiece), the ppermute ring
collectives (its hand-rolled allreduce, done right), and the sequence-
parallel ring-attention extension built on the same ring substrate.
"""

from tpu_dist.parallel.data_parallel import (
    DATA_AXIS,
    average_gradients,
    make_stateful_train_step,
    make_train_step,
    make_train_step_auto,
    replicate,
    shard_batch,
)
from tpu_dist.parallel.ring_attention import (  # noqa: I001
    ring_attention_flash,
    RingMultiHeadAttention,
    ring_attention,
)
from tpu_dist.parallel.moe import (
    EXPERT_AXIS,
    moe_mlp,
    moe_mlp_expert_choice,
    moe_mlp_top2,
    stack_expert_params,
)
from tpu_dist.parallel.pipeline import (
    PIPE_AXIS,
    SCHEDULE_KINDS,
    Schedule,
    build_schedule,
    gpipe_bubble_fraction,
    gpipe_ticks,
    interleaved_bubble_fraction,
    interleaved_ticks,
    pipeline_apply,
    pipeline_apply_interleaved,
    pipeline_engine_loss,
    stack_chunk_params,
    stack_stage_params,
)
from tpu_dist.parallel.fsdp import (
    fsdp_gather_params,
    fsdp_gather_params_compiled,
    fsdp_full_params,
    fsdp_shard_params,
    make_fsdp_train_step,
    make_zero1_train_step,
)
from tpu_dist.parallel.overlap import (
    allgather_matmul,
    matmul_reduce_scatter,
    tp_attention_overlapped,
    tp_encoder_block_sp,
    tp_mlp_overlapped,
)
from tpu_dist.parallel.ulysses import ulysses_attention
from tpu_dist.parallel.tensor_parallel import (
    MODEL_AXIS,
    column_parallel,
    row_parallel,
    shard_dim,
    tp_attention,
    tp_attention_cached,
    tp_embedding,
    tp_encoder_block,
    tp_mlp,
    tp_mlp_block,
    tp_vocab_cross_entropy,
)
from tpu_dist.parallel.ring import (
    ring_all_gather,
    ring_all_reduce,
    ring_all_reduce_chunked,
    ring_reduce_scatter,
)

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "fsdp_gather_params",
    "fsdp_gather_params_compiled",
    "fsdp_full_params",
    "fsdp_shard_params",
    "gpipe_bubble_fraction",
    "gpipe_ticks",
    "interleaved_bubble_fraction",
    "interleaved_ticks",
    "allgather_matmul",
    "matmul_reduce_scatter",
    "moe_mlp",
    "moe_mlp_expert_choice",
    "moe_mlp_top2",
    "pipeline_apply",
    "pipeline_apply_interleaved",
    "pipeline_engine_loss",
    "Schedule",
    "SCHEDULE_KINDS",
    "build_schedule",
    "stack_chunk_params",
    "stack_expert_params",
    "stack_stage_params",
    "RingMultiHeadAttention",
    "average_gradients",
    "column_parallel",
    "row_parallel",
    "shard_dim",
    "tp_attention",
    "tp_attention_cached",
    "tp_embedding",
    "tp_encoder_block",
    "tp_mlp",
    "tp_attention_overlapped",
    "tp_encoder_block_sp",
    "tp_mlp_block",
    "tp_mlp_overlapped",
    "tp_vocab_cross_entropy",
    "make_fsdp_train_step",
    "make_zero1_train_step",
    "make_stateful_train_step",
    "make_train_step",
    "make_train_step_auto",
    "replicate",
    "ring_all_gather",
    "ring_all_reduce",
    "ring_all_reduce_chunked",
    "ring_attention",
    "ring_attention_flash",
    "ring_reduce_scatter",
    "shard_batch",
    "ulysses_attention",
]
