"""Data parallelism — the reference's centerpiece, compiled the TPU way.

The reference implements DistributedDataParallel by hand
(tuto.md:204-321): replicate the model, shard the data, and after every
backward pass call ``all_reduce`` *per parameter* then divide by world size
(``average_gradients``, train_dist.py:94-100).  That per-tensor blocking
loop is the didactic gap the tutorial itself points out vs real DDP
(tuto.md:319-320: no bucketing, no compute/comm overlap).

Under XLA the whole train step — forward, backward, gradient averaging,
optimizer update — is one compiled SPMD program, so the collective is
fused, bucketed, and overlapped with the backward pass by the compiler.
Two styles are provided:

- `average_gradients(grads, axis_name)`: the explicit `pmean` over the
  gradient pytree — the literal ``average_gradients`` analog, used inside
  a ``shard_map``'d step.
- `make_train_step(...)`: builds the full jitted step over a mesh: batch
  sharded on the ``data`` axis, params/opt-state replicated, gradients
  averaged, update applied — the whole of train_dist.py:115-124 as one
  XLA program per step.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def average_gradients(
    grads: Any, axis_name: str = DATA_AXIS, *, backend: str = "psum"
) -> Any:
    """``average_gradients(model)`` (train_dist.py:94-100) over a pytree:
    sum across data-parallel ranks, divide by world size — i.e. ``pmean``.
    One fused collective over the whole tree instead of one blocking
    all_reduce per parameter (and without the reference's type-guard bug,
    SURVEY.md §2c.2).

    Per-tensor observable behavior (SURVEY.md §7 hard part (b)): the tree
    map issues one collective PER PARAMETER — exactly the reference's
    loop structure — and XLA's combiner then buckets/fuses them; the
    per-tensor semantics are preserved at the program level while the
    schedule gets the fusion the reference lacks (tuto.md:319-320).

    ``backend='ring'`` swaps in the hand-rolled chunked ppermute ring
    (`tpu_dist.parallel.ring_all_reduce_chunked`) — the reference's
    allreduce.py path used for its real purpose.  Numerically equivalent
    (tests assert identical training).  ``backend='int8'`` / ``'fp8'`` /
    ``'bf16'`` use the per-leaf quantized collective
    (`comm.all_reduce_quantized`, 4× / 4× / 2× less ICI traffic, lossy —
    gradient-noise-level error; fp8 = e4m3 wire, relative precision for
    heavy-tailed gradients; bf16 = scale-free cast).  ``'psum'`` (XLA
    AllReduce) is the production default; for the bucketed
    error-feedback engine see ``compress`` on
    `partition.make_partitioned_train_step` (`comm.compress`).
    """
    if backend == "psum":
        return lax.pmean(grads, axis_name)
    n = lax.axis_size(axis_name)
    if backend == "ring":
        from tpu_dist.parallel.ring import ring_all_reduce_chunked

        return jax.tree.map(
            lambda g: ring_all_reduce_chunked(g, axis_name) / n, grads
        )
    if backend in ("int8", "fp8", "bf16"):
        from tpu_dist.comm.collectives import all_reduce_quantized

        # _wire_spec canonicalizes the short spellings (WIRE_ALIASES)
        return jax.tree.map(
            lambda g: all_reduce_quantized(g, axis_name, dtype=backend) / n,
            grads,
        )
    raise ValueError(f"unknown grad-reduce backend {backend!r}")


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
):
    """Build the compiled data-parallel train step.

    Args:
      loss_fn: ``loss_fn(params, batch, key) -> (loss, aux)`` computed on
        the *local* shard of the batch.  ``aux`` is any pytree (e.g. new
        model state, metrics).
      optimizer: a `tpu_dist.train.optim.Optimizer` (init/update pair).
      mesh: mesh whose ``axis_name`` axis shards the batch.
      donate: donate params/opt-state buffers (in-place update on device).

    Returns ``step(params, opt_state, batch, key) -> (params, opt_state,
    loss, aux)`` where ``batch`` arrays are sharded on their leading axis
    over ``axis_name`` and everything else is replicated.  The gradient
    ``pmean`` — the whole of ``average_gradients`` — is inside the compiled
    program, so XLA overlaps it with the backward pass (the fused design
    required for the 8-chip scaling target, SURVEY.md §7 hard part (e)).

    Implemented as the stateless special case of `make_spmd_train_step`.
    """

    def stateful_loss(params, _state, batch, key):
        loss, aux = loss_fn(params, batch, key)
        return loss, ((), aux)

    stateful = make_spmd_train_step(
        stateful_loss, optimizer, mesh, axis_name=axis_name, donate=donate
    )

    def step(params, opt_state, batch, key):
        params, _, opt_state, loss, aux = stateful(
            params, (), opt_state, batch, key
        )
        return params, opt_state, loss, aux

    return step


def _pmean_float_leaves(tree: Any, axis_name: str) -> Any:
    """pmean floating leaves; pass through non-float leaves (which must be
    rank-invariant)."""
    return jax.tree.map(
        lambda a: lax.pmean(a, axis_name)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
        else a,
        tree,
    )


def accumulate_microbatches(
    grads_and_metrics, params, model_state, batch, key, accum_steps: int
):
    """The microbatch-accumulation scan shared by every step builder
    (replicated DP here; FSDP/ZeRO-1 in `parallel.fsdp`): split the
    local batch into ``accum_steps`` microbatches along axis 0 and scan
    them with a gradient-sum carry, so only one microbatch's activations
    are ever live.

    ``grads_and_metrics(params, state, micro_batch, key) -> (grads,
    loss, new_state, aux)``.  Returns ``(mean_grads, mean_loss,
    final_state, aux)`` — aux float leaves averaged over microbatches,
    non-float leaves from the last microbatch (the step contract).
    The per-microbatch key is ``fold_in(key, i)``.
    """

    def to_micro(a):
        if a.shape[0] % accum_steps:
            raise ValueError(
                f"local batch {a.shape[0]} not divisible by "
                f"accum_steps {accum_steps}"
            )
        return a.reshape(
            (accum_steps, a.shape[0] // accum_steps) + a.shape[1:]
        )

    micro = jax.tree.map(to_micro, batch)
    g0 = jax.tree.map(jnp.zeros_like, params)

    def body(carry, xs):
        state, gacc, lacc = carry
        mb, i = xs
        g, loss, state, aux = grads_and_metrics(
            params, state, mb, jax.random.fold_in(key, i)
        )
        return (state, jax.tree.map(jnp.add, gacc, g), lacc + loss), aux

    (new_state, gsum, lsum), auxs = lax.scan(
        body, (model_state, g0, 0.0), (micro, jnp.arange(accum_steps))
    )
    grads = jax.tree.map(lambda g: g / accum_steps, gsum)
    aux = jax.tree.map(
        lambda a: a.mean(0)
        if jnp.issubdtype(a.dtype, jnp.floating)
        else a[-1],
        auxs,
    )
    return grads, lsum / accum_steps, new_state, aux


def make_spmd_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    grad_reduce: str = "psum",
    accum_steps: int = 1,
    extra_grad_axes: tuple[str, ...] = (),
    grad_psum_axes: tuple[str, ...] = (),
    batch_spec=None,
):
    """Like `make_train_step` but threads non-differentiated model state
    (e.g. batch-norm running statistics) through the step.

    ``extra_grad_axes``: additional mesh axes to pmean gradients (and
    loss/state/aux) over — the tensor-parallel gradient contract: a
    model-sharded loss's per-rank grad is its shard's contribution, and
    the model-axis mean recovers the dense gradient (tested for both TP
    layouts).  ``grad_psum_axes``: axes whose per-rank grads PARTITION
    the dense gradient and must therefore SUM — the pipeline-parallel
    contract (`TransformerLM.loss_pipeline`: each rank's grads are
    nonzero only on its stage's blocks; loss and aux still pmean, being
    replicated).  ``batch_spec``: PartitionSpec for the batch (default
    ``P(axis_name)``) — e.g. ``P('data', 'model')`` shards token windows
    over batch AND sequence for the Megatron-SP layout.

    ``loss_fn(params, model_state, batch, key) -> (loss, (new_state, aux))``.
    Returns ``step(params, model_state, opt_state, batch, key) ->
    (params, model_state, opt_state, loss, aux)``.  New state's floating
    leaves are cross-replica averaged (SyncBN-style statistics), keeping
    replicas bit-identical — the reference's cross-rank identity invariant
    (SURVEY.md §2c.6) extended to stateful models.

    ``accum_steps=k`` enables gradient accumulation: each rank's batch
    shard is split into ``k`` microbatches processed by a ``lax.scan``
    whose carry accumulates the gradient sum — so only ONE microbatch's
    activations are ever live (HBM scales with ``local_batch / k``), the
    optimizer still sees the mean gradient over the full global batch,
    and the collective still fires once per step.  Stateless models match
    the unaccumulated step to fp tolerance (tests); model state threads
    through microbatches sequentially (its per-microbatch semantics —
    e.g. BN statistics see smaller batches — are inherent to
    accumulation).  Aux float leaves are averaged over microbatches.

    For the bucketed error-feedback compressed gradient wire, use the
    partition engine: `partition.make_partitioned_train_step`'s
    ``compress`` option carries it inside the GSPMD program.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    # A `resilience.nan_guard`-wrapped optimizer advertises its live
    # dynamic loss scale; the builder threads it through the backward
    # pass (scaled loss in, unscaled grads + reported loss out) so the
    # scale protects the bf16 intermediate gradients it exists for.
    scale_fn = getattr(optimizer, "current_scale", None)
    if scale_fn is not None:
        # Import here, not module-top: guards pulls in tpu_dist.train,
        # which circularly imports this package at tpu_dist-init time.
        from tpu_dist.resilience.guards import _poison

    def grads_and_metrics(params, model_state, batch, key, scale=None):
        """(grads, loss, new_state, aux) for one (micro)batch; ``scale``
        (a traced scalar) multiplies the loss before the backward and is
        divided back out of grads and the reported loss."""
        fn = loss_fn
        if scale is not None:
            def fn(p, s, b, k):
                loss, (new_state, aux) = loss_fn(p, s, b, k)
                return loss * scale, (new_state, aux)
        (loss, (new_state, aux)), grads = jax.value_and_grad(
            fn, has_aux=True
        )(params, model_state, batch, key)
        if scale is not None:
            inv = 1.0 / scale
            grads = jax.tree.map(lambda g: g * inv, grads)
            loss = loss * inv
        return grads, loss, new_state, aux

    def spmd_step(params, model_state, opt_state, batch, key):
        # fold over the DATA axis only: model-axis ranks run the same
        # replicated computation and must share keys (dropout identity)
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
        scale = scale_fn(opt_state) if scale_fn is not None else None
        gm = functools.partial(grads_and_metrics, scale=scale)
        if accum_steps == 1:
            grads, loss, new_state, aux = gm(params, model_state, batch, key)
        else:
            grads, loss, new_state, aux = accumulate_microbatches(
                gm, params, model_state, batch, key, accum_steps
            )
        if scale_fn is not None:
            # Guarded step: a non-finite LOSS must trip the skip even in
            # the corner where every gradient stays finite (e.g. the NaN
            # arises in a branch with zero cotangent) — poison the grads
            # BEFORE the reduce, so the exact psum propagates the NaN to
            # every rank and the guard skips the step.
            grads = _poison(grads, ~jnp.isfinite(loss))
        grads = average_gradients(grads, axis_name, backend=grad_reduce)
        loss = lax.pmean(loss, axis_name)
        for ax in extra_grad_axes:
            grads = jax.tree.map(lambda g: lax.pmean(g, ax), grads)
            loss = lax.pmean(loss, ax)
            new_state = _pmean_float_leaves(new_state, ax)
            aux = _pmean_float_leaves(aux, ax)
        for ax in grad_psum_axes:
            grads = jax.tree.map(lambda g: lax.psum(g, ax), grads)
            loss = lax.pmean(loss, ax)  # replicated loss: mean, not sum
            new_state = _pmean_float_leaves(new_state, ax)
            aux = _pmean_float_leaves(aux, ax)
        new_state = _pmean_float_leaves(new_state, axis_name)
        aux = _pmean_float_leaves(aux, axis_name)
        params, new_opt = optimizer.update(params, grads, opt_state)
        return params, new_state, new_opt, loss, aux

    mapped = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(
            P(), P(), P(),
            batch_spec if batch_spec is not None else P(axis_name),
            P(),
        ),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())


def make_train_step_auto(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
):
    """The compiler-driven alternative to `make_spmd_train_step`.

    Instead of writing per-rank SPMD code with an explicit ``pmean``
    (the shard_map style that mirrors the reference's
    ``average_gradients``), this expresses the *global* computation —
    ``loss_fn(params, model_state, global_batch, key)`` over the whole
    batch — under ``jit`` with sharding annotations: batch sharded on
    ``axis_name``, everything else replicated.  XLA's SPMD partitioner
    derives the gradient all-reduce itself (GSPMD), which is the most
    idiomatic modern-JAX form and lets the compiler choose collective
    schedules.  Both styles are tested to produce identical training.

    ``loss_fn`` must compute a mean over the batch axis for gradients to
    match the explicit-pmean path.
    """
    repl = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(axis_name))

    def global_step(params, model_state, opt_state, batch, key):
        (loss, (new_state, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, model_state, batch, key)
        params, opt_state = optimizer.update(params, grads, opt_state)
        return params, new_state, opt_state, loss, aux

    return jax.jit(
        global_step,
        in_shardings=(repl, repl, repl, sharded, repl),
        out_shardings=(repl, repl, repl, repl, repl),
        donate_argnums=(0, 1, 2) if donate else (),
    )


def shard_batch(
    batch: Any, mesh: Mesh, axis_name: str = DATA_AXIS, *, spec=None
) -> Any:
    """Place a host batch on the mesh, sharded over its leading axis —
    the device-side analog of handing each process its partition.
    ``spec`` overrides the default ``P(axis_name)`` (e.g.
    ``P('data', 'model')`` for sequence-sharded token windows)."""
    sharding = NamedSharding(mesh, spec if spec is not None else P(axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Replicate a pytree (params/opt state) across the mesh — the model
    replication half of data parallelism (tuto.md:216)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
