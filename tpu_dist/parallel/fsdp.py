"""FSDP flat-row layout utilities (shard/gather helpers).

The hand-written FSDP/ZeRO-1 *train-step builders* that used to live
here are retired: `parallel.partition.make_partitioned_train_step` is
the one sharded train step (the ``fsdp`` / ``zero1:dp`` rule sets), and
the trainers' ``fsdp``/``zero1`` flags route through it.  What remains
is the flat ``(n, k)`` row layout itself — still the storage format of
pre-engine sharded checkpoints and a useful manual-sharding primitive:

- each leaf is stored flattened and padded to ``(n, k)``, sharded
  ``P(axis)`` (rank r holds row r: 1/n of the leaf);
- `fsdp_shard_params` / `fsdp_gather_params` convert between logical
  pytrees and the row layout;
- `fsdp_gather_params_compiled` is the multi-host-safe compiled
  all_gather reassembly (`fsdp_full_params` picks between them).

Padding is benign: padded entries are zero and stay zero.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.data_parallel import DATA_AXIS
from tpu_dist.utils.tree import pad_to_multiple


def _pad_rows(flat: jax.Array, n: int) -> jax.Array:
    return pad_to_multiple(flat, n).reshape(n, -1)


def _unshard_rows(rows: Any, template: Any, axis_name: str) -> Any:
    """all_gather each local (1, k) row back into its full logical leaf
    (inside shard_map)."""

    def un(s, t):
        full = lax.all_gather(s, axis_name, axis=0, tiled=True)
        return full.reshape(-1)[: math.prod(t.shape)].reshape(t.shape)

    return jax.tree.map(un, rows, template)



def fsdp_shard_params(params: Any, mesh: Mesh, axis_name: str = DATA_AXIS) -> Any:
    """Shard a full parameter pytree: every leaf becomes an ``(n, k)``
    array sharded ``P(axis_name)`` (row r on rank r, zero-padded)."""
    n = mesh.shape[axis_name]
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(
        lambda p: jax.device_put(_pad_rows(jnp.ravel(p), n), sharding), params
    )


def fsdp_gather_params(sharded: Any, template: Any) -> Any:
    """Reassemble full parameters from FSDP shards (host-side: eval,
    checkpointing).  ``template`` supplies the original shapes/dtypes.

    Single-host only: shards living on another process's devices cannot
    be fetched here — on a multi-host pod, checkpoint the sharded arrays
    directly (orbax handles distributed arrays) or gather inside a
    compiled program."""
    import numpy as np

    for leaf in jax.tree.leaves(sharded):
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            raise RuntimeError(
                "fsdp_gather_params: shards span non-addressable devices "
                "(multi-host mesh) — checkpoint the sharded pytree with "
                "orbax, or all_gather inside a jitted fn instead"
            )
    return jax.tree.map(
        lambda s, t: jnp.asarray(np.asarray(s).reshape(-1)[: math.prod(t.shape)])
        .reshape(t.shape)
        .astype(t.dtype),
        sharded,
        template,
    )



_GATHER_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def fsdp_gather_params_compiled(
    sharded: Any, template: Any, mesh: Mesh, axis_name: str = DATA_AXIS
) -> Any:
    """Reassemble full parameters INSIDE a compiled program — the
    multi-host-safe sibling of `fsdp_gather_params` (which fetches shard
    bytes to one host and raises when shards live on another process's
    devices).  Each (n, k) leaf all-gathers its rows over ``axis_name``
    and reshapes to the template's shape; the output is replicated, so
    every process holds (and can read) the full tree.

    The jitted gather is cached per (mesh, axis, tree structure/shapes),
    so repeated eval/perplexity/generate calls hit one compilation
    instead of re-tracing a fresh lambda every time."""
    in_treedef = jax.tree.structure(sharded)
    in_shapes = tuple(
        (tuple(leaf.shape), np.dtype(leaf.dtype).str)
        for leaf in jax.tree.leaves(sharded)
    )
    out_shapes = tuple(
        (tuple(t.shape), np.dtype(t.dtype).str)
        for t in jax.tree.leaves(template)
    )
    cache_key = (mesh, axis_name, in_treedef, in_shapes,
                 jax.tree.structure(template), out_shapes)
    fn = _GATHER_CACHE.get(cache_key)
    if fn is not None:
        _GATHER_CACHE.move_to_end(cache_key)  # LRU: keep hot entries
    else:
        tmpl_struct = jax.tree.map(
            lambda t: jax.ShapeDtypeStruct(tuple(t.shape), t.dtype), template
        )
        mapped = jax.shard_map(
            lambda local: _unshard_rows(local, tmpl_struct, axis_name),
            mesh=mesh,
            in_specs=(
                jax.tree.map(
                    lambda leaf: P(axis_name) if jnp.ndim(leaf) >= 1 else P(),
                    sharded,
                ),
            ),
            out_specs=P(),
            check_vma=False,
        )
        fn = jax.jit(mapped)
        if len(_GATHER_CACHE) >= 8:  # bound: keys pin meshes/executables
            _GATHER_CACHE.popitem(last=False)  # evict least-recently-used
        _GATHER_CACHE[cache_key] = fn
    return fn(sharded)



def fsdp_full_params(
    sharded: Any, template: Any, mesh: Mesh, axis_name: str = DATA_AXIS
) -> Any:
    """Reassemble full parameters, choosing the cheap host fetch when
    every shard is process-local and the compiled all_gather
    (`fsdp_gather_params_compiled`) on multi-host meshes."""
    if all(
        getattr(leaf, "is_fully_addressable", True)
        for leaf in jax.tree.leaves(sharded)
    ):
        return fsdp_gather_params(sharded, template)
    return fsdp_gather_params_compiled(sharded, template, mesh, axis_name)



