"""Fully-sharded data parallelism (ZeRO-3 style) over the ``data`` axis.

Beyond the reference (its DP replicates the model on every rank,
train_dist.py:107 + tuto.md:216); this is the memory-scaled variant:
parameters, gradients, and optimizer state are all sharded 1/n per rank,
with parameters gathered just-in-time for compute.

TPU-first design: everything happens inside ONE compiled shard_map
program per step —

- each leaf is stored flattened and padded to ``(n, k)``, sharded
  ``P(axis)`` (rank r holds row r: 1/n of the leaf);
- forward/backward: ``all_gather`` (tiled) un-shards each leaf to its
  original shape, XLA overlapping the gathers with compute;
- gradients: flat-pad then ``psum_scatter`` (XLA ReduceScatter) /n — each
  rank reduces exactly its shard, wire cost identical to the allreduce
  the replicated path pays (RS + AG == allreduce, tuto.md:354's identity);
- update: the optimizer's elementwise pytree update runs on the local
  (1, k) shards, so its state (momentum/adam moments) is born sharded.

Padding is benign: padded grads are zero, so padded param/opt entries
stay exactly zero under SGD/momentum/AdamW.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.data_parallel import DATA_AXIS, _pmean_float_leaves
from tpu_dist.utils.tree import pad_to_multiple


def _pad_rows(flat: jax.Array, n: int) -> jax.Array:
    return pad_to_multiple(flat, n).reshape(n, -1)


# Shared building blocks of the ZeRO family (used by both ZeRO-3 and
# ZeRO-1 steps — keep them in one place so a fix applies to both paths).


def _unshard_rows(rows: Any, template: Any, axis_name: str) -> Any:
    """all_gather each local (1, k) row back into its full logical leaf
    (inside shard_map)."""

    def un(s, t):
        full = lax.all_gather(s, axis_name, axis=0, tiled=True)
        return full.reshape(-1)[: math.prod(t.shape)].reshape(t.shape)

    return jax.tree.map(un, rows, template)


def _reduce_scatter_grads(grads: Any, n: int, axis_name: str) -> Any:
    """Flat-pad each grad to (n, k) then ReduceScatter / n: rank r
    reduces exactly its row (inside shard_map)."""
    return jax.tree.map(
        lambda g: lax.psum_scatter(
            _pad_rows(jnp.ravel(g), n), axis_name,
            scatter_dimension=0, tiled=True,
        )
        / n,
        grads,
    )


def _compress_setup(grad_compress, grad_pmean_axes, builder: str):
    """Parse/validate the compressed-reduce-scatter config for a ZeRO
    builder (config-parse time, not trace time)."""
    from tpu_dist.comm import compress as compress_mod

    ccfg = compress_mod.parse(grad_compress)
    if ccfg is not None and grad_pmean_axes:
        compress_mod.refuse_model_axes(
            builder,
            grad_pmean_axes,
            rules="grad_pmean_axes (the TP gradient contract)",
        )
    return ccfg, ccfg is not None and ccfg.error_feedback


def _compressed_gshards(grads, opt_state, ccfg, wrap_ef, n, axis_name):
    """The gradient hop of a ZeRO step: exact ``psum_scatter`` (ccfg
    None) or the bucketed quantized reduce-scatter with error feedback
    (`comm.compress.reduce_scatter_rows`).  Returns ``(gshards,
    inner_opt_state, new_ef_or_None)`` — gshards in the per-leaf (1, k)
    row format either way (inside shard_map)."""
    if ccfg is None:
        return _reduce_scatter_grads(grads, n, axis_name), opt_state, None
    from tpu_dist.comm import compress as compress_mod

    plan = compress_mod.FlatPlan(grads, n, ccfg)
    res = opt_state["ef"]["residual"][0] if wrap_ef else None
    local, new_res, stats = compress_mod.reduce_scatter_rows(
        plan.to_rows(grads), res, plan, axis_name
    )
    gshards = plan.shard_rows(local / n)
    inner = opt_state["opt"] if wrap_ef else opt_state
    new_ef = (
        {"residual": new_res[None], "err": stats["err"]} if wrap_ef else None
    )
    return gshards, inner, new_ef


def _accumulate_grads(loss_grad_fn, params, batch, key, accum_steps: int):
    """Microbatch gradient accumulation for the sharded step builders —
    the stateless adapter over the shared scan
    (`data_parallel.accumulate_microbatches`, one contract for DP and
    ZeRO).  ``loss_grad_fn(full, micro_batch, key) -> ((loss, aux),
    grads)`` on FULL logical params; returns ``(mean_grads, mean_loss,
    aux)``."""
    from tpu_dist.parallel.data_parallel import accumulate_microbatches

    def gm(p, _state, mb, k):
        (loss, aux), g = loss_grad_fn(p, mb, k)
        return g, loss, _state, aux

    grads, loss, _, aux = accumulate_microbatches(
        gm, params, None, batch, key, accum_steps
    )
    return grads, loss, aux


def _apply_grad_contract(grads, loss, aux, axis_name, grad_pmean_axes):
    """The TP-composition tail shared by the ZeRO step builders: pmean
    grads over the extra model axes (the tensor-parallel gradient
    contract — the model-axis mean of a model-sharded loss's grads
    equals the dense gradient), then reduce loss/aux over ALL axes so
    their replicated out_specs are honest."""
    if grad_pmean_axes:
        grads = jax.tree.map(lambda g: lax.pmean(g, grad_pmean_axes), grads)
    all_axes = (axis_name, *grad_pmean_axes)
    return grads, lax.pmean(loss, all_axes), _pmean_float_leaves(aux, all_axes)


def _batch_in_spec(batch_spec, axis_name: str):
    """The batch partition spec (default: leading axis over the data
    axis) — one definition for both ZeRO builders."""
    return batch_spec if batch_spec is not None else P(axis_name)


def _spec_of(axis_name: str):
    """Per-leaf partition spec: (n, k) leaves sharded over the axis,
    scalar leaves (e.g. a schedule step counter) replicated."""
    return lambda leaf: P(axis_name) if jnp.ndim(leaf) >= 1 else P()


def _commit_scalars(tree: Any, mesh: Mesh) -> Any:
    """Commit scalar leaves (step counters) to the mesh, replicated:
    uncommitted single-device scalars round-trip through sharded
    checkpoints as committed device-0 arrays, which then clash with the
    mesh-wide step at dispatch."""
    return jax.tree.map(
        lambda l: l
        if jnp.ndim(l) >= 1
        else jax.device_put(l, NamedSharding(mesh, P())),
        tree,
    )


def fsdp_shard_params(params: Any, mesh: Mesh, axis_name: str = DATA_AXIS) -> Any:
    """Shard a full parameter pytree: every leaf becomes an ``(n, k)``
    array sharded ``P(axis_name)`` (row r on rank r, zero-padded)."""
    n = mesh.shape[axis_name]
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(
        lambda p: jax.device_put(_pad_rows(jnp.ravel(p), n), sharding), params
    )


def fsdp_gather_params(sharded: Any, template: Any) -> Any:
    """Reassemble full parameters from FSDP shards (host-side: eval,
    checkpointing).  ``template`` supplies the original shapes/dtypes.

    Single-host only: shards living on another process's devices cannot
    be fetched here — on a multi-host pod, checkpoint the sharded arrays
    directly (orbax handles distributed arrays) or gather inside a
    compiled program."""
    import numpy as np

    for leaf in jax.tree.leaves(sharded):
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            raise RuntimeError(
                "fsdp_gather_params: shards span non-addressable devices "
                "(multi-host mesh) — checkpoint the sharded pytree with "
                "orbax, or all_gather inside a jitted fn instead"
            )
    return jax.tree.map(
        lambda s, t: jnp.asarray(np.asarray(s).reshape(-1)[: math.prod(t.shape)])
        .reshape(t.shape)
        .astype(t.dtype),
        sharded,
        template,
    )


def _sharded_update_fn(optimizer, builder: str):
    """The optimizer update to run on flat-padded PER-RANK rows, as
    ``fn(params, grads, state, axis_name)``.

    An optimizer advertising ``shard_update`` (e.g. `clip_by_global_norm`,
    which psums squared shard norms to the true global norm) is used
    as-is; otherwise the plain update is valid only when each element's
    update depends on its own history alone — whole-tensor statistics
    (adafactor's factoring/RMS clipping) would silently differ per world
    size, so non-elementwise optimizers without a sharded form are
    refused loudly."""
    sharded = getattr(optimizer, "shard_update", None)
    if sharded is not None:
        return sharded
    if not getattr(optimizer, "elementwise", True):
        raise ValueError(
            f"{builder} requires an elementwise optimizer (sgd/adamw) or "
            "one with a shard_update (clip_by_global_norm provides one); "
            "this optimizer carries whole-tensor statistics that per-rank "
            "shards would compute differently at every world size"
        )
    return lambda params, grads, state, _axis: optimizer.update(
        params, grads, state
    )


_GATHER_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def fsdp_gather_params_compiled(
    sharded: Any, template: Any, mesh: Mesh, axis_name: str = DATA_AXIS
) -> Any:
    """Reassemble full parameters INSIDE a compiled program — the
    multi-host-safe sibling of `fsdp_gather_params` (which fetches shard
    bytes to one host and raises when shards live on another process's
    devices).  Each (n, k) leaf all-gathers its rows over ``axis_name``
    and reshapes to the template's shape; the output is replicated, so
    every process holds (and can read) the full tree.

    The jitted gather is cached per (mesh, axis, tree structure/shapes),
    so repeated eval/perplexity/generate calls hit one compilation
    instead of re-tracing a fresh lambda every time."""
    in_treedef = jax.tree.structure(sharded)
    in_shapes = tuple(
        (tuple(leaf.shape), np.dtype(leaf.dtype).str)
        for leaf in jax.tree.leaves(sharded)
    )
    out_shapes = tuple(
        (tuple(t.shape), np.dtype(t.dtype).str)
        for t in jax.tree.leaves(template)
    )
    cache_key = (mesh, axis_name, in_treedef, in_shapes,
                 jax.tree.structure(template), out_shapes)
    fn = _GATHER_CACHE.get(cache_key)
    if fn is not None:
        _GATHER_CACHE.move_to_end(cache_key)  # LRU: keep hot entries
    else:
        tmpl_struct = jax.tree.map(
            lambda t: jax.ShapeDtypeStruct(tuple(t.shape), t.dtype), template
        )
        mapped = jax.shard_map(
            lambda local: _unshard_rows(local, tmpl_struct, axis_name),
            mesh=mesh,
            in_specs=(
                jax.tree.map(
                    lambda leaf: P(axis_name) if jnp.ndim(leaf) >= 1 else P(),
                    sharded,
                ),
            ),
            out_specs=P(),
            check_vma=False,
        )
        fn = jax.jit(mapped)
        if len(_GATHER_CACHE) >= 8:  # bound: keys pin meshes/executables
            _GATHER_CACHE.popitem(last=False)  # evict least-recently-used
        _GATHER_CACHE[cache_key] = fn
    return fn(sharded)


def make_fsdp_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    params: Any,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    grad_pmean_axes: tuple[str, ...] = (),
    batch_spec=None,
    accum_steps: int = 1,
    grad_compress=None,
):
    """Build the compiled FSDP train step.

    Args:
      loss_fn: ``loss_fn(params, batch, key) -> (loss, aux)`` on the local
        batch shard (same contract as `make_train_step`).
      optimizer: `tpu_dist.train.optim.Optimizer`; its state is created
        over the SHARDED leaves, so it is 1/n per rank by construction.
      mesh: mesh whose ``axis_name`` axis shards batch AND model state.
        May have MORE axes than ``axis_name`` — params/opt state are then
        replicated over the extra axes and ``loss_fn`` is free to use
        them (e.g. tensor parallelism over a 'model' axis).
      params: the full initial parameter pytree (consumed: returned
        sharded).
      grad_pmean_axes: extra mesh axes to pmean gradients over BEFORE
        the ``axis_name`` reduce-scatter.  For FSDP x TP composition
        pass ``('model',)``: per the TP gradient contract
        (test_tensor_parallel.py), the model-axis mean of
        `loss_tensor_parallel` grads equals the dense gradient.
      batch_spec: PartitionSpec for the batch (default ``P(axis_name)``)
        — e.g. ``P('data', 'model')`` for the Megatron-SP layout, whose
        token windows shard over batch AND sequence.
      accum_steps: microbatch gradient accumulation (``lax.scan`` with a
        gradient-sum carry, like the replicated DP step): activations
        live one microbatch at a time; the reduce-scatter still fires
        once per step on the mean gradient.  Params stay gathered for
        the whole step (the per-microbatch re-gather trade is left to
        XLA's scheduler).

    Returns ``(step, sharded_params, opt_state)`` with
    ``step(sharded_params, opt_state, batch, key) -> (sharded_params,
    opt_state, loss, aux)`` — batch sharded on its leading axis, loss
    replicated (pmean), params/opt-state permanently sharded.

    ``grad_compress`` (a `comm.compress.CompressConfig` or spec string)
    swaps the gradient ``psum_scatter`` for the bucketed quantized
    reduce-scatter with error feedback (`comm.compress`): each rank
    ships 1-byte (or bf16) bucket chunks instead of f32 and dequantizes
    into its exact shard rows.  The returned ``opt_state`` then becomes
    ``{"opt": <state>, "ef": <residual>}``; data-axis only (incompatible
    with ``grad_pmean_axes``).
    """
    n = mesh.shape[axis_name]
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    ccfg, wrap_ef = _compress_setup(
        grad_compress, grad_pmean_axes, "make_fsdp_train_step"
    )
    opt_update = _sharded_update_fn(optimizer, "make_fsdp_train_step")
    template = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
    )
    sharded_params = fsdp_shard_params(params, mesh, axis_name)
    opt_state = _commit_scalars(optimizer.init(sharded_params), mesh)
    if wrap_ef:
        from tpu_dist.comm import compress as compress_mod

        opt_state = {
            "opt": opt_state,
            "ef": compress_mod.init_ef_state(
                template, n, ccfg, mesh, axis_name
            ),
        }
    vg = jax.value_and_grad(loss_fn, has_aux=True)

    def spmd_step(local_shards, opt_state, batch, key):
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
        full = _unshard_rows(local_shards, template, axis_name)
        if accum_steps == 1:
            (loss, aux), grads = vg(full, batch, key)
        else:
            grads, loss, aux = _accumulate_grads(
                vg, full, batch, key, accum_steps
            )
        grads, loss, aux = _apply_grad_contract(
            grads, loss, aux, axis_name, grad_pmean_axes
        )
        gshards, inner_opt, new_ef = _compressed_gshards(
            grads, opt_state, ccfg, wrap_ef, n, axis_name
        )
        new_shards, new_opt = opt_update(
            local_shards, gshards, inner_opt, axis_name
        )
        if wrap_ef:
            new_opt = {"opt": new_opt, "ef": new_ef}
        return new_shards, new_opt, loss, aux

    p_specs = jax.tree.map(_spec_of(axis_name), sharded_params)
    o_specs = jax.tree.map(_spec_of(axis_name), opt_state)
    mapped = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(
            p_specs, o_specs, _batch_in_spec(batch_spec, axis_name), P(),
        ),
        out_specs=(p_specs, o_specs, P(), P()),
        check_vma=False,
    )
    step = jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
    return step, sharded_params, opt_state


def fsdp_full_params(
    sharded: Any, template: Any, mesh: Mesh, axis_name: str = DATA_AXIS
) -> Any:
    """Reassemble full parameters, choosing the cheap host fetch when
    every shard is process-local and the compiled all_gather
    (`fsdp_gather_params_compiled`) on multi-host meshes."""
    if all(
        getattr(leaf, "is_fully_addressable", True)
        for leaf in jax.tree.leaves(sharded)
    ):
        return fsdp_gather_params(sharded, template)
    return fsdp_gather_params_compiled(sharded, template, mesh, axis_name)


def make_zero1_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    params: Any,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    accum_steps: int = 1,
    grad_pmean_axes: tuple[str, ...] = (),
    batch_spec=None,
    grad_compress=None,
):
    """ZeRO-1: replicated parameters, SHARDED optimizer state — the
    middle point between replicated DP and FSDP/ZeRO-3.

    Forward/backward run on the full replicated params (none of ZeRO-3's
    per-step parameter all_gathers); gradients are reduce-scattered so
    each rank holds one (1, k) row of every padded-flat leaf and updates
    only its row — optimizer state (momentum/Adam moments) is therefore
    born sharded, 1/n memory per rank; the updated rows all_gather back
    into full parameters.  RS + shard-update + AG costs the same wire
    traffic as the replicated path's allreduce (the tuto.md:354
    identity), and the elementwise optimizer math makes the trajectory
    identical to replicated DP to fp tolerance.  (ZeRO-2's gradient
    sharding is implicit here: the reduce-scatter means full gradients
    never persist — XLA frees them within the step.)

    ``accum_steps``, ``grad_pmean_axes``, and ``batch_spec`` carry the
    same contracts as `make_fsdp_train_step` — in particular TP×ZeRO-1:
    pass ``grad_pmean_axes=('model',)`` with a tensor-parallel loss on a
    (data × model) mesh (and ``batch_spec=P('data','model')`` for the
    SP layout) and the optimizer state shards over 'data' while the
    loss runs model-sharded.

    Returns ``(step, replicated_params, sharded_opt_state)`` with
    ``step(params, opt_state, batch, key) -> (params, opt_state, loss,
    aux)`` — params replicated, batch sharded on its leading axis.
    """
    n = mesh.shape[axis_name]
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    ccfg, wrap_ef = _compress_setup(
        grad_compress, grad_pmean_axes, "make_zero1_train_step"
    )
    opt_update = _sharded_update_fn(optimizer, "make_zero1_train_step")
    vg = jax.value_and_grad(loss_fn, has_aux=True)
    template = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
    )
    replicated = jax.tree.map(
        lambda p: jax.device_put(jnp.asarray(p), NamedSharding(mesh, P())),
        params,
    )
    # Optimizer state over the (1, k)-per-rank row shards.
    opt_state = _commit_scalars(
        optimizer.init(fsdp_shard_params(params, mesh, axis_name)), mesh
    )
    if wrap_ef:
        from tpu_dist.comm import compress as compress_mod

        opt_state = {
            "opt": opt_state,
            "ef": compress_mod.init_ef_state(
                template, n, ccfg, mesh, axis_name
            ),
        }

    def local_rows(full):
        """This rank's (1, k) row of each padded-flat leaf."""
        r = lax.axis_index(axis_name)
        return jax.tree.map(
            lambda p: lax.dynamic_slice_in_dim(
                _pad_rows(jnp.ravel(p), n), r, 1, axis=0
            ),
            full,
        )

    def spmd_step(full_params, opt_state, batch, key):
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
        if accum_steps == 1:
            (loss, aux), grads = vg(full_params, batch, key)
        else:
            grads, loss, aux = _accumulate_grads(
                vg, full_params, batch, key, accum_steps
            )
        grads, loss, aux = _apply_grad_contract(
            grads, loss, aux, axis_name, grad_pmean_axes
        )
        gshards, inner_opt, new_ef = _compressed_gshards(
            grads, opt_state, ccfg, wrap_ef, n, axis_name
        )
        new_rows, new_opt = opt_update(
            local_rows(full_params), gshards, inner_opt, axis_name
        )
        if wrap_ef:
            new_opt = {"opt": new_opt, "ef": new_ef}
        return (
            _unshard_rows(new_rows, template, axis_name),
            new_opt,
            loss,
            aux,
        )

    o_specs = jax.tree.map(_spec_of(axis_name), opt_state)
    mapped = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(
            P(), o_specs, _batch_in_spec(batch_spec, axis_name), P(),
        ),
        out_specs=(P(), o_specs, P(), P()),
        check_vma=False,
    )
    step = jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
    return step, replicated, opt_state
