"""Expert parallelism — a mixture-of-experts MLP sharded one expert per
rank, with all_to_all token dispatch.

Listed as a non-goal in SURVEY.md §2d (the reference has no MoE);
implemented so the expert-parallel row of the parallelism table is a
working configuration.  Scheme (top-1 routing, capacity-bounded —
Switch-Transformer style):

1. every rank routes its LOCAL tokens: ``argmax(x @ gate_w)`` picks an
   expert, softmax gives the combine weight;
2. tokens are packed into a ``(n_experts, capacity, d)`` dispatch buffer
   (position = running count within the expert; overflow beyond capacity
   is dropped — standard MoE behavior, surfaced in the aux stats);
3. ONE ``all_to_all`` ships row e of every rank to rank e (the expert's
   owner), which runs its expert MLP on all arriving tokens;
4. a second ``all_to_all`` ships results back, and tokens are combined
   into their original positions scaled by the gate weight (dropped
   tokens contribute zero — use MoE layers residually).

Everything is static-shaped (capacity bound), so the whole layer compiles
into the surrounding SPMD program; both all_to_alls ride ICI.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import all_to_all

EXPERT_AXIS = "expert"


def capacity_for(tokens_per_rank: int, n_experts: int, factor: float = 1.25) -> int:
    """Per-expert per-source-rank slot count."""
    return max(1, math.ceil(tokens_per_rank / n_experts * factor))


def moe_mlp(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Top-1 MoE MLP inside shard_map over ``axis_name``.

    Args:
      x: local token shard ``(T, d)`` (tokens sharded over the same axis).
      gate_w: replicated router weights ``(d, n_experts)``.
      w_up, w_down: THIS rank's expert parameters ``(d, hidden)`` /
        ``(hidden, d)`` (i.e. the local slice of expert-stacked weights).

    Returns ``(y, stats)`` with ``y: (T, d)`` — the gated expert outputs
    (zeros for dropped tokens) — and routing stats (fraction dropped,
    per-expert load).
    """
    n = lax.axis_size(axis_name)
    T, d = x.shape
    cap = capacity_for(T, n, capacity_factor)

    scores = x @ gate_w  # (T, n)
    probs = jax.nn.softmax(scores, axis=-1)
    assign = jnp.argmax(scores, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(assign, n, dtype=jnp.int32)  # (T, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # (T, n), -1 elsewhere
    pos_in_expert = pos.max(axis=1)  # (T,)
    kept = pos_in_expert < cap
    load = onehot.sum(axis=0)  # tokens per expert from this rank

    # Pack: dispatch[e, c] = the token assigned to expert e at slot c.
    dispatch = jnp.zeros((n, cap, d), x.dtype)
    dispatch = dispatch.at[
        assign, jnp.clip(pos_in_expert, 0, cap - 1)
    ].add(jnp.where(kept[:, None], x, 0.0))

    # Ship: row e -> rank e.  Arrives as (n_src, cap, d) stacked by source.
    arriving = all_to_all(dispatch, axis_name, split_axis=0, concat_axis=0)
    flat = arriving.reshape(n * cap, d)
    hidden = activation(flat @ w_up)
    processed = (hidden @ w_down).reshape(n, cap, d)

    # Ship back: row s of the result returns to source rank s, stacked by
    # expert again: returned[e, c] = expert e's output for my slot c.
    returned = all_to_all(processed, axis_name, split_axis=0, concat_axis=0)

    # Combine into original token positions.
    out_tokens = returned[assign, jnp.clip(pos_in_expert, 0, cap - 1)]
    y = jnp.where(kept[:, None], out_tokens * gate[:, None], 0.0)
    stats = {
        "dropped_fraction": 1.0 - kept.mean(),
        "local_load": load,
    }
    return y, stats


def stack_expert_params(experts: list[dict[str, Any]]) -> dict[str, Any]:
    """Stack per-expert param dicts on a leading axis (shard with
    ``P('expert')`` entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(experts)
