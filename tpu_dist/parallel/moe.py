"""Expert parallelism — a mixture-of-experts MLP sharded one expert per
rank, with all_to_all token dispatch.

Listed as a non-goal in SURVEY.md §2d (the reference has no MoE);
implemented so the expert-parallel row of the parallelism table is a
working configuration.  Scheme (top-1 routing, capacity-bounded —
Switch-Transformer style):

1. every rank routes its LOCAL tokens: ``argmax(x @ gate_w)`` picks an
   expert, softmax gives the combine weight;
2. tokens are packed into a ``(n_experts, capacity, d)`` dispatch buffer
   (position = running count within the expert; overflow beyond capacity
   is dropped — standard MoE behavior, surfaced in the aux stats);
3. ONE ``all_to_all`` ships row e of every rank to rank e (the expert's
   owner), which runs its expert MLP on all arriving tokens;
4. a second ``all_to_all`` ships results back, and tokens are combined
   into their original positions scaled by the gate weight (dropped
   tokens contribute zero — use MoE layers residually).

Everything is static-shaped (capacity bound), so the whole layer compiles
into the surrounding SPMD program; both all_to_alls ride ICI.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import all_to_all

EXPERT_AXIS = "expert"


def capacity_for(tokens_per_rank: int, n_experts: int, factor: float = 1.25) -> int:
    """Per-expert per-source-rank slot count."""
    return max(1, math.ceil(tokens_per_rank / n_experts * factor))


def _dispatch_process_combine(
    xv, assign, gate, w_up, w_down, axis_name, cap, activation
):
    """Shared MoE transport: pack ``(R, d)`` virtual tokens into the
    ``(n_experts, cap, d)`` dispatch buffer (cumulative-count slots,
    overflow dropped), ship with ONE all_to_all each way, run the local
    expert MLP, and return each virtual token's gated output (zeros when
    dropped) plus kept mask and per-expert load."""
    n = lax.axis_size(axis_name)
    d = xv.shape[-1]
    onehot = jax.nn.one_hot(assign, n, dtype=jnp.int32)  # (R, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    pos_in_expert = pos.max(axis=1)  # (R,)
    kept = pos_in_expert < cap
    load = onehot.sum(axis=0)

    dispatch = jnp.zeros((n, cap, d), xv.dtype)
    dispatch = dispatch.at[
        assign, jnp.clip(pos_in_expert, 0, cap - 1)
    ].add(jnp.where(kept[:, None], xv, 0.0))

    arriving = all_to_all(dispatch, axis_name, split_axis=0, concat_axis=0)
    flat = arriving.reshape(n * cap, d)
    hidden = activation(flat @ w_up)
    processed = (hidden @ w_down).reshape(n, cap, d)
    returned = all_to_all(processed, axis_name, split_axis=0, concat_axis=0)

    out_v = returned[assign, jnp.clip(pos_in_expert, 0, cap - 1)]
    yv = jnp.where(kept[:, None], out_v * gate[:, None], 0.0)
    return yv, kept, load


def moe_mlp(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Top-1 MoE MLP inside shard_map over ``axis_name``.

    Args:
      x: local token shard ``(T, d)`` (tokens sharded over the same axis).
      gate_w: replicated router weights ``(d, n_experts)``.
      w_up, w_down: THIS rank's expert parameters ``(d, hidden)`` /
        ``(hidden, d)`` (i.e. the local slice of expert-stacked weights).

    Returns ``(y, stats)`` with ``y: (T, d)`` — the gated expert outputs
    (zeros for dropped tokens) — and routing stats (fraction dropped,
    per-expert load).
    """
    n = lax.axis_size(axis_name)
    T, d = x.shape
    cap = capacity_for(T, n, capacity_factor)

    scores = x @ gate_w  # (T, n)
    probs = jax.nn.softmax(scores, axis=-1)
    assign = jnp.argmax(scores, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]

    y, kept, load = _dispatch_process_combine(
        x, assign, gate, w_up, w_down, axis_name, cap, activation
    )
    stats = {
        "dropped_fraction": jnp.mean(~kept),
        "local_load": load,
    }
    return y, stats


def moe_mlp_top2(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 2.0,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Top-2 MoE MLP (GShard-style) inside shard_map over ``axis_name``.

    Each token is sent to its two highest-probability experts with
    combine weights renormalized over the pair (``g1 + g2 = 1``).  The
    token's two placements are packed as ``2T`` virtual tokens — all
    first choices before all second choices, so first choices win
    capacity — through the same single-all_to_all-each-way transport as
    `moe_mlp`.  Default ``capacity_factor`` doubles to hold the second
    copies.

    ``stats`` additionally carries ``balance_loss``: the Switch/GShard
    load-balancing auxiliary ``n · Σ_e f_e · P_e`` (``f_e`` = fraction of
    tokens whose FIRST choice is e, ``P_e`` = mean router probability) —
    1.0 at perfect balance; add ``pmean(balance_loss) · λ`` to the
    training loss to keep experts utilized.
    """
    n = lax.axis_size(axis_name)
    T, d = x.shape
    cap = capacity_for(T, n, capacity_factor)

    scores = x @ gate_w
    probs = jax.nn.softmax(scores, axis=-1)
    top2_p, top2_e = lax.top_k(probs, 2)  # (T, 2)
    gates = top2_p / jnp.maximum(top2_p.sum(-1, keepdims=True), 1e-9)

    assign = jnp.concatenate([top2_e[:, 0], top2_e[:, 1]])  # (2T,)
    gate = jnp.concatenate([gates[:, 0], gates[:, 1]])
    xv = jnp.concatenate([x, x], axis=0)

    yv, kept, load = _dispatch_process_combine(
        xv, assign, gate, w_up, w_down, axis_name, cap, activation
    )
    y = yv[:T] + yv[T:]

    f = jax.nn.one_hot(top2_e[:, 0], n, dtype=jnp.float32).mean(axis=0)
    balance = n * jnp.sum(f * probs.mean(axis=0))
    stats = {
        "dropped_fraction": jnp.mean(~kept),
        "local_load": load,
        "balance_loss": balance,
    }
    return y, stats


def stack_expert_params(experts: list[dict[str, Any]]) -> dict[str, Any]:
    """Stack per-expert param dicts on a leading axis (shard with
    ``P('expert')`` entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(experts)
