"""Expert parallelism — a mixture-of-experts MLP sharded one expert per
rank, with all_to_all token dispatch.

Listed as a non-goal in SURVEY.md §2d (the reference has no MoE);
implemented so the expert-parallel row of the parallelism table is a
working configuration.  Scheme (top-1 routing, capacity-bounded —
Switch-Transformer style):

1. every rank routes its LOCAL tokens: ``argmax(x @ gate_w)`` picks an
   expert, softmax gives the combine weight;
2. tokens are packed into a ``(n_experts, capacity, d)`` dispatch buffer
   (position = running count within the expert; overflow beyond capacity
   is dropped — standard MoE behavior, surfaced in the aux stats);
3. ONE ``all_to_all`` ships row e of every rank to rank e (the expert's
   owner), which runs its expert MLP on all arriving tokens;
4. a second ``all_to_all`` ships results back, and tokens are combined
   into their original positions scaled by the gate weight (dropped
   tokens contribute zero — use MoE layers residually).

Everything is static-shaped (capacity bound), so the whole layer compiles
into the surrounding SPMD program; both all_to_alls ride ICI.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import all_to_all

EXPERT_AXIS = "expert"


def capacity_for(tokens_per_rank: int, n_experts: int, factor: float = 1.25) -> int:
    """Per-expert per-source-rank slot count."""
    return max(1, math.ceil(tokens_per_rank / n_experts * factor))


def _dispatch_process_combine(
    xv, assign, gate, w_up, w_down, axis_name, cap, activation
):
    """Shared MoE transport: pack ``(R, d)`` virtual tokens into the
    ``(n_experts, cap, d)`` dispatch buffer (cumulative-count slots,
    overflow dropped), ship with ONE all_to_all each way, run the local
    expert MLP, and return each virtual token's gated output (zeros when
    dropped) plus kept mask and per-expert load."""
    n = lax.axis_size(axis_name)
    d = xv.shape[-1]
    onehot = jax.nn.one_hot(assign, n, dtype=jnp.int32)  # (R, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    pos_in_expert = pos.max(axis=1)  # (R,)
    kept = pos_in_expert < cap
    load = onehot.sum(axis=0)

    dispatch = jnp.zeros((n, cap, d), xv.dtype)
    dispatch = dispatch.at[
        assign, jnp.clip(pos_in_expert, 0, cap - 1)
    ].add(jnp.where(kept[:, None], xv, 0.0))

    arriving = all_to_all(dispatch, axis_name, split_axis=0, concat_axis=0)
    flat = arriving.reshape(n * cap, d)
    hidden = activation(flat @ w_up)
    processed = (hidden @ w_down).reshape(n, cap, d)
    returned = all_to_all(processed, axis_name, split_axis=0, concat_axis=0)

    out_v = returned[assign, jnp.clip(pos_in_expert, 0, cap - 1)]
    yv = jnp.where(kept[:, None], out_v * gate[:, None], 0.0)
    return yv, kept, load


def moe_mlp(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Top-1 MoE MLP inside shard_map over ``axis_name``.

    Args:
      x: local token shard ``(T, d)`` (tokens sharded over the same axis).
      gate_w: replicated router weights ``(d, n_experts)``.
      w_up, w_down: THIS rank's expert parameters ``(d, hidden)`` /
        ``(hidden, d)`` (i.e. the local slice of expert-stacked weights).

    Returns ``(y, stats)`` with ``y: (T, d)`` — the gated expert outputs
    (zeros for dropped tokens) — and routing stats (fraction dropped,
    per-expert load).
    """
    n = lax.axis_size(axis_name)
    T, d = x.shape
    cap = capacity_for(T, n, capacity_factor)

    scores = x @ gate_w  # (T, n)
    probs = jax.nn.softmax(scores, axis=-1)
    assign = jnp.argmax(scores, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]

    y, kept, load = _dispatch_process_combine(
        x, assign, gate, w_up, w_down, axis_name, cap, activation
    )
    stats = {
        "dropped_fraction": jnp.mean(~kept),
        "local_load": load,
    }
    return y, stats


def moe_mlp_top2(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 2.0,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Top-2 MoE MLP (GShard-style) inside shard_map over ``axis_name``.

    Each token is sent to its two highest-probability experts with
    combine weights renormalized over the pair (``g1 + g2 = 1``).  The
    token's two placements are packed as ``2T`` virtual tokens — all
    first choices before all second choices, so first choices win
    capacity — through the same single-all_to_all-each-way transport as
    `moe_mlp`.  Default ``capacity_factor`` doubles to hold the second
    copies.

    ``stats`` additionally carries ``balance_loss``: the Switch/GShard
    load-balancing auxiliary ``n · Σ_e f_e · P_e`` (``f_e`` = fraction of
    tokens whose FIRST choice is e, ``P_e`` = mean router probability) —
    1.0 at perfect balance; add ``pmean(balance_loss) · λ`` to the
    training loss to keep experts utilized.
    """
    n = lax.axis_size(axis_name)
    T, d = x.shape
    cap = capacity_for(T, n, capacity_factor)

    scores = x @ gate_w
    probs = jax.nn.softmax(scores, axis=-1)
    top2_p, top2_e = lax.top_k(probs, 2)  # (T, 2)
    gates = top2_p / jnp.maximum(top2_p.sum(-1, keepdims=True), 1e-9)

    assign = jnp.concatenate([top2_e[:, 0], top2_e[:, 1]])  # (2T,)
    gate = jnp.concatenate([gates[:, 0], gates[:, 1]])
    xv = jnp.concatenate([x, x], axis=0)

    yv, kept, load = _dispatch_process_combine(
        xv, assign, gate, w_up, w_down, axis_name, cap, activation
    )
    y = yv[:T] + yv[T:]

    f = jax.nn.one_hot(top2_e[:, 0], n, dtype=jnp.float32).mean(axis=0)
    balance = n * jnp.sum(f * probs.mean(axis=0))
    stats = {
        "dropped_fraction": jnp.mean(~kept),
        "local_load": load,
        "balance_loss": balance,
    }
    return y, stats


def moe_mlp_expert_choice(
    x: jax.Array,
    gate_w: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 2.0,
    activation=jax.nn.gelu,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Expert-choice MoE MLP (Zhou et al. 2022) inside shard_map: the
    EXPERTS pick their tokens, not the other way around.

    Each expert takes its top-``C`` tokens over the GLOBAL batch by
    router score (``C = T_local · capacity_factor``), so every expert is
    perfectly load-balanced by construction — no balance-loss auxiliary,
    no capacity overflow drops (a token is "dropped" only if no expert
    chose it, which top-scoring tokens never are; it then contributes
    zero, so use the layer residually like the others).

    CAUSALITY CAVEAT: the top-C competition conditions every token's
    routing on the WHOLE batch — including future positions — so this
    layer is for encoder / non-autoregressive models (the paper's
    setting).  A causal LM trained with it would leak future
    information through the routing decisions; that is why
    `TransformerLM(moe_experts=)` uses token-choice top-2, not this.

    Wire pattern (all static shapes): scores all_gather (tiny, T×n),
    identical global top-C on every rank; one ``all_to_all`` ships each
    rank's owned slots of every expert's token list to the expert (rows
    summed on arrival — non-owned slots are zero); the expert MLP runs
    on its (C, d) pick; one ``all_gather`` returns every expert's
    outputs and each rank combines its own tokens weighted by the
    router's softmax-over-experts gate.

    Args/returns mirror `moe_mlp` (stats: total picks owned by this
    rank, mean experts-per-token coverage over this rank's tokens).
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    T, d = x.shape
    # the pick pool is the n·T global tokens — clamp so a generous
    # capacity_factor (or a 1-rank axis) cannot ask top_k for more
    # entries than exist
    cap = max(1, min(int(T * capacity_factor), n * T))

    scores = x @ gate_w  # (T, n) local
    probs = jax.nn.softmax(scores, axis=-1)  # gates: softmax over experts
    # identical global score table on every rank (tiny: T_global × n)
    probs_g = lax.all_gather(probs, axis_name, axis=0, tiled=True)
    Tg = n * T

    # expert e's picks: top-cap GLOBAL token ids by its column, computed
    # identically everywhere (deterministic)
    top_w, top_idx = lax.top_k(probs_g.T, cap)  # (n, cap) each

    # dispatch: this rank owns global tokens [r·T, (r+1)·T); fill the
    # slots whose chosen token lives here, zero elsewhere
    owner = top_idx // T  # (n, cap) source rank of each pick
    local_tok = jnp.clip(top_idx - r * T, 0, T - 1)
    mine = owner == r
    dispatch = jnp.where(mine[:, :, None], x[local_tok], 0.0)  # (n, cap, d)
    arriving = all_to_all(dispatch, axis_name, split_axis=0, concat_axis=0)
    # (n, cap, d): source ranks' partial rows of MY expert — sum fills
    # every slot exactly once (each slot owned by one rank)
    picked = arriving.reshape(n, cap, d).sum(axis=0)  # (cap, d)

    hidden = activation(picked @ w_up)
    out_local = hidden @ w_down  # (cap, d) — my expert's outputs
    # every expert's outputs everywhere (n · cap · d, same order as
    # top_idx rows)
    out_all = lax.all_gather(out_local, axis_name, axis=0)  # (n, cap, d)

    # combine: token t's output = Σ over (e, slot) picks of t:
    #   gate[t, e] · out_all[e, slot]
    flat_idx = top_idx.reshape(-1)  # (n·cap,) global token ids
    flat_out = out_all.reshape(n * cap, d)
    flat_gate = top_w.reshape(-1)  # == probs_g[token, expert] of the pick
    # scatter-add into the GLOBAL token axis, then slice my window —
    # cheaper: mask to my window and scatter into (T, d)
    in_mine = (flat_idx >= r * T) & (flat_idx < (r + 1) * T)
    local_ids = jnp.clip(flat_idx - r * T, 0, T - 1)
    y = jnp.zeros((T, d), x.dtype).at[local_ids].add(
        jnp.where(in_mine[:, None], flat_gate[:, None] * flat_out, 0.0)
    )
    # coverage: how many experts picked each of MY tokens (mean)
    cover = jnp.zeros((T,), jnp.float32).at[local_ids].add(
        jnp.where(in_mine, 1.0, 0.0)
    )
    stats = {
        "local_pick_count": jnp.sum(mine),
        "mean_experts_per_token": cover.mean(),
    }
    return y, stats


def stack_expert_params(experts: list[dict[str, Any]]) -> dict[str, Any]:
    """Stack per-expert param dicts on a leading axis (shard with
    ``P('expert')`` entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(experts)
