"""Collective matmuls: communication overlapped INTO the matmul.

The reference's didactic gap is that its gradient averaging is a blocking
per-parameter collective after the computation (train_dist.py:94-100;
tuto.md:319-320 names overlap as what real DDP adds).  The TPU-native
version of "overlap communication with computation" goes further than
bucketing: for tensor-parallel layers whose activations are
sequence-sharded (the Megatron-SP layout), the all-gather/reduce-scatter
around a sharded matmul can be decomposed into a ``ppermute`` ring whose
hops ride ICI *while* the MXU chews the chunk that already arrived — the
"collective matmul" pattern of the scaling playbook.

Structure, not scheduling: these functions EXPOSE the overlap by making
each ring hop independent of the chunk-matmul issued alongside it; XLA's
async collectives + latency-hiding scheduler do the actual interleaving
on TPU (on the CPU-sim mesh they are merely correct).

Layout convention: the FIRST axis of an activation is the token axis and
is the sharded one; gathered outputs are rank-major along it.  The pair

- `allgather_matmul`   — ``all_gather(x) @ w`` without waiting for the
  gather: rank r multiplies its resident chunk while the ring rotates
  the others in (n chunk-matmuls, n-1 hops).
- `matmul_reduce_scatter` — ``reduce_scatter(x @ w)`` without
  materializing the full product: the accumulator for each output chunk
  travels the ring, gaining one rank's chunk-matmul per hop (owner adds
  last).

compose into `tp_mlp_overlapped`, the sequence-parallel Megatron MLP:
activations enter and leave sequence-sharded (1/n of the activation
memory of `tp_mlp`), and neither collective is a standalone barrier.

Cross-checked against ``all_gather``/``psum_scatter`` and the dense
computation in tests/test_overlap.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm as _ring_perm
from tpu_dist.parallel.tensor_parallel import MODEL_AXIS, shard_dim


def allgather_matmul(
    x_shard: jax.Array,
    w: jax.Array,
    axis_name: str = MODEL_AXIS,
    *,
    bidirectional: bool = False,
) -> jax.Array:
    """``all_gather(x_shard, tiled) @ w`` with the gather decomposed into
    a ppermute ring overlapped with per-chunk matmuls.

    ``x_shard``: (rows_l, d) — this rank's row chunk (rank-major order).
    ``w``: (d, f) — typically a column-parallel weight shard, but any
    per-rank right operand works.  Returns (n * rows_l, f): the full-row
    product every rank can use locally.

    Step i multiplies the chunk that originated at rank ``r - i`` (it has
    hopped i times) into its output slot while the ring forwards it on —
    the matmul for hop i and the permute for hop i+1 have no data
    dependence, which is what lets the scheduler overlap them.

    ``bidirectional=True`` splits each chunk's rows in half and sends one
    half around each ring direction: a physical torus link carries both
    directions at once, so each hop ships half the bytes in the same
    wall-clock — ~2x effective gather bandwidth (same total traffic; on
    the CPU-sim mesh it is merely equivalent).  Requires even rows.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x_shard @ w
    if bidirectional:
        rows_l = x_shard.shape[0]
        if rows_l % 2:
            raise ValueError(
                f"bidirectional needs even rows per rank, got {rows_l}"
            )
        h = rows_l // 2
        right = _allgather_matmul_dir(x_shard[:h], w, axis_name, +1)
        left = _allgather_matmul_dir(x_shard[h:], w, axis_name, -1)
        # interleave: global rows = [chunk0 top, chunk0 bottom, chunk1 ...]
        f = w.shape[1]
        return jnp.concatenate(
            [right.reshape(n, h, f), left.reshape(n, h, f)], axis=1
        ).reshape(n * rows_l, f)
    return _allgather_matmul_dir(x_shard, w, axis_name, +1)


def _allgather_matmul_dir(x_shard, w, axis_name, direction):
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    rows_l = x_shard.shape[0]
    perm = (
        _ring_perm(n)
        if direction > 0
        else [(i, (i - 1) % n) for i in range(n)]
    )
    out = jnp.zeros((n * rows_l, w.shape[1]), jnp.result_type(x_shard, w))
    chunk = x_shard
    for i in range(n):
        # send-right rings hold the chunk from rank r-i after i hops;
        # send-left rings the chunk from rank r+i
        src = (r - direction * i) % n
        out = lax.dynamic_update_slice_in_dim(
            out, (chunk @ w).astype(out.dtype), src * rows_l, 0
        )
        if i < n - 1:  # last chunk needs no forwarding
            chunk = lax.ppermute(chunk, axis_name, perm)
    return out


def matmul_reduce_scatter(
    x: jax.Array,
    w: jax.Array,
    axis_name: str = MODEL_AXIS,
    *,
    bidirectional: bool = False,
) -> jax.Array:
    """``psum_scatter(x @ w)`` over row chunks, with the ring reduction
    overlapped with the per-chunk matmuls.

    ``x``: (rows, d_l) — rows divisible by the axis size; typically the
    hidden activations entering a row-parallel weight shard ``w``
    (d_l, f).  Returns (rows / n, f): row chunk r of the full product,
    summed over every rank's partial contribution.

    The accumulator for chunk c is SEEDED at rank c-1 (each rank r seeds
    chunk r+1) and travels left, collecting one rank's chunk-matmul per
    hop; the owner contributes last, so after n-1 hops rank r holds
    exactly chunk r.  Each hop's permute is independent of the matmul
    for the incoming chunk.

    ``bidirectional=True`` halves each traveling accumulator: the top
    half-rows of every chunk reduce around the left ring, the bottom
    half around the right — both torus directions carry at once (~2x
    effective reduction bandwidth; same math).  Requires even rows/n.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x @ w
    rows = x.shape[0]
    if rows % n:
        raise ValueError(f"rows {rows} not divisible by axis size {n}")
    rows_l = rows // n
    if bidirectional:
        if rows_l % 2:
            raise ValueError(
                f"bidirectional needs even rows per chunk, got {rows_l}"
            )
        h = rows_l // 2
        top = _mrs_dir(x, w, axis_name, -1, offset=0, size=h)
        bot = _mrs_dir(x, w, axis_name, +1, offset=h, size=h)
        return jnp.concatenate([top, bot], axis=0)
    return _mrs_dir(x, w, axis_name, -1, offset=0, size=rows_l)


def _mrs_dir(x, w, axis_name, direction, *, offset, size):
    """One reduction ring: ``direction=-1`` sends accumulators left
    (chunk c seeded at rank c-1 — each rank seeds chunk r+1), ``+1``
    sends right (chunk c seeded at rank c+1 — each rank seeds chunk
    r-1); either way the owner adds last after n-1 hops.
    ``offset/size`` select the row window of each chunk this ring
    carries."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    rows_l = x.shape[0] // n
    perm = (
        _ring_perm(n)
        if direction > 0
        else [(i, (i - 1) % n) for i in range(n)]
    )

    def partial(c):
        return lax.dynamic_slice_in_dim(x, c * rows_l + offset, size, 0) @ w

    acc = partial((r - direction) % n)
    for i in range(1, n):
        acc = lax.ppermute(acc, axis_name, perm)
        acc = acc + partial((r - direction * (1 + i)) % n)
    return acc


def tp_attention_overlapped(
    x_shard: jax.Array,
    attn_params,
    heads: int,
    axis_name: str = MODEL_AXIS,
    *,
    causal: bool = True,
    bidirectional: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Sharded-heads attention with SEQUENCE-SHARDED activations: the
    all-gather before the QKV projection and the reduce-scatter after the
    output projection are collective matmuls (Megatron-SP attention).

    ``x_shard``: (b, s_l, d) — rank r holds global positions
    ``r*s_l .. (r+1)*s_l - 1`` (rank-major sequence order).
    ``attn_params``: the fused-QKV pytree (``{"qkv", "out"}``,
    `nn.MultiHeadAttention` with ``kv_heads == heads``); each rank slices
    its ``heads/n`` head shard exactly like `tp_attention`.  Attention
    itself runs over the FULL gathered sequence on the local heads (the
    softmax needs every position — that is why SP gathers here), and the
    output returns sequence-sharded.  Dropout-free, like
    `tp_encoder_block`.
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if heads % n:
        raise ValueError(f"heads {heads} not divisible by axis size {n}")
    if "qkv" not in attn_params:
        raise ValueError(
            "tp_attention_overlapped supports the fused-QKV layout only "
            "(kv_heads == heads); the replicated GQA K/V projection would "
            "need a second gather of x"
        )
    hl = heads // n
    b, s_l, d = x_shard.shape
    w = attn_params["qkv"]["w"]
    hd = w.shape[1] // (3 * heads)
    w_loc = lax.dynamic_slice_in_dim(
        w.reshape(d, 3, heads, hd), r * hl, hl, 2
    ).reshape(d, 3 * hl * hd)
    b_loc = lax.dynamic_slice_in_dim(
        attn_params["qkv"]["b"].reshape(3, heads, hd), r * hl, hl, 1
    ).reshape(3 * hl * hd)

    qkv_rows = (
        allgather_matmul(
            x_shard.reshape(b * s_l, d), w_loc, axis_name,
            bidirectional=bidirectional,
        )
        + b_loc
    )  # (n*b*s_l, 3*hl*hd), rank-major chunks = global sequence order
    qkv = qkv_rows.reshape(n, b, s_l, 3, hl, hd)
    # (n, b, s_l, hl, hd) -> (b, hl, S, hd); chunk index n IS the outer
    # sequence index, so merging (n, s_l) reconstructs global order
    q, k, v = (
        qkv[:, :, :, i].transpose(1, 3, 0, 2, 4).reshape(b, hl, n * s_l, hd)
        for i in range(3)
    )

    from tpu_dist.nn.attention import dot_product_attention

    # the gathered sequence is FULL here, so the window band applies
    # exactly as in the dense path
    o = dot_product_attention(q, k, v, causal=causal, window=window)  # (b, hl, S, hd)
    # back to rank-major rows for the reduce-scatter
    o_rows = (
        o.reshape(b, hl, n, s_l, hd)
        .transpose(2, 0, 3, 1, 4)
        .reshape(n * b * s_l, hl * hd)
    )
    wo_loc = lax.dynamic_slice_in_dim(
        attn_params["out"]["w"], r * hl * hd, hl * hd, 0
    )
    out = matmul_reduce_scatter(
        o_rows, wo_loc, axis_name, bidirectional=bidirectional
    )  # (b*s_l, d)
    return out.reshape(b, s_l, d) + attn_params["out"]["b"]


def tp_encoder_block_sp(
    block, params, x_shard, axis_name: str = MODEL_AXIS,
    *, bidirectional: bool = False,
):
    """A full pre-norm transformer block in the Megatron-SP layout:
    activations stay SEQUENCE-SHARDED between sublayers (1/n of
    `tp_encoder_block`'s activation memory), LayerNorms run token-local
    on replicated params, and all four collectives are folded into their
    matmuls (`tp_attention_overlapped` + `tp_mlp_overlapped`).  ``block``
    is the EncoderBlock instance; ``params`` its replicated pytree.
    Numerics match ``block.apply`` on the gathered sequence (tested)."""
    if getattr(block.attn, "use_rope", False):
        raise ValueError(
            "tp_encoder_block_sp does not apply rotary embeddings — "
            "un-rotated q/k would be silently wrong; use learned positions"
        )
    h, _ = block.ln1.apply(params["ln1"], {}, x_shard)
    x = x_shard + tp_attention_overlapped(
        h, params["attn"], block.attn.heads, axis_name,
        causal=block.attn.causal, bidirectional=bidirectional,
        window=getattr(block.attn, "sliding_window", None),
    )
    h, _ = block.ln2.apply(params["ln2"], {}, x)
    return x + tp_mlp_overlapped(
        h, params["mlp"], axis_name, bidirectional=bidirectional
    )


def tp_mlp_overlapped(
    x_shard: jax.Array,
    mlp_params,
    axis_name: str = MODEL_AXIS,
    *,
    activation=jax.nn.gelu,
    bidirectional: bool = False,
) -> jax.Array:
    """The sequence-parallel Megatron MLP with both collectives folded
    into their matmuls: ``activation(AG(x) @ W1 + b1) @ W2 -> RS``.

    ``x_shard``: (b, s_l, d) or (s_l, d) — this rank's sequence chunk of
    the replicated-model activations.  ``mlp_params`` is the model zoo's
    MLP pytree ``{"fc1": {"w","b"}, "fc2": {"w","b"}}``, passed
    replicated; each rank slices its column shard of fc1 and row shard of
    fc2 (same contract as `tp_mlp_block`).  Output has ``x_shard``'s
    shape: activations stay sequence-sharded through the block, using
    1/n of `tp_mlp_block`'s activation memory and replacing its psum
    with a gather+scatter pair that never stands alone as a barrier.
    """
    w1 = shard_dim(mlp_params["fc1"]["w"], axis_name, 1)
    b1 = shard_dim(mlp_params["fc1"]["b"], axis_name, 0)
    w2 = shard_dim(mlp_params["fc2"]["w"], axis_name, 0)
    b2 = mlp_params["fc2"]["b"]

    lead = x_shard.shape[:-1]
    x2d = x_shard.reshape(-1, x_shard.shape[-1])
    hidden = activation(
        allgather_matmul(x2d, w1, axis_name, bidirectional=bidirectional)
        + b1
    )
    out = (
        matmul_reduce_scatter(
            hidden, w2, axis_name, bidirectional=bidirectional
        )
        + b2
    )
    return out.reshape(*lead, out.shape[-1])
