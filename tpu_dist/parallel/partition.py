"""Rule-driven partition engine: regex rules over flattened parameter
paths → `PartitionSpec`s, and ONE sharded train step for any composed
dp×fsdp×tp mesh.

Why this exists (ROADMAP item 2): the sharding decision used to live in
three separate step builders — replicated DP (`data_parallel`), flat-row
FSDP/ZeRO-1 (`fsdp`) — each a hand-written shard_map program, which is
why the trainers refuse most mode compositions (pipeline×fsdp,
compress×TP, ...): every pair of strategies is a new code path.  Here
the strategy is DATA, not code:

- `match_partition_rules(rules, tree, mesh)` maps ``(regex, spec)``
  rules over '/'-joined tree paths (the `fmengine`/EasyLM pattern,
  SNIPPETS.md [1]) to a `PartitionSpec` pytree — scalars and size-1
  leaves fall back to replicated, axes that don't divide a dim are
  dropped per-leaf, first match wins.
- `make_partitioned_train_step` compiles the GLOBAL train step under
  ``jax.jit`` with those specs as in/out shardings and lets XLA's SPMD
  partitioner derive every collective (the GSPMD form of
  `make_train_step_auto`, extended to sharded state).  The weight
  update is constrained to the OPT-STATE rules, so optimizer state and
  the update math run sharded — automatic cross-replica sharding of the
  weight update per PAPERS.md (arxiv 2004.13336): ZeRO-1 is a rule set,
  not a step builder ("zero1-for-free on any dp axis").
- `resolve_rules("dp=2,fsdp=2")` (or ``zero1:dp=8``, ``dp=2,tp=2``, ...)
  re-expresses data_parallel / fsdp / zero1 as built-in rule sets and
  composes them with a Megatron-layout ``tp`` vocabulary for
  `TransformerLM` — 2-D/3-D meshes come from one config knob
  (`TrainConfig.mesh_axes` / `LMTrainConfig.mesh_axes`), and per-layer
  overrides ride user rules (config list or the ``TPU_DIST_RULES`` env)
  matched FIRST.

Numerics: the partitioned program is the SAME global math, partitioned —
grads/opt-state match the strategy implementations to fp tolerance
(tests/test_partition.py pins dp/fsdp/zero1 and the composed meshes
against the legacy builders and the dense reference).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
KNOWN_AXES = (DP_AXIS, FSDP_AXIS, TP_AXIS)
ENV_RULES = "TPU_DIST_RULES"

__all__ = [
    "DP_AXIS",
    "FSDP_AXIS",
    "TP_AXIS",
    "ENV_RULES",
    "RuleSet",
    "PartitionedTrainStep",
    "build_mesh",
    "dead_user_rules",
    "match_partition_rules",
    "make_partitioned_train_step",
    "make_shard_and_gather_fns",
    "gather_replicated",
    "parse_mesh_axes",
    "parse_rules",
    "partition_summary",
    "per_device_bytes",
    "state_bytes_by_class",
    "resolve_rules",
    "resolve_trainer_rules",
    "rule_match_report",
    "shard_over",
    "strategy_engine_spec",
    "tree_paths",
]


# --------------------------------------------------------------- tree paths


def _key_name(k) -> str:
    """One path component of a tree_flatten_with_path key entry."""
    for attr in ("key", "idx", "name"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


def tree_paths(tree: Any) -> list[tuple[str, Any]]:
    """``[('blocks/0/mlp/fc1/w', leaf), ...]`` — the '/'-joined flat
    paths the rule regexes match against (``re.search``, so a rule like
    ``mlp/fc1/w$`` matches the same parameter inside ANY wrapper tree,
    including optimizer-state subtrees like ``m/blocks/0/mlp/fc1/w``)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_name(k) for k in kp), leaf) for kp, leaf in flat]


# ------------------------------------------------------------ spec fitting


def _axis_size(mesh: Mesh, name: str) -> int:
    try:
        return int(mesh.shape[name])
    except KeyError:
        raise ValueError(
            f"partition rule names mesh axis {name!r}, but the mesh axes "
            f"are {tuple(mesh.axis_names)}"
        ) from None


def _fit_spec(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Validate ``spec`` against a concrete leaf: unknown axis names
    raise; an axis whose size does not divide its dim is DROPPED (the
    small-leaf fallback — a 1-D bias too small for the fsdp axis simply
    stays replicated); a spec longer than the leaf's rank raises."""
    entries = tuple(spec)
    if len(entries) > len(shape):
        raise ValueError(
            f"partition spec {spec} has {len(entries)} entries for a "
            f"leaf of shape {shape}"
        )
    out = []
    for dim, entry in enumerate(entries):
        if entry is None:
            out.append(None)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        kept, prod = [], 1
        for name in names:
            size = _axis_size(mesh, name)
            if shape[dim] % (prod * size) == 0:
                kept.append(name)
                prod *= size
        out.append(tuple(kept) if len(kept) > 1 else (kept[0] if kept else None))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _greedy_assign(
    shape: tuple[int, ...], axes: Sequence[str], mesh: Mesh, init: P = P()
) -> P:
    """Assign ``axes`` (in order) to dims of ``shape``, largest
    divisible dim first, starting from the ``init`` spec.  An axis that
    fits nowhere (or already appears in ``init``) is skipped — the
    replicated fallback the engine promises for small leaves."""
    entries: list[Any] = [
        (e if isinstance(e, tuple) else (e,)) if e is not None else ()
        for e in tuple(init)
    ]
    entries += [()] * (len(shape) - len(entries))
    used = {name for e in entries for name in e}
    for axis in axes:
        if axis in used:
            continue
        size = _axis_size(mesh, axis)
        # prefer the largest per-shard dim (dim size / what's already
        # assigned there), unsharded dims before stacking onto sharded
        best = None
        for dim in range(len(shape)):
            prod = int(np.prod([_axis_size(mesh, n) for n in entries[dim]] or [1]))
            if shape[dim] % (prod * size):
                continue
            key = (len(entries[dim]) == 0, shape[dim] // prod)
            if best is None or key > best[0]:
                best = (key, dim)
        if best is not None:
            entries[best[1]] = tuple(entries[best[1]]) + (axis,)
            used.add(axis)
    out = [
        tuple(e) if len(e) > 1 else (e[0] if e else None) for e in entries
    ]
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def shard_over(*axes: str) -> Callable:
    """Rule value: shard the leaf over ``axes``, each axis greedily
    placed on the largest dim it divides (replicated when nothing
    divides) — the generic fsdp/zero1 rule."""

    def rule(path, leaf, mesh):
        return _greedy_assign(tuple(leaf.shape), axes, mesh)

    return rule


def _fill(value, axes: tuple[str, ...]) -> Callable:
    """Wrap a rule value so the resulting spec is EXTENDED by ``axes``
    on remaining dims — how a param rule becomes its sharded-update/
    opt-state rule (`zero1`-for-free: the update additionally shards
    over the data axes the gradient was reduced over)."""

    def rule(path, leaf, mesh):
        base = _apply_rule_value(value, path, leaf, mesh)
        return _greedy_assign(tuple(leaf.shape), axes, mesh, base)

    return rule


def _apply_rule_value(value, path, leaf, mesh) -> P:
    if callable(value):
        spec = value(path, leaf, mesh)
    elif isinstance(value, str):
        spec = _parse_spec(value)
    else:
        spec = value
    return _fit_spec(spec, tuple(leaf.shape), mesh)


# ------------------------------------------------------------ rule matching


def _match_leaves(rules, tree: Any, mesh: Mesh) -> tuple[list, Any]:
    """The matching core: ``([(path, shape, rule_index, spec), ...],
    treedef)`` in leaf order.  ``rule_index`` is None for scalar/size-1
    leaves (replicated unconditionally, no rule consulted)."""
    rules = tuple(rules)
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for kp, leaf in flat:
        path = "/".join(_key_name(k) for k in kp)
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            out.append((path, shape, None, P()))  # scalars replicate
            continue
        for idx, (pattern, value) in enumerate(rules):
            if re.search(pattern, path) is not None:
                out.append(
                    (path, shape, idx, _apply_rule_value(value, path, leaf, mesh))
                )
                break
        else:
            raise ValueError(
                f"no partition rule matched leaf {path!r} "
                f"(shape {shape}); add a catch-all ('.*', P()) rule"
            )
    return out, treedef


def match_partition_rules(rules, tree: Any, mesh: Mesh) -> Any:
    """`PartitionSpec` pytree for ``tree``: first rule whose regex
    ``re.search``-matches the leaf's '/'-joined path wins; scalar and
    size-1 leaves are replicated unconditionally; a leaf no rule matches
    raises (built-in rule sets always end with a catch-all).

    ``rules``: iterable of ``(pattern, value)`` where value is a
    `PartitionSpec`, a spec string (see `parse_rules`), or a callable
    ``(path, leaf, mesh) -> PartitionSpec`` (e.g. `shard_over`)."""
    matched, treedef = _match_leaves(rules, tree, mesh)
    return jax.tree_util.tree_unflatten(
        treedef, [spec for _, _, _, spec in matched]
    )


def rule_match_report(rules, tree: Any, mesh: Mesh) -> dict:
    """Which rule claimed which leaf — the raw material for the static
    analyzer's dead-rule / replicated-fallthrough lints and for
    debugging a rule set by hand.

    Returns ``{"leaves": [{"path", "shape", "rule", "pattern", "spec",
    "replicated"}, ...], "counts": [matches per rule], "dead": [indices
    of rules that matched nothing]}``.  ``rule`` is None for the
    scalar/size-1 leaves no rule is consulted for."""
    rules = tuple(rules)
    matched, _ = _match_leaves(rules, tree, mesh)
    counts = [0] * len(rules)
    leaves = []
    for path, shape, idx, spec in matched:
        if idx is not None:
            counts[idx] += 1
        leaves.append(
            {
                "path": path,
                "shape": shape,
                "rule": idx,
                "pattern": rules[idx][0] if idx is not None else None,
                "spec": spec,
                "replicated": all(e is None for e in tuple(spec)),
            }
        )
    return {
        "leaves": leaves,
        "counts": counts,
        "dead": [i for i, c in enumerate(counts) if c == 0],
    }


def dead_user_rules(
    rules: "RuleSet", tree: Any, mesh: Mesh, *, opt_tree: Any = None
) -> tuple[str, ...]:
    """Patterns among the USER rules (env + config, the first
    ``rules.n_user`` entries) that match no leaf of ``tree`` — a typo'd
    pattern silently falling through to the built-ins is the classic way
    a "pinned" layer ends up sharded wrong.  Dead BUILT-IN rules are
    normal (the tp vocabulary matches nothing on a conv net) and are not
    reported here.  User rules also apply to the optimizer state (whose
    paths carry wrapper prefixes like ``buf/``), so pass ``opt_tree`` to
    clear rules that legitimately pin only an opt-state leaf."""
    if not rules.n_user:
        return ()
    dead = set(rule_match_report(rules.param_rules, tree, mesh)["dead"])
    if opt_tree is not None and dead:
        dead &= set(
            rule_match_report(rules.opt_rules, opt_tree, mesh)["dead"]
        )
    return tuple(
        rules.param_rules[i][0] for i in sorted(dead) if i < rules.n_user
    )


# ----------------------------------------------------------- rule parsing


def _parse_spec(text: str) -> P:
    """``'None,tp'`` → ``P(None, 'tp')``; ``'dp+fsdp'`` → one dim
    sharded by both axes; ``'replicated'`` / ``''`` → ``P()``."""
    text = text.strip()
    if text in ("", "replicated", "P()"):
        return P()
    entries = []
    for part in text.split(","):
        part = part.strip()
        if part in ("None", "-", ""):
            entries.append(None)
        elif "+" in part:
            entries.append(tuple(p.strip() for p in part.split("+")))
        else:
            entries.append(part)
    return P(*entries)


def parse_rules(text: str) -> tuple:
    """User rules from a string (the ``TPU_DIST_RULES`` env format):
    ``'pattern=spec;pattern=spec'`` with spec per `_parse_spec`, e.g.
    ``'embed/table$=None,tp;blocks/0/.*=replicated'``.  Returned rules
    are matched FIRST (ahead of config and built-in rules)."""
    rules = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"malformed {ENV_RULES} clause {clause!r} — expected "
                "'pattern=spec' (spec like 'None,tp' or 'replicated')"
            )
        pattern, spec = clause.split("=", 1)
        rules.append((pattern.strip(), _parse_spec(spec)))
    return tuple(rules)


def _normalize_user_rules(user_rules) -> tuple:
    out = []
    for pattern, value in user_rules or ():
        out.append(
            (pattern, _parse_spec(value) if isinstance(value, str) else value)
        )
    return tuple(out)


# --------------------------------------------------------------- rule sets


@dataclass(frozen=True)
class RuleSet:
    """A named partition strategy: rules for params, rules for the
    optimizer state / weight update, which mesh axes shard the batch
    (gradients reduce over these), and which shard the MODEL in a
    non-data way (the tensor-parallel axes other subsystems — e.g.
    `comm.compress` — must refuse)."""

    name: str
    param_rules: tuple
    opt_rules: tuple
    data_axes: tuple[str, ...]
    model_axes: tuple[str, ...] = ()
    # how many leading entries of param_rules/opt_rules came from the
    # user (env + config) — the slice `dead_user_rules` audits
    n_user: int = 0

    def batch_spec(self) -> P:
        """Batch partition: leading dim sharded over every data axis."""
        if not self.data_axes:
            return P()
        if len(self.data_axes) == 1:
            return P(self.data_axes[0])
        return P(tuple(self.data_axes))


def _p_rule(*entries) -> Callable:
    """Fixed-layout rule value (divisibility still fitted per leaf)."""
    spec = P(*entries)

    def rule(path, leaf, mesh):
        return _fit_spec(spec, tuple(leaf.shape), mesh)

    return rule


def _megatron_rules(tp: str) -> tuple:
    """The Megatron layout over `TransformerLM`/`EncoderBlock` params:
    column-parallel QKV/fc1 (output dim sharded), row-parallel out/fc2
    (input dim sharded), vocab-sharded embedding table; norms/positions
    replicated via the caller's catch-all."""
    return (
        (r"attn/qkv/w$", _p_rule(None, tp)),
        (r"attn/qkv/b$", _p_rule(tp)),
        (r"attn/(q|kv)/w$", _p_rule(None, tp)),
        (r"attn/(q|kv)/b$", _p_rule(tp)),
        (r"attn/out/w$", _p_rule(tp, None)),
        (r"mlp/fc1/w$", _p_rule(None, tp)),
        (r"mlp/fc1/b$", _p_rule(tp)),
        (r"mlp/fc2/w$", _p_rule(tp, None)),
        (r"embed/table$", _p_rule(tp, None)),
    )


def parse_mesh_axes(spec: str) -> tuple[str | None, dict[str, int | None]]:
    """``'dp=2,fsdp=4'`` / ``'zero1:dp=8'`` / ``'dp=2,tp=2'`` →
    ``(prefix_or_None, {axis: size_or_None})``.  Sizes may be omitted
    (``'dp,fsdp'``) and are then taken from the mesh at resolve time."""
    prefix = None
    body = spec.strip()
    if ":" in body:
        prefix, body = (s.strip() for s in body.split(":", 1))
        if prefix != "zero1":
            raise ValueError(
                f"unknown rule-set prefix {prefix!r} in mesh_axes "
                f"{spec!r} — only 'zero1:' is recognized"
            )
    axes: dict[str, int | None] = {}
    for part in body.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in KNOWN_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r} in mesh_axes {spec!r} — "
                f"known axes are {KNOWN_AXES}"
            )
        if name in axes:
            raise ValueError(f"duplicate axis {name!r} in mesh_axes {spec!r}")
        axes[name] = int(size) if size else None
    if not axes:
        raise ValueError(f"mesh_axes {spec!r} names no axes")
    if DP_AXIS not in axes and FSDP_AXIS not in axes:
        raise ValueError(
            f"mesh_axes {spec!r} has no data axis — include 'dp' or "
            "'fsdp' (the batch must shard over something)"
        )
    if prefix == "zero1" and FSDP_AXIS in axes:
        raise ValueError(
            "zero1: is redundant with an fsdp axis (fsdp already shards "
            "params AND optimizer state) — drop one"
        )
    return prefix, axes


def build_mesh(
    spec: str,
    *,
    platform: str | None = None,
    mesh_devices=None,
) -> Mesh:
    """A `Mesh` shaped by a mesh_axes spec (sizes required here, except
    that ONE axis may omit its size and absorbs the remaining devices)."""
    from tpu_dist.comm import mesh as mesh_mod

    _, axes = parse_mesh_axes(spec)
    devs = (
        list(mesh_devices)
        if mesh_devices is not None
        else mesh_mod.devices(platform)
    )
    free = [a for a, s in axes.items() if s is None]
    if len(free) > 1:
        raise ValueError(
            f"build_mesh({spec!r}): at most one axis may omit its size"
        )
    if free:
        known = int(np.prod([s for s in axes.values() if s is not None]))
        if len(devs) % known:
            raise ValueError(
                f"build_mesh({spec!r}): {len(devs)} devices not divisible "
                f"by the explicit axis product {known}"
            )
        axes[free[0]] = len(devs) // known
    return mesh_mod.make_mesh(
        tuple(axes.values()), tuple(axes.keys()),
        platform=platform, mesh_devices=mesh_devices,
    )


def enumerate_mesh_axes(
    n_chips: int,
    *,
    tp: bool = False,
    zero1: bool = True,
) -> list[str]:
    """Every built-in mesh_axes spec expressible at ``n_chips`` chips —
    the candidate space `analysis.advisor` ranks statically.

    Covers the single-axis rule sets (``dp=N``, ``zero1:dp=N``,
    ``fsdp=N``) plus every 2-axis factorization of the chip count:
    ``dp=a,fsdp=b`` always, ``dp=a,tp=b`` when ``tp=True`` (the
    Megatron vocabulary only binds to transformer parameter names —
    pointless for models it cannot shard).  Each spec resolves through
    `resolve_rules` on a `build_mesh` of that shape, so the enumeration
    and the engine can never disagree about what a candidate means.
    Deterministic order (the advisor's tie-break)."""
    n = int(n_chips)
    if n < 1:
        raise ValueError(f"need at least one chip, got {n}")
    specs = [f"dp={n}"]
    if n >= 2:
        if zero1:
            specs.append(f"zero1:dp={n}")
        specs.append(f"fsdp={n}")
    for a in range(2, n):
        if n % a:
            continue
        b = n // a
        if b < 2:
            continue
        specs.append(f"dp={a},fsdp={b}")
        if tp:
            specs.append(f"dp={a},tp={b}")
    return specs


def resolve_rules(
    spec: str,
    mesh: Mesh,
    *,
    user_rules=None,
    env: bool = True,
    bind: dict[str, str] | None = None,
) -> RuleSet:
    """The `RuleSet` for a mesh_axes spec, validated against ``mesh``.

    Built-in sets (derived from the axes present):

    - ``'dp=N'`` — everything replicated; the reference data-parallel
      baseline (the replicated weight update the bench compares against).
    - ``'zero1:dp=N'`` — params replicated, optimizer state + update
      sharded over dp (ZeRO-1 as data).
    - ``'fsdp=N'`` / ``'dp=A,fsdp=B'`` — params sharded over fsdp
      (largest divisible dim per leaf), opt state additionally over dp.
    - ``'dp=A,tp=B'`` (± fsdp) — Megatron-layout TP rules for the
      transformer param names, fsdp/catch-all for the rest; opt state
      picks up the dp axis (sharded update on every set but pure dp).

    ``bind`` maps spec ROLE names onto the mesh's actual axis names
    (e.g. ``{"fsdp": "data"}`` runs the fsdp rule set on a mesh whose
    axis is called ``data``) — how the trainers route their legacy
    fsdp/zero1/dp flags through the engine on the caller's existing
    mesh without renaming its axes.  The `RuleSet`'s ``name`` (and
    therefore checkpoint/telemetry provenance) stays role-based;
    ``data_axes``/``model_axes`` and every rule carry the BOUND names.

    ``user_rules`` (list of ``(pattern, spec)``) and the
    ``TPU_DIST_RULES`` env (when ``env=True``) are matched ahead of the
    built-ins, env first — so a single layer can be pinned to a
    different spec without forking the rule set.  User rules apply to
    params AND optimizer state (the update follows the pinned layout).
    """
    prefix, axes = parse_mesh_axes(spec)
    bind = dict(bind or {})
    if set(bind) - set(axes):
        raise ValueError(
            f"bind maps roles {sorted(set(bind) - set(axes))} that the "
            f"mesh_axes spec {spec!r} does not name"
        )
    # role -> actual mesh axis name (identity unless bound)
    actual = {role: bind.get(role, role) for role in axes}
    mesh_shape = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    want = {
        actual[a]: (s if s is not None else mesh_shape.get(actual[a]))
        for a, s in axes.items()
    }
    if tuple(want) != tuple(mesh.axis_names) or any(
        mesh_shape.get(a) != s for a, s in want.items()
    ):
        raise ValueError(
            f"mesh_axes {spec!r} (axes {want}) does not match the mesh "
            f"(axes {mesh_shape}) — build the mesh with "
            f"partition.build_mesh({spec!r}) or align the spec"
        )
    has_fsdp = FSDP_AXIS in axes
    has_tp = TP_AXIS in axes
    fsdp_ax, tp_ax = actual.get(FSDP_AXIS), actual.get(TP_AXIS)
    data_axes = tuple(
        actual[a] for a in axes if a in (DP_AXIS, FSDP_AXIS)
    )

    catch_all = shard_over(fsdp_ax) if has_fsdp else _p_rule()
    if has_tp:
        param_rules = _megatron_rules(tp_ax)
        if has_fsdp:  # 2-D weight sharding: tp dim + fsdp on the rest
            param_rules = tuple(
                (pat, _fill(val, (fsdp_ax,))) for pat, val in param_rules
            )
        param_rules += ((r".*", catch_all),)
    else:
        param_rules = ((r".*", catch_all),)

    # The sharded weight update: pure dp keeps the replicated update
    # (the baseline); every other set extends the param layout by the
    # data axes — optimizer state born 1/|dp| (ZeRO-1 for free).
    name = prefix or "+".join(axes)
    plain_dp = name == DP_AXIS and not has_fsdp and not has_tp
    if plain_dp:
        opt_rules = param_rules
    else:
        update_axes = (actual[DP_AXIS],) if DP_AXIS in axes else ()
        opt_rules = tuple(
            (pat, _fill(val, update_axes)) for pat, val in param_rules
        )
    user = parse_rules(os.environ.get(ENV_RULES, "")) if env else ()
    user += _normalize_user_rules(user_rules)
    return RuleSet(
        name=name,
        param_rules=user + tuple(param_rules),
        opt_rules=user + tuple(opt_rules),
        data_axes=data_axes,
        model_axes=(tp_ax,) if has_tp else (),
        n_user=len(user),
    )


def partition_summary(rules: RuleSet, mesh: Mesh) -> dict:
    """JSON-able provenance for telemetry / checkpoint metadata."""
    return {
        "rules": rules.name,
        "axes": {str(k): int(v) for k, v in dict(mesh.shape).items()},
        "data_axes": list(rules.data_axes),
        "model_axes": list(rules.model_axes),
    }


def strategy_engine_spec(
    mesh: Mesh,
    *,
    fsdp: bool = False,
    zero1: bool = False,
    data_axis: str,
    tp_axis: str | None = None,
) -> tuple[str, dict[str, str]]:
    """The ``(mesh_axes spec, bind)`` pair that routes the retired
    fsdp/zero1/dp trainer FLAGS through the engine on the caller's
    existing mesh — one synthesis for both trainers, so the flag→rule
    translation cannot drift between them.  ``data_axis`` is the mesh's
    batch axis (the legacy builders' ``'data'``); ``tp_axis`` composes
    the Megatron tp vocabulary (the tensor_parallel flag's model axis).
    Neither flag set means plain dp."""
    if fsdp and zero1:
        raise ValueError("fsdp and zero1 are mutually exclusive")
    d = _axis_size(mesh, data_axis)
    role = FSDP_AXIS if fsdp else DP_AXIS
    prefix = "zero1:" if zero1 else ""
    spec = f"{prefix}{role}={d}"
    bind = {role: data_axis}
    if tp_axis is not None:
        spec += f",tp={_axis_size(mesh, tp_axis)}"
        bind[TP_AXIS] = tp_axis
    return spec, bind


def resolve_trainer_rules(
    where: str,
    mesh: Mesh,
    mesh_axes: str,
    *,
    user_rules=None,
    bind: dict[str, str] | None = None,
) -> tuple[RuleSet, dict]:
    """The shared trainer-side resolution (`Trainer` and `LMTrainer`
    engine modes): rule set + checkpoint/telemetry summary.  The
    compressed gradient wire is part of the engine itself
    (`make_partitioned_train_step(compress=...)`), so there is no
    trainer-level compress refusal here anymore — the only remaining
    refusal (2-D model×data weight sharding) is raised by the step
    builder, naming the offending leaves."""
    rules = resolve_rules(mesh_axes, mesh, user_rules=user_rules, bind=bind)
    return rules, partition_summary(rules, mesh)


def gather_replicated(tree: Any, mesh: Mesh) -> Any:
    """Full (replicated) copies of a rule-sharded pytree, multi-host
    safe: fully-addressable trees pass through untouched (``np.asarray``
    on the leaves already works); otherwise one compiled identity with
    replicated out-shardings all-gathers every leaf — the engine-mode
    analog of `fsdp_full_params` for eval/generate paths."""
    if all(
        getattr(leaf, "is_fully_addressable", True)
        for leaf in jax.tree.leaves(tree)
    ):
        return tree
    repl = NamedSharding(mesh, P())
    return jax.jit(lambda t: t, out_shardings=repl)(tree)


# ------------------------------------------------------- shard/gather fns


def make_shard_and_gather_fns(specs: Any, mesh: Mesh) -> tuple[Any, Any]:
    """Per-leaf ``(shard_fns, gather_fns)`` for a `PartitionSpec` pytree
    (the SNIPPETS.md [3] pattern): ``shard_fns`` place host arrays under
    their `NamedSharding` (a fresh committed buffer — never an alias the
    donating step could invalidate); ``gather_fns`` fetch the full
    logical array back to host (single-controller: every shard must be
    addressable — use the checkpoint layer for multi-host gathers)."""

    def make_shard(spec):
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(np.asarray(x), sharding)

    def make_gather(_spec):
        return lambda x: np.asarray(jax.device_get(x))

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    return (
        jax.tree_util.tree_map(make_shard, specs, is_leaf=is_spec),
        jax.tree_util.tree_map(make_gather, specs, is_leaf=is_spec),
    )


def per_device_bytes(tree: Any, device=None) -> int:
    """Bytes of ``tree`` resident on ONE device (default: the first
    device of the first leaf's sharding) — the honest per-chip cost of
    params/opt state under a rule set (a replicated leaf counts once, a
    sharded leaf counts its local shard)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            # abstract leaves (analysis programs): logical bytes — the
            # caller's tree is single-device or already shard-shaped
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            continue
        if not hasattr(leaf, "addressable_shards"):
            total += np.asarray(leaf).nbytes
            continue
        dev = device
        if dev is None:
            dev = sorted(leaf.sharding.device_set, key=lambda d: d.id)[0]
        total += sum(
            s.data.nbytes for s in leaf.addressable_shards if s.device == dev
        )
    return total


def state_bytes_by_class(params=None, opt_state=None, device=None,
                         **extra) -> list[dict]:
    """Per-device resident bytes bucketed into the classes an OOM (or a
    memory plan) should name: ``params``, ``opt``, and — when the
    optimizer state carries the compressed-wire EF wrapper — the
    ``ef_residual`` split out of ``opt`` (the residual is n× a gradient,
    so it deserves its own line).  Extra kwargs add caller-labeled trees
    (``batch=...``, ``weights=...``, ``kv_pool=...``).  Returns
    ``[{class, bytes}]`` rows, zero-byte classes dropped."""
    trees: list[tuple[str, Any]] = []
    if params is not None:
        trees.append(("params", params))
    if opt_state is not None:
        if isinstance(opt_state, dict) and "ef" in opt_state:
            ef = opt_state["ef"]
            trees.append(("opt", {k: v for k, v in opt_state.items()
                                  if k != "ef"}))
            trees.append(("ef_residual", ef.get("residual")))
        else:
            trees.append(("opt", opt_state))
    trees.extend(extra.items())
    rows = []
    for name, tree in trees:
        if tree is None:
            continue
        nbytes = per_device_bytes(tree, device)
        if nbytes:
            rows.append({"class": name, "bytes": int(nbytes)})
    return rows


# ----------------------------------------------------------- train step


def _strip_spec(spec: P, keep) -> P:
    """``spec`` restricted to axis names in ``keep`` (tuples filtered,
    empty entries -> None, trailing Nones trimmed) — how one leaf spec
    splits into its manual (data) and auto (model) components for the
    compressed-wire region."""
    keep = set(keep)
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
            continue
        names = e if isinstance(e, tuple) else (e,)
        kept = tuple(n for n in names if n in keep)
        out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _local_shape(shape, spec: P, axes, mesh: Mesh) -> tuple[int, ...]:
    """The per-device shape of a leaf along ``axes`` only (other axis
    names in the spec are ignored)."""
    axes = set(axes)
    out = list(shape)
    for d, e in enumerate(tuple(spec)):
        if e is None:
            continue
        for nme in e if isinstance(e, tuple) else (e,):
            if nme in axes:
                out[d] //= _axis_size(mesh, nme)
    return tuple(out)


def _gather_axes(leaf: jax.Array, spec: P, axes) -> jax.Array:
    """Inside a manual region: all_gather the ``axes`` components of
    ``spec`` back to the full leaf (tiled, per dim) — the per-step
    un-shard of fsdp-ruled params the compressed region pays exactly
    like GSPMD's derived gathers do."""
    from jax import lax

    axes = set(axes)
    for d, e in enumerate(tuple(spec)):
        if e is None:
            continue
        names = tuple(
            n for n in (e if isinstance(e, tuple) else (e,)) if n in axes
        )
        if names:
            leaf = lax.all_gather(
                leaf, names if len(names) > 1 else names[0],
                axis=d, tiled=True,
            )
    return leaf


@dataclass
class PartitionedTrainStep:
    """What `make_partitioned_train_step` hands back: the compiled step
    plus the sharded live state and the resolved specs (checkpoint
    metadata, telemetry, tests)."""

    step: Callable
    params: Any
    opt_state: Any
    param_specs: Any
    opt_specs: Any
    ruleset: RuleSet
    mesh: Mesh = field(repr=False, default=None)
    # user-rule patterns that matched no parameter leaf (surfaced as a
    # warning event at build time and a `dead-rule` analyzer finding)
    dead_rules: tuple[str, ...] = ()
    # the resolved compressed-wire config + flat bucket plan (None when
    # the step syncs exact f32); plan shapes are per MODEL shard — the
    # wire accounting and `analysis_expectations` the telemetry and the
    # `compress-wire` lint consume
    compress: Any = None
    flat_plan: Any = field(repr=False, default=None)

    def summary(self) -> dict:
        return partition_summary(self.ruleset, self.mesh)


def make_partitioned_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh: Mesh,
    params: Any,
    rules: RuleSet,
    *,
    accum_steps: int = 1,
    donate: bool = True,
    compress=None,
) -> PartitionedTrainStep:
    """ONE train step for every rule set — the engine's whole point.

    ``loss_fn(params, batch, key) -> (loss, aux)`` is the GLOBAL
    computation (mean over the global batch), written as if on one big
    device; XLA's SPMD partitioner derives the per-device program and
    every collective from the shardings:

    - params enter/leave under the param rules;
    - the batch shards its leading axis over ``rules.data_axes``;
    - gradients are constrained to the OPT rules before the update, so
      the optimizer math (and its state) runs sharded — the compiled
      step carries no full-size replicated update op on any set but
      pure dp (tests/test_hlo_structure.py asserts this);
    - ``accum_steps=k`` scans k microbatches with a gradient-sum carry
      (same contract as the strategy builders: one sync per step, mean
      gradient, activations 1/k).

    ``compress`` (a `comm.compress.CompressConfig` or spec string like
    ``"int8"``) swaps the partitioner-derived f32 gradient sync for the
    bucketed quantized wire with two-round error feedback
    (`comm.compress.all_reduce_rows`), INSIDE the same GSPMD program:
    the loss/backward run in a shard_map region manual over the DATA
    axes only (model axes stay auto — XLA still partitions the math
    over tp), each data rank's gradient ships as 1-byte (or bf16)
    bucket chunks through a compressed reduce-scatter + all-gather pair
    per bucket, and the EF residual rides the optimizer-state slot as
    ``{"opt": ..., "ef": {"residual", "err"}}``, sharded by the
    engine's own rules and donated with it.  Model-sharded (tp) leaves
    compress AT THEIR SHARD SHAPE — the wire reduces over the data
    axes; model axes are untouched.  Per-rank loss keys are derived by
    folding the data-axis coordinate into the step key, so dropout
    masks differ across data ranks exactly like the retired strategy
    builders' did.  The only refusal left: a leaf whose single dim is
    sharded over BOTH a data and a model axis (mixed 2-D tuples) cannot
    ride the wire.

    Returns a `PartitionedTrainStep`; its ``step(params, opt_state,
    batch, key) -> (params, opt_state, loss, aux)`` donates params/opt
    state when ``donate``.  The returned ``params``/``opt_state`` are
    freshly placed under the rules (safe to donate immediately)."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    from tpu_dist.comm import compress as compress_mod

    ccfg = compress_mod.parse(compress)
    wrap_ef = ccfg is not None and ccfg.error_feedback
    # Opt-state specs from the ABSTRACT init (eval_shape): the full
    # replicated state is never materialized — under an fsdp rule set
    # whose adamw moments only fit sharded, a concrete init here would
    # OOM before the first step.
    opt_template = jax.eval_shape(optimizer.init, params)
    # A user rule matching ZERO leaves (in params AND opt state) is
    # almost always a typo'd pattern whose layer silently fell through
    # to the built-ins — loud at build time (warning + telemetry event)
    # and a `dead-rule` lint finding in `tpu_dist.analysis`.
    dead = dead_user_rules(rules, params, mesh, opt_tree=opt_template)
    if dead:
        import warnings

        from tpu_dist.observe import events as _events

        msg = (
            f"partition rule set {rules.name!r}: user rules matching no "
            f"parameter leaf (dead): {list(dead)}"
        )
        warnings.warn(msg, stacklevel=2)
        _events.from_env().emit("warning", reason=msg, dead_rules=list(dead))
    param_specs = match_partition_rules(rules.param_rules, params, mesh)
    update_specs = match_partition_rules(rules.opt_rules, params, mesh)
    opt_specs = match_partition_rules(rules.opt_rules, opt_template, mesh)

    as_sharding = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    p_sh = jax.tree_util.tree_map(as_sharding, param_specs, is_leaf=is_spec)
    o_sh = jax.tree_util.tree_map(as_sharding, opt_specs, is_leaf=is_spec)
    u_sh = jax.tree_util.tree_map(as_sharding, update_specs, is_leaf=is_spec)
    b_sh = NamedSharding(mesh, rules.batch_spec())

    vg = jax.value_and_grad(loss_fn, has_aux=True)

    def accumulate(params, batch, key):
        def to_micro(a):
            if a.shape[0] % accum_steps:
                raise ValueError(
                    f"global batch {a.shape[0]} not divisible by "
                    f"accum_steps {accum_steps}"
                )
            return a.reshape(
                (accum_steps, a.shape[0] // accum_steps) + a.shape[1:]
            )

        micro = jax.tree.map(to_micro, batch)
        g0 = jax.tree.map(jnp.zeros_like, params)

        def body(carry, xs):
            gacc, lacc = carry
            mb, i = xs
            (loss, aux), g = vg(params, mb, jax.random.fold_in(key, i))
            return (jax.tree.map(jnp.add, gacc, g), lacc + loss), aux

        (gsum, lsum), auxs = jax.lax.scan(
            body, (g0, 0.0), (micro, jnp.arange(accum_steps))
        )
        grads = jax.tree.map(lambda g: g / accum_steps, gsum)
        aux = jax.tree.map(
            lambda a: a.mean(0)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a[-1],
            auxs,
        )
        return grads, lsum / accum_steps, aux

    flat_plan = None
    if ccfg is None:

        def global_step(params, opt_state, batch, key):
            if accum_steps == 1:
                (loss, aux), grads = vg(params, batch, key)
            else:
                grads, loss, aux = accumulate(params, batch, key)
            # The sharded weight update: pin the gradient (same shapes
            # as params) to the UPDATE layout, so the optimizer's
            # elementwise math — and the momenta it reads/writes —
            # partitions with it instead of replicating (arxiv
            # 2004.13336's transformation, expressed as a sharding
            # constraint instead of a rewrite).
            grads = jax.lax.with_sharding_constraint(grads, u_sh)
            new_params, new_opt = optimizer.update(params, grads, opt_state)
            return new_params, new_opt, loss, aux

        o_sh_step = o_sh
    else:
        # ---- the compressed data-axis wire, inside the GSPMD program.
        # Manual region over the DATA axes only (model axes stay auto):
        # each data rank computes its local-shard gradient, ships it as
        # quantized buckets through `all_reduce_rows`, and hands the
        # data-replicated mean gradient back to the sharded update.
        data_axes = tuple(rules.data_axes)
        model_axes = tuple(rules.model_axes)
        ax = data_axes if len(data_axes) > 1 else data_axes[0]
        n_data = int(np.prod([_axis_size(mesh, a) for a in data_axes]))
        p_leaves, p_treedef = jax.tree_util.tree_flatten(params)
        spec_leaves = p_treedef.flatten_up_to(param_specs)
        # A dim sharded over BOTH a data and a model axis (the 2-D
        # tp×fsdp weight sharding) interleaves model and data shards in
        # one dimension — the flat bucket layout cannot split that into
        # a model-local row matrix.  Refuse loudly, naming the leaves.
        mixed = [
            path
            for (path, _), spec in zip(tree_paths(params), spec_leaves)
            for e in tuple(spec)
            if isinstance(e, tuple)
            and set(e) & set(data_axes)
            and set(e) & set(model_axes)
        ]
        if mixed:
            compress_mod.refuse_model_axes(
                "make_partitioned_train_step(compress=...)",
                model_axes,
                rules=(
                    f"rule set {rules.name!r}: leaves {sorted(set(mixed))} "
                    "shard one dim over model AND data axes (2-D weight "
                    "sharding)"
                ),
                hint="Use a mesh_axes spec whose model and data axes land "
                "on different dims (e.g. dp×tp), or drop compress.",
            )
        # Shapes as the sync region sees them: full along data dims
        # (params are gathered there), 1/|tp| along model-sharded dims.
        local_tmpl = jax.tree_util.tree_unflatten(p_treedef, [
            jax.ShapeDtypeStruct(
                _local_shape(tuple(leaf.shape), spec, model_axes, mesh),
                leaf.dtype,
            )
            for leaf, spec in zip(p_leaves, spec_leaves)
        ])
        flat_plan = compress_mod.FlatPlan(local_tmpl, n_data, ccfg)
        res_spec = compress_mod.engine_residual_spec(data_axes, model_axes)
        res_manual = _strip_spec(res_spec, data_axes)
        g_model_specs = jax.tree_util.tree_unflatten(
            p_treedef, [_strip_spec(s, model_axes) for s in spec_leaves]
        )
        manual_p_specs = jax.tree_util.tree_unflatten(
            p_treedef, [_strip_spec(s, data_axes) for s in spec_leaves]
        )
        # nan_guard-wrapped optimizers advertise current_scale: poison
        # grads on a non-finite LOSS before the sync so the wire's
        # all-finite predicate holds the residual and the guard skips
        # the step — the legacy builders' contract, kept.
        guarded = getattr(optimizer, "current_scale", None) is not None

        def sync_local(grads_local, residual_local):
            """Leaves at MODEL-shard shapes; reduce over data axes."""
            rows = flat_plan.to_rows(grads_local)
            res = residual_local[0] if residual_local is not None else None
            total, new_res, stats = compress_mod.all_reduce_rows(
                rows, res, flat_plan, ax,
                predicate_axes=data_axes + model_axes,
            )
            grads_mean = flat_plan.from_rows(total / n_data)
            err = stats["err"]
            if model_axes:
                err = jax.lax.pmean(err, model_axes)
            return (
                grads_mean,
                new_res[None] if new_res is not None else None,
                err,
            )

        if model_axes:
            m_ax = model_axes if len(model_axes) > 1 else model_axes[0]
            inner_res_spec = P(None, None, m_ax)

            def sync(grads, residual):
                if wrap_ef:
                    return jax.shard_map(
                        sync_local,
                        mesh=mesh,
                        in_specs=(g_model_specs, inner_res_spec),
                        out_specs=(g_model_specs, inner_res_spec, P()),
                        check_vma=False,
                    )(grads, residual)
                def stateless(g_):
                    out = sync_local(g_, None)
                    return out[0], out[2]

                g, e = jax.shard_map(
                    stateless,
                    mesh=mesh,
                    in_specs=(g_model_specs,),
                    out_specs=(g_model_specs, P()),
                    check_vma=False,
                )(grads)
                return g, None, e
        else:
            sync = sync_local

        def region(params, batch, key, residual):
            # Per-rank keys: the data-axis coordinate folds into the
            # step key, so dropout masks differ across data ranks (the
            # strategy builders' per-rank stream, kept under the
            # engine).
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))
            full = jax.tree_util.tree_unflatten(p_treedef, [
                _gather_axes(leaf, spec, data_axes)
                for leaf, spec in zip(
                    p_treedef.flatten_up_to(params), spec_leaves
                )
            ])
            if accum_steps == 1:
                (loss, aux), grads = vg(full, batch, key)
            else:
                grads, loss, aux = accumulate(full, batch, key)
            if guarded:
                from tpu_dist.resilience.guards import _poison

                grads = _poison(grads, ~jnp.isfinite(loss))
            grads, new_res, err = sync(grads, residual)
            from tpu_dist.parallel.data_parallel import _pmean_float_leaves

            loss = jax.lax.pmean(loss, ax)
            aux = _pmean_float_leaves(aux, ax)
            return grads, loss, aux, new_res, err

        auto = frozenset(model_axes)
        if wrap_ef:
            mapped = jax.shard_map(
                region,
                mesh=mesh,
                in_specs=(manual_p_specs, rules.batch_spec(), P(), res_manual),
                out_specs=(P(), P(), P(), res_manual, P()),
                check_vma=False,
                auto=auto,
            )
        else:
            mapped = jax.shard_map(
                lambda p, b, k: region(p, b, k, None)[:3],
                mesh=mesh,
                in_specs=(manual_p_specs, rules.batch_spec(), P()),
                out_specs=(P(), P(), P()),
                check_vma=False,
                auto=auto,
            )

        def global_step(params, opt_state, batch, key):
            inner_opt = opt_state["opt"] if wrap_ef else opt_state
            if wrap_ef:
                grads, loss, aux, new_res, err = mapped(
                    params, batch, key, opt_state["ef"]["residual"]
                )
            else:
                grads, loss, aux = mapped(params, batch, key)
            grads = jax.lax.with_sharding_constraint(grads, u_sh)
            new_params, new_opt = optimizer.update(params, grads, inner_opt)
            if wrap_ef:
                new_opt = {
                    "opt": new_opt,
                    "ef": {"residual": new_res, "err": err},
                }
            return new_params, new_opt, loss, aux

        if wrap_ef:
            ef_sh = {
                "residual": NamedSharding(mesh, res_spec),
                "err": NamedSharding(mesh, P()),
            }
            o_sh_step = {"opt": o_sh, "ef": ef_sh}
            opt_specs = {
                "opt": opt_specs,
                "ef": {"residual": res_spec, "err": P()},
            }
        else:
            o_sh_step = o_sh

    step = jax.jit(
        global_step,
        in_shardings=(p_sh, o_sh_step, b_sh, None),
        out_shardings=(p_sh, o_sh_step, None, None),
        donate_argnums=(0, 1) if donate else (),
    )
    placed_params = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(np.asarray(a), s), params, p_sh
    )
    # Opt state is born sharded: init compiled with the opt shardings as
    # out-shardings, so each device writes only its own shard (no full
    # host copy, no device->host->device round trip).
    placed_opt = jax.jit(optimizer.init, out_shardings=o_sh)(placed_params)
    if ccfg is not None and wrap_ef:
        placed_opt = {
            "opt": placed_opt,
            "ef": compress_mod.init_engine_ef_state(
                flat_plan, mesh, rules.data_axes, rules.model_axes
            ),
        }
    return PartitionedTrainStep(
        step=step,
        params=placed_params,
        opt_state=placed_opt,
        param_specs=param_specs,
        opt_specs=opt_specs,
        ruleset=rules,
        mesh=mesh,
        dead_rules=dead,
        compress=ccfg,
        flat_plan=flat_plan,
    )
