"""Pipeline parallelism — GPipe-style microbatching over a ``pipe`` mesh
axis.

Listed as a non-goal for parity in SURVEY.md §2d (the reference has no
model big enough to split); implemented here so every row of the
parallelism table is expressible, not just "the mesh could".  Design:

- The model is split into ``n`` *stages* with uniform activation shapes
  (e.g. transformer blocks).  Under ``shard_map`` over the ``pipe`` axis,
  each rank holds ONE stage's parameters (stacked pytree sharded on its
  leading axis).
- The global batch is split into ``M`` microbatches.  The schedule runs
  ``M + n - 1`` lockstep ticks: at tick ``t``, stage ``s`` processes
  microbatch ``t - s`` (when valid) and hands its activation to stage
  ``s+1`` via the same neighbor ``ppermute`` the ring collectives use.
  Bubble fraction is the usual ``(n-1)/(M+n-1)``.
- Every rank executes the same compiled program (SPMD); validity is
  masking, not control flow — XLA-friendly by construction.

`pipeline_apply` is forward-only scheduling; because it is pure JAX, the
whole schedule differentiates (backward replays the scan in reverse), so
it composes with `jax.grad`/train steps — tested.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm

PIPE_AXIS = "pipe"


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack per-stage parameter pytrees on a new leading axis (shard it
    over the ``pipe`` axis with ``P('pipe')`` when entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params_local: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Run the staged model over the pipeline.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` — this rank's
        stage.  Activation shapes must be uniform across stages.
      params_local: this rank's stage parameters (inside shard_map: the
        local slice of the stacked pytree, leading stage axis of size 1 is
        squeezed by the caller or carried — see `tests/test_pipeline.py`).
      x: the FULL local batch ``(B, ...)`` (replicated input); it is split
        into ``n_microbatches`` microbatches of ``B // n_microbatches``.
      n_microbatches: M; must divide B.
      remat_stages: rematerialize each stage's forward during backward
        (``jax.checkpoint``): activation memory per device drops from
        O(ticks) scan residuals to O(1) per tick at the cost of one extra
        stage forward — the standard pipeline-training memory trade.

    Returns the full output batch ``(B, ...)``, valid on every rank (the
    last stage's results are broadcast back over the ring as part of the
    drain, costing nothing extra in program count).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by n_microbatches {n_microbatches}"
        )
    mb = B // n_microbatches
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    perm = ring_perm(n)
    ticks = n_microbatches + n - 1

    out0 = jnp.zeros_like(micro)

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived from the left neighbor last tick.
        inject_idx = jnp.clip(t, 0, n_microbatches - 1)
        injected = lax.dynamic_index_in_dim(micro, inject_idx, 0, keepdims=False)
        x_in = jnp.where(s == 0, injected, buf)
        y = stage_fn(params_local, x_in)
        # Last stage: write microbatch t - (n-1) when valid.
        out_idx = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
        valid = (s == n - 1) & (t >= n - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid, y, lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)),
            out_idx,
            0,
        )
        # activations flow right around the ring (the last->first hop
        # carries garbage that stage 0 ignores — it injects instead)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, updated), None

    init = (jnp.zeros((mb,) + x.shape[1:], x.dtype), out0)
    (final_buf, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    # Everyone needs the result (losses are usually computed replicated):
    # take the last stage's outputs via a masked psum.
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(outputs, axis_name)
    # Replicated-loss gradient convention: every rank recomputes the SAME
    # loss from these replicated outputs, and the transpose of the psum
    # above sums all n identical cotangents — n× the true gradient.
    # Scale the differentiable path by 1/n (forward value unchanged) so
    # grads through pipeline_apply equal sequential-execution grads.
    outputs = outputs / n + lax.stop_gradient(outputs * (n - 1) / n)
    return outputs.reshape((B,) + x.shape[1:])


def gpipe_ticks(n: int, n_microbatches: int) -> int:
    """GPipe schedule length in full-stage ticks."""
    return n_microbatches + n - 1


def gpipe_bubble_fraction(n: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (n-1)/(M+n-1)."""
    return (n - 1) / gpipe_ticks(n, n_microbatches)


def interleaved_ticks(n: int, n_microbatches: int, n_chunks: int) -> int:
    """Interleaved schedule length in CHUNK ticks (each 1/n_chunks of a
    full per-rank stage): M·v + n - 1."""
    return n_microbatches * n_chunks + n - 1


def interleaved_bubble_fraction(
    n: int, n_microbatches: int, n_chunks: int
) -> float:
    """Idle fraction of the interleaved schedule: (n-1)/(M·v+n-1).

    Each of the M·v work ticks is 1/v of a full stage, so the n-1 drain
    ticks shrink relative to the work — the Megatron interleaving win.
    Strictly below `gpipe_bubble_fraction` for v > 1.
    """
    return (n - 1) / interleaved_ticks(n, n_microbatches, n_chunks)


def stack_chunk_params(chunk_params_per_rank: list[list[Any]]) -> Any:
    """Stack a [rank][chunk] params nest for the interleaved schedule:
    leading axes (n_ranks, n_chunks); shard with ``P('pipe')`` so each
    rank's local slice carries its n_chunks chunk-parameter pytrees.

    Chunk c on rank s implements GLOBAL stage ``c·n + s`` (Megatron
    interleaved assignment): rank s holds stages s, n+s, 2n+s, ...
    """
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(
        [stack_pytrees(chunks) for chunks in chunk_params_per_rank]
    )


def pipeline_apply_interleaved(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    chunks_local: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Interleaved (Megatron 1F1B-style) pipeline schedule.

    Each rank holds ``v`` model CHUNKS (virtual stages) instead of one:
    chunk ``c`` on rank ``s`` is global stage ``c·n + s``, so activations
    still only ever hop to the right neighbor (the chunk boundary
    ``c·n - 1 → c·n`` is the wrap-around hop ``n-1 → 0``).  Microbatches
    are processed in rounds of ``n``: within round ``r``, chunk-stage
    ``g = c·n + s`` runs microbatch ``m = r·n + j`` at tick
    ``r·n·v + c·n + j + s``.  Every rank does exactly one chunk per tick
    (1/v of a GPipe tick), giving ``M·v + n - 1`` chunk-ticks total and
    bubble fraction ``(n-1)/(M·v+n-1)`` — below GPipe's ``(n-1)/(M+n-1)``
    for v > 1 (see `interleaved_bubble_fraction`).

    Args:
      stage_fn: ``(chunk_params, activation) -> activation``; uniform
        activation shapes across all ``n·v`` chunk-stages.
      chunks_local: this rank's stacked chunk parameters — inside
        shard_map, the local slice of `stack_chunk_params` output with the
        rank axis (size 1) squeezed, leaving a leading ``v`` axis.
      x: full local batch ``(B, ...)``, replicated; split into
        ``n_microbatches`` microbatches.  ``n_microbatches`` must be a
        multiple of the pipe world (rounds of n — Megatron's constraint)
        and divide B.

    Forward-only scheduling like `pipeline_apply`; pure JAX, so the
    backward replays the scan in reverse and grads match sequential
    execution (tested), the 1F1B memory shape coming from
    ``remat_stages=True``.
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    v = jax.tree.leaves(chunks_local)[0].shape[0]
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by n_microbatches {n_microbatches}"
        )
    if n_microbatches % n:
        raise ValueError(
            f"n_microbatches {n_microbatches} must be a multiple of the "
            f"pipe world {n} (rounds of n)"
        )
    mb = B // n_microbatches
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    perm = ring_perm(n)
    ticks = interleaved_ticks(n, n_microbatches, v)

    def tick(carry, t):
        buf, outputs = carry
        # This rank's schedule position: t' = t - s, decomposed into
        # (round r, chunk c, offset j) with t' = r·n·v + c·n + j.
        tp = t - s
        active = (tp >= 0) & (tp < n_microbatches * v)
        tp_c = jnp.clip(tp, 0, n_microbatches * v - 1)
        r = tp_c // (n * v)
        rem = tp_c % (n * v)
        c = rem // n
        j = rem % n
        m = jnp.clip(r * n + j, 0, n_microbatches - 1)

        chunk_params = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            chunks_local,
        )
        # Global stage c·n + s == 0 (rank 0, chunk 0) injects microbatch m;
        # everything else consumes the right-flowing neighbor hand-off.
        injected = lax.dynamic_index_in_dim(micro, m, 0, keepdims=False)
        x_in = jnp.where((s == 0) & (c == 0), injected, buf)
        y = stage_fn(chunk_params, x_in)
        # Global last stage (rank n-1, chunk v-1) banks microbatch m.
        valid_out = active & (s == n - 1) & (c == v - 1)
        prev = lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid_out, y, prev), m, 0
        )
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, updated), None

    init = (
        jnp.zeros((mb,) + x.shape[1:], x.dtype),
        jnp.zeros_like(micro),
    )
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(outputs, axis_name)
    # Same replicated-cotangent correction as `pipeline_apply`.
    outputs = outputs / n + lax.stop_gradient(outputs * (n - 1) / n)
    return outputs.reshape((B,) + x.shape[1:])
