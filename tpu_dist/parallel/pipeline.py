"""Pipeline parallelism — GPipe-style microbatching over a ``pipe`` mesh
axis.

Listed as a non-goal for parity in SURVEY.md §2d (the reference has no
model big enough to split); implemented here so every row of the
parallelism table is expressible, not just "the mesh could".  Design:

- The model is split into ``n`` *stages* with uniform activation shapes
  (e.g. transformer blocks).  Under ``shard_map`` over the ``pipe`` axis,
  each rank holds ONE stage's parameters (stacked pytree sharded on its
  leading axis).
- The global batch is split into ``M`` microbatches.  The schedule runs
  ``M + n - 1`` lockstep ticks: at tick ``t``, stage ``s`` processes
  microbatch ``t - s`` (when valid) and hands its activation to stage
  ``s+1`` via the same neighbor ``ppermute`` the ring collectives use.
  Bubble fraction is the usual ``(n-1)/(M+n-1)``.
- Every rank executes the same compiled program (SPMD); validity is
  masking, not control flow — XLA-friendly by construction.

`pipeline_apply` is forward-only scheduling; because it is pure JAX, the
whole schedule differentiates (backward replays the scan in reverse), so
it composes with `jax.grad`/train steps — tested.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm

PIPE_AXIS = "pipe"


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack per-stage parameter pytrees on a new leading axis (shard it
    over the ``pipe`` axis with ``P('pipe')`` when entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params_local: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Run the staged model over the pipeline.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` — this rank's
        stage.  Activation shapes must be uniform across stages.
      params_local: this rank's stage parameters (inside shard_map: the
        local slice of the stacked pytree, leading stage axis of size 1 is
        squeezed by the caller or carried — see `tests/test_pipeline.py`).
      x: the FULL local batch ``(B, ...)`` (replicated input); it is split
        into ``n_microbatches`` microbatches of ``B // n_microbatches``.
      n_microbatches: M; must divide B.
      remat_stages: rematerialize each stage's forward during backward
        (``jax.checkpoint``): activation memory per device drops from
        O(ticks) scan residuals to O(1) per tick at the cost of one extra
        stage forward — the standard pipeline-training memory trade.

    Returns the full output batch ``(B, ...)``, valid on every rank (the
    last stage's results are broadcast back over the ring as part of the
    drain, costing nothing extra in program count).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by n_microbatches {n_microbatches}"
        )
    mb = B // n_microbatches
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    perm = ring_perm(n)
    ticks = n_microbatches + n - 1

    out0 = jnp.zeros_like(micro)

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived from the left neighbor last tick.
        inject_idx = jnp.clip(t, 0, n_microbatches - 1)
        injected = lax.dynamic_index_in_dim(micro, inject_idx, 0, keepdims=False)
        x_in = jnp.where(s == 0, injected, buf)
        y = stage_fn(params_local, x_in)
        # Last stage: write microbatch t - (n-1) when valid.
        out_idx = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
        valid = (s == n - 1) & (t >= n - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid, y, lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)),
            out_idx,
            0,
        )
        # activations flow right around the ring (the last->first hop
        # carries garbage that stage 0 ignores — it injects instead)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, updated), None

    init = (jnp.zeros((mb,) + x.shape[1:], x.dtype), out0)
    (final_buf, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    # Everyone needs the result (losses are usually computed replicated):
    # take the last stage's outputs via a masked psum.
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(outputs, axis_name)
    # Replicated-loss gradient convention: every rank recomputes the SAME
    # loss from these replicated outputs, and the transpose of the psum
    # above sums all n identical cotangents — n× the true gradient.
    # Scale the differentiable path by 1/n (forward value unchanged) so
    # grads through pipeline_apply equal sequential-execution grads.
    outputs = outputs / n + lax.stop_gradient(outputs * (n - 1) / n)
    return outputs.reshape((B,) + x.shape[1:])
