"""Pipeline parallelism — GPipe-style microbatching over a ``pipe`` mesh
axis.

Listed as a non-goal for parity in SURVEY.md §2d (the reference has no
model big enough to split); implemented here so every row of the
parallelism table is expressible, not just "the mesh could".  Design:

- The model is split into ``n`` *stages* with uniform activation shapes
  (e.g. transformer blocks).  Under ``shard_map`` over the ``pipe`` axis,
  each rank holds ONE stage's parameters (stacked pytree sharded on its
  leading axis).
- The global batch is split into ``M`` microbatches.  The schedule runs
  ``M + n - 1`` lockstep ticks: at tick ``t``, stage ``s`` processes
  microbatch ``t - s`` (when valid) and hands its activation to stage
  ``s+1`` via the same neighbor ``ppermute`` the ring collectives use.
  Bubble fraction is the usual ``(n-1)/(M+n-1)``.
- Every rank executes the same compiled program (SPMD); validity is
  masking, not control flow — XLA-friendly by construction.

`pipeline_apply` is forward-only scheduling; because it is pure JAX, the
whole schedule differentiates (backward replays the scan in reverse), so
it composes with `jax.grad`/train steps — tested.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm

PIPE_AXIS = "pipe"

# Schedule-table op codes (`Schedule.ops` cells; also the `lax.switch`
# branch indices in the engine executor).
IDLE, FWD, BWD = 0, 1, 2

SCHEDULE_KINDS = ("gpipe", "1f1b", "interleaved_1f1b")


def default_schedule_kind(n_chunks: int) -> str:
    """The 1F1B schedule kind for a chunk count — the ONE place the
    v>1 → interleaved default lives (trainer and model both call it, so
    the telemetry table and the executed table can never disagree on
    the default)."""
    return "interleaved_1f1b" if n_chunks > 1 else "1f1b"


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack per-stage parameter pytrees on a new leading axis (shard it
    over the ``pipe`` axis with ``P('pipe')`` when entering shard_map)."""
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params_local: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Run the staged model over the pipeline.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` — this rank's
        stage.  Activation shapes must be uniform across stages.
      params_local: this rank's stage parameters (inside shard_map: the
        local slice of the stacked pytree, leading stage axis of size 1 is
        squeezed by the caller or carried — see `tests/test_pipeline.py`).
      x: the FULL local batch ``(B, ...)`` (replicated input); it is split
        into ``n_microbatches`` microbatches of ``B // n_microbatches``.
      n_microbatches: M; must divide B.
      remat_stages: rematerialize each stage's forward during backward
        (``jax.checkpoint``): activation memory per device drops from
        O(ticks) scan residuals to O(1) per tick at the cost of one extra
        stage forward — the standard pipeline-training memory trade.

    Returns the full output batch ``(B, ...)``, valid on every rank (the
    last stage's results are broadcast back over the ring as part of the
    drain, costing nothing extra in program count).
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by n_microbatches {n_microbatches}"
        )
    mb = B // n_microbatches
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    perm = ring_perm(n)
    ticks = n_microbatches + n - 1

    out0 = jnp.zeros_like(micro)

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived from the left neighbor last tick.
        inject_idx = jnp.clip(t, 0, n_microbatches - 1)
        injected = lax.dynamic_index_in_dim(micro, inject_idx, 0, keepdims=False)
        x_in = jnp.where(s == 0, injected, buf)
        y = stage_fn(params_local, x_in)
        # Last stage: write microbatch t - (n-1) when valid.
        out_idx = jnp.clip(t - (n - 1), 0, n_microbatches - 1)
        valid = (s == n - 1) & (t >= n - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(valid, y, lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)),
            out_idx,
            0,
        )
        # activations flow right around the ring (the last->first hop
        # carries garbage that stage 0 ignores — it injects instead)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, updated), None

    init = (jnp.zeros((mb,) + x.shape[1:], x.dtype), out0)
    (final_buf, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    # Everyone needs the result (losses are usually computed replicated):
    # take the last stage's outputs via a masked psum.
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(outputs, axis_name)
    # Replicated-loss gradient convention: every rank recomputes the SAME
    # loss from these replicated outputs, and the transpose of the psum
    # above sums all n identical cotangents — n× the true gradient.
    # Scale the differentiable path by 1/n (forward value unchanged) so
    # grads through pipeline_apply equal sequential-execution grads.
    outputs = outputs / n + lax.stop_gradient(outputs * (n - 1) / n)
    return outputs.reshape((B,) + x.shape[1:])


def gpipe_ticks(n: int, n_microbatches: int) -> int:
    """GPipe schedule length in full-stage ticks."""
    return n_microbatches + n - 1


def gpipe_bubble_fraction(n: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (n-1)/(M+n-1)."""
    return (n - 1) / gpipe_ticks(n, n_microbatches)


def interleaved_ticks(n: int, n_microbatches: int, n_chunks: int) -> int:
    """Interleaved schedule length in CHUNK ticks (each 1/n_chunks of a
    full per-rank stage): M·v + n - 1."""
    return n_microbatches * n_chunks + n - 1


def interleaved_bubble_fraction(
    n: int, n_microbatches: int, n_chunks: int
) -> float:
    """Idle fraction of the interleaved schedule: (n-1)/(M·v+n-1).

    Each of the M·v work ticks is 1/v of a full stage, so the n-1 drain
    ticks shrink relative to the work — the Megatron interleaving win.
    Strictly below `gpipe_bubble_fraction` for v > 1.
    """
    return (n - 1) / interleaved_ticks(n, n_microbatches, n_chunks)


def stack_chunk_params(chunk_params_per_rank: list[list[Any]]) -> Any:
    """Stack a [rank][chunk] params nest for the interleaved schedule:
    leading axes (n_ranks, n_chunks); shard with ``P('pipe')`` so each
    rank's local slice carries its n_chunks chunk-parameter pytrees.

    Chunk c on rank s implements GLOBAL stage ``c·n + s`` (Megatron
    interleaved assignment): rank s holds stages s, n+s, 2n+s, ...
    """
    from tpu_dist.utils.tree import stack_pytrees

    return stack_pytrees(
        [stack_pytrees(chunks) for chunks in chunk_params_per_rank]
    )


def pipeline_apply_interleaved(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    chunks_local: Any,
    x: jax.Array,
    *,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Interleaved (Megatron 1F1B-style) pipeline schedule.

    Each rank holds ``v`` model CHUNKS (virtual stages) instead of one:
    chunk ``c`` on rank ``s`` is global stage ``c·n + s``, so activations
    still only ever hop to the right neighbor (the chunk boundary
    ``c·n - 1 → c·n`` is the wrap-around hop ``n-1 → 0``).  Microbatches
    are processed in rounds of ``n``: within round ``r``, chunk-stage
    ``g = c·n + s`` runs microbatch ``m = r·n + j`` at tick
    ``r·n·v + c·n + j + s``.  Every rank does exactly one chunk per tick
    (1/v of a GPipe tick), giving ``M·v + n - 1`` chunk-ticks total and
    bubble fraction ``(n-1)/(M·v+n-1)`` — below GPipe's ``(n-1)/(M+n-1)``
    for v > 1 (see `interleaved_bubble_fraction`).

    Args:
      stage_fn: ``(chunk_params, activation) -> activation``; uniform
        activation shapes across all ``n·v`` chunk-stages.
      chunks_local: this rank's stacked chunk parameters — inside
        shard_map, the local slice of `stack_chunk_params` output with the
        rank axis (size 1) squeezed, leaving a leading ``v`` axis.
      x: full local batch ``(B, ...)``, replicated; split into
        ``n_microbatches`` microbatches.  ``n_microbatches`` must be a
        multiple of the pipe world (rounds of n — Megatron's constraint)
        and divide B.

    Forward-only scheduling like `pipeline_apply`; pure JAX, so the
    backward replays the scan in reverse and grads match sequential
    execution (tested), the 1F1B memory shape coming from
    ``remat_stages=True``.
    """
    n = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    v = jax.tree.leaves(chunks_local)[0].shape[0]
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by n_microbatches {n_microbatches}"
        )
    if n_microbatches % n:
        raise ValueError(
            f"n_microbatches {n_microbatches} must be a multiple of the "
            f"pipe world {n} (rounds of n)"
        )
    mb = B // n_microbatches
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    perm = ring_perm(n)
    ticks = interleaved_ticks(n, n_microbatches, v)

    def tick(carry, t):
        buf, outputs = carry
        # This rank's schedule position: t' = t - s, decomposed into
        # (round r, chunk c, offset j) with t' = r·n·v + c·n + j.
        tp = t - s
        active = (tp >= 0) & (tp < n_microbatches * v)
        tp_c = jnp.clip(tp, 0, n_microbatches * v - 1)
        r = tp_c // (n * v)
        rem = tp_c % (n * v)
        c = rem // n
        j = rem % n
        m = jnp.clip(r * n + j, 0, n_microbatches - 1)

        chunk_params = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            chunks_local,
        )
        # Global stage c·n + s == 0 (rank 0, chunk 0) injects microbatch m;
        # everything else consumes the right-flowing neighbor hand-off.
        injected = lax.dynamic_index_in_dim(micro, m, 0, keepdims=False)
        x_in = jnp.where((s == 0) & (c == 0), injected, buf)
        y = stage_fn(chunk_params, x_in)
        # Global last stage (rank n-1, chunk v-1) banks microbatch m.
        valid_out = active & (s == n - 1) & (c == v - 1)
        prev = lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid_out, y, prev), m, 0
        )
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, updated), None

    init = (
        jnp.zeros((mb,) + x.shape[1:], x.dtype),
        jnp.zeros_like(micro),
    )
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    outputs = jnp.where(s == n - 1, outputs, jnp.zeros_like(outputs))
    outputs = lax.psum(outputs, axis_name)
    # Same replicated-cotangent correction as `pipeline_apply`.
    outputs = outputs / n + lax.stop_gradient(outputs * (n - 1) / n)
    return outputs.reshape((B,) + x.shape[1:])


# ===================================================================
# Schedule-driven pipeline engine: a static schedule table (build once
# on the host) + one `lax.scan` executor that interleaves forward and
# backward ticks — TRUE 1F1B.  The scan-replay paths above schedule
# forwards only and let autodiff replay the whole scan in reverse, so
# their activation memory is O(M) microbatch residuals and no backward
# ever overlaps a forward.  The engine below runs the textbook
# schedules: forward ticks push the stage INPUT into a fixed-depth
# ring stash, backward ticks pop it, recompute the stage forward under
# `jax.vjp`, and flow the cotangent through the reverse ppermute ring
# — steady-state activation memory O(n·v), bubble (n-1)/(M·v+n-1).
# ===================================================================


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A compiled pipeline schedule: per-tick op tables plus the ring-
    buffer slot assignments the executor needs, all static numpy.

    Every array is ``(ticks, n)`` indexed ``[t, rank]``:

    - ``ops``: IDLE / FWD / BWD (the `lax.switch` branch per tick)
    - ``chunk`` / ``mb``: which (virtual-stage chunk, microbatch) the op
      touches (0 where idle — masked, never read)
    - ``stash_push`` / ``stash_pop``: activation-stash slot a FWD writes
      its stage input to / a BWD pops (-1 = none)
    - ``fwd_read``: slot of MY fwd ring buffer a FWD consumes its input
      from (-1 = global stage 0, which injects the trunk microbatch)
    - ``bwd_read``: slot of MY bwd ring buffer a BWD takes its incoming
      cotangent from (-1 = last global stage, which seeds from the loss)
    - ``fwd_write`` / ``bwd_write``: slot of MY ring buffer where the
      payload ARRIVING at the end of tick t lands (-1 = drop — the
      neighbor sent garbage or an unconsumed wrap-around)

    Depths are the simulated high-water marks — the bounded-ring sizes
    the executor allocates.  ``stash_depth`` is the memory story: O(n·v)
    for the 1F1B kinds, M for GPipe.
    """

    kind: str
    n: int
    n_microbatches: int
    n_chunks: int
    ops: np.ndarray
    chunk: np.ndarray
    mb: np.ndarray
    stash_push: np.ndarray
    stash_pop: np.ndarray
    fwd_read: np.ndarray
    bwd_read: np.ndarray
    fwd_write: np.ndarray
    bwd_write: np.ndarray
    stash_depth: int
    fwd_depth: int
    bwd_depth: int

    @property
    def ticks(self) -> int:
        return int(self.ops.shape[0])

    def bubble_fraction(self) -> float:
        """MEASURED idle fraction of this table: idle cells over all
        (tick, rank) cells — what the executor will actually burn, as
        opposed to the closed-form `gpipe_bubble_fraction` /
        `interleaved_bubble_fraction` estimates."""
        return float((self.ops == IDLE).mean())

    def stash_high_water(self) -> int:
        """Peak live activation-stash entries on any rank (in microbatch
        activations).  The 1F1B acceptance number: O(n·v), not O(M)."""
        return self.stash_depth

    def work_cells(self) -> int:
        return int((self.ops != IDLE).sum())


def _op_order(kind: str, n: int, M: int, v: int, s: int):
    """Rank ``s``'s op sequence [(op, chunk, mb), ...] — the per-rank
    HALF of the schedule; `build_schedule`'s greedy simulation assigns
    the ticks."""
    if kind == "gpipe":
        # all forwards, flush, then backwards in reverse microbatch
        # order (F(M-1) finishes last downstream, so B(M-1) unblocks
        # first) — the GPipe memory shape: all M inputs stashed.
        return [(FWD, 0, m) for m in range(M)] + [
            (BWD, 0, m) for m in reversed(range(M))
        ]
    if kind == "1f1b":
        w = min(n - 1 - s, M)  # classic warmup: deeper ranks start colder
        order = [(FWD, 0, m) for m in range(w)]
        for i in range(M - w):
            order += [(FWD, 0, w + i), (BWD, 0, i)]
        order += [(BWD, 0, i) for i in range(M - w, M)]
        return order
    # interleaved_1f1b: Megatron's virtual-stage order — microbatches in
    # rounds of n, chunks cycled within each round (reversed for the
    # backward half), warmup (n-1-s)·2 + (v-1)·n chunk-ops.
    f_order = [
        (c, r * n + j)
        for r in range(M // n)
        for c in range(v)
        for j in range(n)
    ]
    b_order = [
        (c, r * n + j)
        for r in range(M // n)
        for c in reversed(range(v))
        for j in range(n)
    ]
    w = min((n - 1 - s) * 2 + (v - 1) * n, M * v)
    order = [(FWD,) + f_order[i] for i in range(w)]
    bi = 0
    for fi in range(w, M * v):
        order.append((FWD,) + f_order[fi])
        order.append((BWD,) + b_order[bi])
        bi += 1
    order += [(BWD,) + b_order[i] for i in range(bi, M * v)]
    return order


def _ready(op, c, m, s, done_at, n, v):
    """Can rank ``s`` fire (op, c, m) this tick?  Payloads produced at
    tick t arrive at the start of tick t+1 (one ppermute hop), so a
    dependency completed strictly BEFORE this tick is required."""
    g = c * n + s  # global stage
    if op == FWD:
        if g == 0:
            return True  # injects the trunk microbatch — always ready
        ps, pc = (s - 1, c) if s > 0 else (n - 1, c - 1)
        return (FWD, pc, m, ps) in done_at
    if g == n * v - 1:
        # last global stage seeds its own backward from the loss; only
        # its OWN forward (the stashed input) gates it.
        return (FWD, c, m, s) in done_at
    ds, dc = (s + 1, c) if s < n - 1 else (0, c + 1)
    return (BWD, dc, m, ds) in done_at


def _alloc_slots(events, T):
    """Bounded-ring slot allocation for one rank's buffer: ``events`` is
    [(write_tick, read_tick, key)] — payload lands at the END of
    write_tick, is consumed DURING read_tick (so a slot freed by a read
    can take that same tick's arrival).  Returns (write_slot_by_tick,
    read_slot_by_tick, depth)."""
    writes_at: dict[int, tuple] = {}
    reads_at: dict[int, tuple] = {}
    for tw, tr, key in events:
        assert tw not in writes_at and tr not in reads_at  # 1 op/tick/rank
        writes_at[tw] = key
        reads_at[tr] = key
    w_slot = -np.ones(T, np.int32)
    r_slot = -np.ones(T, np.int32)
    free: list[int] = []
    live: dict[tuple, int] = {}
    n_alloc = 0
    for t in range(T):
        if t in reads_at:
            slot = live.pop(reads_at[t])
            r_slot[t] = slot
            heapq.heappush(free, slot)
        if t in writes_at:
            slot = heapq.heappop(free) if free else n_alloc
            if slot == n_alloc:
                n_alloc += 1
            live[writes_at[t]] = slot
            w_slot[t] = slot
    assert not live
    return w_slot, r_slot, max(1, n_alloc)


def build_schedule(
    n: int, n_microbatches: int, n_chunks: int = 1, kind: str = "1f1b"
) -> Schedule:
    """Compile a pipeline schedule table for ``n`` ranks, ``M``
    microbatches, and ``v`` chunks (virtual stages) per rank.

    ``kind``: ``'gpipe'`` (flush: all forwards then all backwards, stash
    grows to M), ``'1f1b'`` (one-forward-one-backward steady state,
    stash ≤ n), or ``'interleaved_1f1b'`` (Megatron virtual stages,
    stash O(n·v), drain bubble (n-1)/(M·v+n-1)).  Generation is a greedy
    lockstep simulation: each rank executes its textbook op order
    as-soon-as-ready (payloads arrive one tick after production), then
    stash and neighbor ring-buffer slots are assigned from the simulated
    lifetimes — so the executor's buffers are exactly as deep as the
    schedule's true high-water mark, never M-sized for the 1F1B kinds.
    """
    M, v = int(n_microbatches), int(n_chunks)
    if kind not in SCHEDULE_KINDS:
        raise ValueError(f"kind must be one of {SCHEDULE_KINDS}, got {kind!r}")
    if n < 1 or M < 1 or v < 1:
        raise ValueError(f"need n, M, v >= 1, got {(n, M, v)}")
    if kind in ("gpipe", "1f1b") and v != 1:
        raise ValueError(f"{kind} schedules take n_chunks=1, got {v}")
    if kind == "interleaved_1f1b":
        if v == 1:
            kind = "1f1b"  # v=1 interleaving IS the classic schedule
        elif M % n:
            raise ValueError(
                f"interleaved_1f1b needs n_microbatches ({M}) to be a "
                f"multiple of the pipe world ({n}) — rounds of n"
            )

    orders = [_op_order(kind, n, M, v, s) for s in range(n)]
    ptr = [0] * n
    done_at: dict[tuple, int] = {}
    cols: list[list] = []
    remaining = sum(len(o) for o in orders)
    while remaining:
        # readiness is evaluated for every rank against the PREVIOUS
        # tick's completions before any of this tick's are committed
        col = []
        for s in range(n):
            if ptr[s] >= len(orders[s]):
                col.append(None)
                continue
            op, c, m = orders[s][ptr[s]]
            col.append((op, c, m) if _ready(op, c, m, s, done_at, n, v) else None)
        fired = [e for e in col if e is not None]
        if not fired:
            raise RuntimeError(
                f"schedule deadlock: kind={kind} n={n} M={M} v={v} at "
                f"tick {len(cols)}"
            )
        t = len(cols)
        for s, e in enumerate(col):
            if e is not None:
                done_at[e + (s,)] = t
                ptr[s] += 1
                remaining -= 1
        cols.append(col)
    T = len(cols)

    ops = np.zeros((T, n), np.int32)
    chunk = np.zeros((T, n), np.int32)
    mb = np.zeros((T, n), np.int32)
    stash_push = -np.ones((T, n), np.int32)
    stash_pop = -np.ones((T, n), np.int32)
    fwd_read = -np.ones((T, n), np.int32)
    bwd_read = -np.ones((T, n), np.int32)
    fwd_write = -np.ones((T, n), np.int32)
    bwd_write = -np.ones((T, n), np.int32)
    for t, col in enumerate(cols):
        for s, e in enumerate(col):
            if e is None:
                continue
            op, c, m = e
            ops[t, s], chunk[t, s], mb[t, s] = op, c, m

    # Activation stash: FWD pushes its stage input, the SAME rank's BWD
    # of the same (chunk, mb) pops it.
    stash_depth = 1
    for s in range(n):
        events = []
        for key, t in done_at.items():
            op, c, m, rs = key
            if rs != s or op != FWD:
                continue
            tb = done_at[(BWD, c, m, s)]
            events.append((t, tb, (c, m)))
        # pushes happen DURING the tick (not at its end), but a rank
        # runs one op per tick so a push never collides with its own
        # pop; the end-of-tick write model is equivalent here.
        w, r, depth = _alloc_slots(events, T)
        for t in range(T):
            if w[t] >= 0:
                stash_push[t, s] = w[t]
            if r[t] >= 0:
                stash_pop[t, s] = r[t]
        stash_depth = max(stash_depth, depth)

    # Neighbor ring buffers: a FWD at global stage g on rank ps lands in
    # rank (ps+1)%n's fwd buffer at the end of its tick and is consumed
    # by stage g+1's FWD; the last global stage's output has no consumer
    # (dropped).  Cotangents mirror this leftward.
    fwd_events: list[list] = [[] for _ in range(n)]
    bwd_events: list[list] = [[] for _ in range(n)]
    for key, t in done_at.items():
        op, c, m, s = key
        g = c * n + s
        if op == FWD and g < n * v - 1:
            cs, cc = (s + 1, c) if s < n - 1 else (0, c + 1)
            tc = done_at[(FWD, cc, m, cs)]
            fwd_events[cs].append((t, tc, (cc, m)))
        elif op == BWD and g > 0:
            cs, cc = (s - 1, c) if s > 0 else (n - 1, c - 1)
            tc = done_at[(BWD, cc, m, cs)]
            bwd_events[cs].append((t, tc, (cc, m)))
    fwd_depth = bwd_depth = 1
    for s in range(n):
        w, r, depth = _alloc_slots(fwd_events[s], T)
        fwd_write[:, s], fwd_depth = w, max(fwd_depth, depth)
        for t in range(T):
            if r[t] >= 0:
                fwd_read[t, s] = r[t]
        w, r, depth = _alloc_slots(bwd_events[s], T)
        bwd_write[:, s], bwd_depth = w, max(bwd_depth, depth)
        for t in range(T):
            if r[t] >= 0:
                bwd_read[t, s] = r[t]

    return Schedule(
        kind=kind, n=n, n_microbatches=M, n_chunks=v,
        ops=ops, chunk=chunk, mb=mb,
        stash_push=stash_push, stash_pop=stash_pop,
        fwd_read=fwd_read, bwd_read=bwd_read,
        fwd_write=fwd_write, bwd_write=bwd_write,
        stash_depth=stash_depth, fwd_depth=fwd_depth, bwd_depth=bwd_depth,
    )


def _store_slot(buf: jax.Array, payload: jax.Array, slot) -> jax.Array:
    """Write ``payload`` into ring-buffer ``buf`` at ``slot`` (traced
    scalar); slot < 0 drops the payload."""
    updated = lax.dynamic_update_index_in_dim(
        buf, payload, jnp.maximum(slot, 0), 0
    )
    return jnp.where(slot >= 0, updated, buf)


def _take_slot(buf: jax.Array, slot) -> jax.Array:
    return lax.dynamic_index_in_dim(buf, jnp.maximum(slot, 0), 0, keepdims=False)


def pipeline_engine_loss(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    last_fn: Callable[[Any, Any, jax.Array, Any], jax.Array],
    schedule: Schedule,
    chunks_local: Any,
    head_params: Any,
    h: jax.Array,
    loss_args: Any,
    *,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
) -> jax.Array:
    """Schedule-driven pipeline TRAINING loss for use INSIDE shard_map
    over ``axis_name`` — true fwd/bwd interleaving.

    The executor runs ``schedule``'s table under ONE `lax.scan`: each
    tick `lax.switch`es on the op code (idle / forward / backward),
    forward ticks stash their stage input in the bounded ring, backward
    ticks pop it and run the stage under `jax.vjp` (recompute-from-
    input — stage-granular checkpointing is inherent, so the stash is
    the ONLY schedule-lifetime activation memory), and both ppermute
    rings fire every tick (receivers mask by the static slot tables).
    The per-microbatch loss and its cotangent seed live on the LAST
    global stage, which backpropagates ``last_fn`` (stage + head +
    loss) the tick after that microbatch's forward — the 1F1B shape.

    Exposed as a `jax.custom_vjp` scalar: ``jax.grad`` of the returned
    loss works, with per-rank gradients following the pipeline psum
    contract — chunk grads land on the owning rank, head grads on the
    last rank, trunk cotangents (through ``h``) on rank 0 — so the psum
    over ``axis_name`` equals sequential-execution gradients (tested).

    Args:
      stage_fn: ``(chunk_params, activation) -> activation``.
      last_fn: ``(chunk_params, head_params, activation, loss_args_mb)
        -> scalar`` — the LAST stage fused with the head and the
        per-microbatch loss (mean over the microbatch).
      schedule: a `build_schedule` table; ``schedule.n`` must equal the
        ``axis_name`` mesh size.
      chunks_local: this rank's chunk params, leading axis
        ``schedule.n_chunks``.
      head_params: pytree entering ``last_fn`` (replicated; grads land
        on the last rank only).
      h: the full local batch of stage-0 inputs ``(B, ...)``; split into
        ``schedule.n_microbatches`` microbatches.
      loss_args: pytree of per-example arrays (leading dim divisible by
        M, e.g. target tokens), microbatched alongside ``h``.  Not
        differentiated.

    Returns the mean loss over microbatches, replicated on every rank.
    """
    n = lax.axis_size(axis_name)
    if n != schedule.n:
        raise ValueError(
            f"schedule built for n={schedule.n} but {axis_name!r} axis "
            f"has size {n}"
        )
    M, v = schedule.n_microbatches, schedule.n_chunks
    chunk_leaves = jax.tree.leaves(chunks_local)
    if chunk_leaves and chunk_leaves[0].shape[0] != v:
        raise ValueError(
            f"chunks_local leading axis {chunk_leaves[0].shape[0]} != "
            f"schedule n_chunks {v}"
        )
    B = h.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    mb = B // M
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    s_idx = lax.axis_index(axis_name)
    perm_right = ring_perm(n)
    perm_left = [(i, (i - 1) % n) for i in range(n)]

    def micro_split(a):
        if a.shape[0] % M:
            raise ValueError(
                f"loss_args leading dim {a.shape[0]} not divisible by "
                f"n_microbatches {M}"
            )
        return a.reshape((M, a.shape[0] // M) + a.shape[1:])

    micro_args = jax.tree.map(micro_split, loss_args)
    # This rank's (T,) schedule rows, sliced from the static tables.
    rows = {
        name: jnp.take(jnp.asarray(tbl), s_idx, axis=1)
        for name, tbl in (
            ("op", schedule.ops), ("chunk", schedule.chunk),
            ("mb", schedule.mb),
            ("stash_push", schedule.stash_push),
            ("stash_pop", schedule.stash_pop),
            ("fwd_read", schedule.fwd_read),
            ("bwd_read", schedule.bwd_read),
            ("fwd_write", schedule.fwd_write),
            ("bwd_write", schedule.bwd_write),
        )
    }

    def _run(chunks_local, head_params, h):
        micro_h = h.reshape((M, mb) + h.shape[1:])
        zero_act = jnp.zeros((mb,) + h.shape[1:], h.dtype)

        def tick(carry, row):
            fwd_buf, bwd_buf, stash, gacc, hacc, dh, lacc = carry
            c, m = row["chunk"], row["mb"]
            params_c = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
                chunks_local,
            )
            args_m = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                micro_args,
            )
            injects = (s_idx == 0) & (c == 0)   # global stage 0
            is_last = (s_idx == n - 1) & (c == v - 1)

            def idle_op(_):
                return (
                    zero_act, zero_act, stash, gacc, hacc, dh,
                    jnp.float32(0.0),
                )

            def fwd_op(_):
                x_buf = _take_slot(fwd_buf, row["fwd_read"])
                h_m = lax.dynamic_index_in_dim(micro_h, m, 0, keepdims=False)
                x_in = jnp.where(injects, h_m, x_buf)
                y = stage_fn(params_c, x_in)
                new_stash = lax.dynamic_update_index_in_dim(
                    stash, x_in, jnp.maximum(row["stash_push"], 0), 0
                )
                return (
                    y, zero_act, new_stash, gacc, hacc, dh, jnp.float32(0.0)
                )

            def bwd_op(_):
                x_in = _take_slot(stash, row["stash_pop"])
                g_in = _take_slot(bwd_buf, row["bwd_read"])

                def last_case(_):
                    lval, pull = jax.vjp(
                        lambda pc, hp, xi: last_fn(pc, hp, xi, args_m),
                        params_c, head_params, x_in,
                    )
                    dp, dhp, dx = pull(jnp.ones_like(lval))
                    return lval.astype(jnp.float32), dp, dhp, dx

                def mid_case(_):
                    _, pull = jax.vjp(stage_fn, params_c, x_in)
                    dp, dx = pull(g_in)
                    zero_head = jax.tree.map(jnp.zeros_like, head_params)
                    return jnp.float32(0.0), dp, zero_head, dx

                lval, dp, dhp, dx = lax.cond(is_last, last_case, mid_case, None)

                def add_chunk(acc, d):
                    cur = lax.dynamic_index_in_dim(acc, c, 0, keepdims=False)
                    return lax.dynamic_update_index_in_dim(acc, cur + d, c, 0)

                new_gacc = jax.tree.map(add_chunk, gacc, dp)
                new_hacc = jax.tree.map(jnp.add, hacc, dhp)
                # global stage 0's input cotangent is the trunk's: bank
                # it per microbatch (other ranks' dx rides the ring out)
                cur = lax.dynamic_index_in_dim(dh, m, 0, keepdims=False)
                upd = cur + jnp.where(injects, dx, jnp.zeros_like(dx))
                new_dh = lax.dynamic_update_index_in_dim(dh, upd, m, 0)
                return (
                    zero_act, dx, stash, new_gacc, new_hacc, new_dh, lval
                )

            y_out, g_out, stash2, gacc2, hacc2, dh2, lval = lax.switch(
                row["op"], [idle_op, fwd_op, bwd_op], None
            )
            # Both rings fire every tick (SPMD lockstep); the static
            # write tables mask the garbage hops.
            y_in = lax.ppermute(y_out, axis_name, perm_right)
            g_arr = lax.ppermute(g_out, axis_name, perm_left)
            fwd_buf2 = _store_slot(fwd_buf, y_in, row["fwd_write"])
            bwd_buf2 = _store_slot(bwd_buf, g_arr, row["bwd_write"])
            return (
                fwd_buf2, bwd_buf2, stash2, gacc2, hacc2, dh2, lacc + lval
            ), None

        init = (
            jnp.zeros((schedule.fwd_depth, mb) + h.shape[1:], h.dtype),
            jnp.zeros((schedule.bwd_depth, mb) + h.shape[1:], h.dtype),
            jnp.zeros((schedule.stash_depth, mb) + h.shape[1:], h.dtype),
            jax.tree.map(jnp.zeros_like, chunks_local),
            jax.tree.map(jnp.zeros_like, head_params),
            jnp.zeros_like(micro_h),
            jnp.float32(0.0),
        )
        (_, _, _, gacc, hacc, dh, lacc), _ = lax.scan(tick, init, rows)
        # losses accumulate on the last rank only; mean over microbatches,
        # replicated everywhere (the trainer's loss contract)
        loss = lax.psum(lacc, axis_name) / M
        inv = 1.0 / M  # seeds were 1.0 per microbatch; grads are of sum
        scale = lambda t: jax.tree.map(  # noqa: E731
            lambda a: (a * inv).astype(a.dtype), t
        )
        return loss, (scale(gacc), scale(hacc), (dh * inv).reshape(h.shape))

    # custom_vjp boundary: the forward pass already computed the exact
    # gradients (that is what interleaved BWD ticks ARE), so autodiff
    # just scales them by the incoming loss cotangent.
    @jax.custom_vjp
    def engine(chunks_local, head_params, h):
        return _run(chunks_local, head_params, h)[0]

    def engine_fwd(chunks_local, head_params, h):
        return _run(chunks_local, head_params, h)

    def engine_bwd(grads, g):
        dchunks, dhead, dh = grads
        scale = lambda t: jax.tree.map(  # noqa: E731
            lambda a: (a * g).astype(a.dtype), t
        )
        return scale(dchunks), scale(dhead), (dh * g).astype(dh.dtype)

    engine.defvjp(engine_fwd, engine_bwd)
    return engine(chunks_local, head_params, h)


def stage_cost_programs(
    stage_fns: list, stage_params: list, x0
) -> tuple[list[dict], list, list]:
    """Per-global-stage jitted forward/backward programs for MEASURED
    F/B cost tables — the pipeline hook `observe.attribution.
    measure_stage_costs` drives (ROADMAP item 4: cost-weighted schedules
    need measured per-stage costs, and the textbook tables assume every
    F and B tick costs the same, which an embedding-heavy stage 0 or a
    vocab-head-heavy stage n−1 breaks).

    ``stage_fns[s]`` is ``(params_s, x) -> y`` for each GLOBAL stage in
    order; the last one returns the scalar microbatch loss.  Stages may
    be heterogeneous in both shape and cost — the forward chain is run
    once (eagerly) to materialize each stage's input.  Returns
    ``(programs, inputs, outputs)`` where ``programs[s]`` carries
    ``{"stage", "fwd", "bwd"}``: ``fwd(params, x)`` is the jitted stage
    forward, ``bwd(params, x, cotangent)`` the jitted VJP pull (the
    backward tick's recompute-and-pull, exactly what the 1F1B executor's
    BWD op runs per microbatch)."""
    if len(stage_fns) != len(stage_params):
        raise ValueError(
            f"{len(stage_fns)} stage fns vs {len(stage_params)} stage "
            f"param trees"
        )
    progs, inputs, outputs = [], [], []
    x = x0
    for s, fn in enumerate(stage_fns):
        def bwd(p, xi, g, fn=fn):
            _, pull = jax.vjp(fn, p, xi)
            return pull(g)

        progs.append({"stage": s, "fwd": jax.jit(fn), "bwd": jax.jit(bwd)})
        inputs.append(x)
        x = fn(stage_params[s], x)
        outputs.append(x)
    return progs, inputs, outputs


def engine_program(
    stage_fn: Callable,
    last_fn: Callable,
    schedule: Schedule,
    mesh,
    *,
    axis_name: str = PIPE_AXIS,
    remat_stages: bool = False,
):
    """The 1F1B engine as ONE jitted SPMD program — the lowering entry
    `tpu_dist.analysis` (and any HLO inspection) uses.

    Wraps `pipeline_engine_loss` in ``shard_map`` over ``axis_name``
    (each rank dynamic-slices its chunk params from the replicated
    stacked pytree, exactly the executor-parity test harness) and
    returns a jitted ``fn(stacked, head_params, h, loss_args) -> (loss,
    (chunk_grads, head_grads))`` whose gradients are psum'd over the
    pipe axis per the engine's gradient contract.  ``.lower(...)`` /
    ``.trace(...)`` on the result expose the compiled collectives: the
    fwd/bwd neighbor ppermute rings firing every tick plus the final
    gradient psum — nothing else should appear on the wire."""
    from jax.sharding import PartitionSpec as P

    def per_rank(stacked, head_params, h, loss_args):
        r = lax.axis_index(axis_name)

        def loss(stacked, head_params):
            chunks_local = jax.tree.map(
                lambda t: lax.dynamic_index_in_dim(t, r, 0, keepdims=False),
                stacked,
            )
            return pipeline_engine_loss(
                stage_fn, last_fn, schedule, chunks_local, head_params,
                h, loss_args, axis_name=axis_name,
                remat_stages=remat_stages,
            )

        l, grads = jax.value_and_grad(loss, argnums=(0, 1))(
            stacked, head_params
        )
        return l, jax.tree.map(lambda a: lax.psum(a, axis_name), grads)

    mapped = jax.shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=(P(), (P(), P())),
        check_vma=False,
    )
    return jax.jit(mapped)
