"""Ring collectives over ``lax.ppermute`` — the hand-rolled allreduce,
rebuilt correctly.

The reference hand-rolls a DeepSpeech-style ring allreduce over p2p
(allreduce.py:8-34, prose tuto.md:322-354) — but the shipped code is buggy
(zeros circulate; it accumulates the function *arguments* instead of the
received buffers — SURVEY.md §2c.1) and both drivers fall back to the
built-in collective (allreduce.py:44-45).  Here we implement the *intended*
algorithm natively:

- `ring_all_reduce`: the naive ring — ``n-1`` steps, each rank forwards the
  buffer it received last step to ``right = (rank+1) % n`` and accumulates
  (the double-buffer alternation of allreduce.py:22-32 becomes a
  ``lax.scan`` carry; isend/wait overlap becomes XLA async dispatch of the
  CollectivePermute).
- `ring_reduce_scatter` + `ring_all_gather` and the bandwidth-optimal
  chunked `ring_all_reduce_chunked` — the "reduce-scatter followed by
  all-gather" exercise the tutorial leaves to the reader (tuto.md:354).
  Each rank moves ``2·(n-1)/n`` of the payload instead of ``n-1`` copies.

All are cross-checked against ``lax.psum`` in tests (the north-star parity
requirement, BASELINE.md) and must match within fp tolerance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm as _ring_perm
from tpu_dist.comm.mesh import DEFAULT_AXIS


def ring_all_reduce(x: jax.Array, axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Naive ring allreduce: ``n-1`` full-tensor hops.

    Step i: forward the buffer received at step i-1 (initially the local
    tensor) to the right neighbor; accumulate what arrives from the left.
    After ``n-1`` steps every rank has summed every contribution exactly
    once.  This is the algorithm allreduce.py:8-34 *intends* (SURVEY.md
    §2c.1 documents the reference's bug).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    perm = _ring_perm(n)

    def step(carry, _):
        acc, buf = carry
        buf = lax.ppermute(buf, axis_name, perm)
        return (acc + buf, buf), None

    (acc, _), _ = lax.scan(step, (x, x), None, length=n - 1)
    return acc


from tpu_dist.utils.tree import pad_to_multiple as _pad_to_multiple


def ring_reduce_scatter(x: jax.Array, axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Ring reduce-scatter: after ``n-1`` chunk hops, rank r holds the fully
    reduced chunk ``(r+1) % n`` of the flattened (zero-padded) input.

    Returns the owned chunk, shape ``(ceil(size/n),)``.  Chunk ownership is
    the standard ring schedule: at step t, rank r sends chunk ``(r-t) % n``
    and reduces into chunk ``(r-t-1) % n``.
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    chunks = _pad_to_multiple(x.reshape(-1), n).reshape(n, -1)
    if n == 1:
        return chunks[0]
    perm = _ring_perm(n)

    def step(chunks, t):
        send_idx = (r - t) % n
        recv_idx = (r - t - 1) % n
        buf = lax.dynamic_index_in_dim(chunks, send_idx, 0, keepdims=False)
        buf = lax.ppermute(buf, axis_name, perm)
        updated = lax.dynamic_index_in_dim(chunks, recv_idx, 0, keepdims=False) + buf
        return lax.dynamic_update_index_in_dim(chunks, updated, recv_idx, 0), None

    chunks, _ = lax.scan(step, chunks, jnp.arange(n - 1))
    return lax.dynamic_index_in_dim(chunks, (r + 1) % n, 0, keepdims=False)


def ring_all_gather(
    chunk: jax.Array,
    axis_name: str = DEFAULT_AXIS,
    *,
    owner_offset: int = 0,
) -> jax.Array:
    """Ring all-gather: rank r starts owning chunk ``(r + owner_offset) % n``;
    after ``n-1`` hops every rank holds all chunks, ordered by owner index.

    Returns shape ``(n,) + chunk.shape``.
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    out = jnp.zeros((n,) + chunk.shape, chunk.dtype)
    out = lax.dynamic_update_index_in_dim(out, chunk, (r + owner_offset) % n, 0)
    if n == 1:
        return out
    perm = _ring_perm(n)

    def step(carry, t):
        out, buf = carry
        buf = lax.ppermute(buf, axis_name, perm)
        # arrived from rank r-1-t, who owns chunk (r-1-t+owner_offset) % n
        idx = (r - 1 - t + owner_offset) % n
        out = lax.dynamic_update_index_in_dim(out, buf, idx, 0)
        return (out, buf), None

    (out, _), _ = lax.scan(step, (out, chunk), jnp.arange(n - 1))
    return out


def ring_all_reduce_chunked(
    x: jax.Array, axis_name: str = DEFAULT_AXIS
) -> jax.Array:
    """Bandwidth-optimal ring allreduce = reduce-scatter + all-gather
    (the tuto.md:354 exercise).  ``2·(n-1)`` hops of ``size/n`` each."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    own = ring_reduce_scatter(x, axis_name)  # rank r owns chunk (r+1) % n
    gathered = ring_all_gather(own, axis_name, owner_offset=1)
    flat = gathered.reshape(-1)[: x.size]
    return flat.reshape(x.shape)
