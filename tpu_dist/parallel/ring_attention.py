"""Ring attention — sequence/context parallelism over the ppermute ring.

The reference has no sequence models (SURVEY.md §2d records SP/CP as
absent; its only ring is the ring *allreduce*, allreduce.py:18-32), but the
communication topology is identical: blocks circulate around the same
neighbor ring the hand-rolled allreduce uses.  This module makes
long-context a first-class capability: sequences sharded over a mesh axis,
K/V blocks rotated via ``lax.ppermute``, attention accumulated blockwise
with a numerically-stable streaming softmax (the log-sum-exp running
rescale of Flash/Ring attention), so no device ever materializes the full
(seq × seq) score matrix or the full K/V.

Communication per step rides ICI exactly like `ring_all_reduce`; compute
(the two einsums) stays on the MXU, and XLA overlaps the next block's
CollectivePermute with the current block's matmuls inside the scanned body.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm.collectives import ring_perm

NEG_INF = -1e30


def _block_update(m, l, acc, logits, v_blk, mask):
    """One streaming-softmax accumulation step.

    m: (..., sq) running row max;  l: (..., sq) running denominator;
    acc: (..., sq, d) running numerator; logits: (..., sq, sk);
    mask: broadcastable to logits (True = attend).
    """
    logits = jnp.where(mask, logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(-1))
    # Rescale previous accumulation; exp of fully-masked entries is zeroed
    # by re-masking (NEG_INF is finite, so no NaNs from inf - inf).
    correction = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    p = jnp.where(mask, p, 0.0)
    l_new = l * correction + p.sum(-1)
    acc_new = acc * correction[..., None] + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Blockwise ring attention over sequence shards.

    Args:
      q, k, v: local shards of shape ``(..., s_local, d)`` (e.g.
        ``(batch, heads, s_local, d)``), with the sequence axis sharded
        over mesh axis ``axis_name``; global sequence order is rank-major.
      causal: apply a causal mask over *global* positions.
      window: sliding-window band ``k > q - window`` over *global*
        positions (combine with ``causal`` for the Mistral-style local
        band) — same semantics as `nn.dot_product_attention(window=)`,
        so windowed models train sequence-parallel == dense.

    Returns the local output shard ``(..., s_local, d)`` in the input
    dtype.  Numerically matches `tpu_dist.nn.dot_product_attention` on the
    gathered sequence (tests assert this on the simulated mesh).
    Accumulators (running max / denominator / numerator) are kept in
    float32 regardless of input dtype — with bf16 inputs on long
    sequences, accumulating thousands of exp terms in an 8-bit mantissa
    would destroy the streaming softmax (standard flash/ring practice).
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    s_local = q.shape[-2]
    d = q.shape[-1]
    scale = d**-0.5
    qs = (q * scale).astype(q.dtype)

    perm = ring_perm(n)
    lead = q.shape[:-2]
    m0 = jnp.full(lead + (s_local,), NEG_INF, jnp.float32)
    l0 = jnp.zeros(lead + (s_local,), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)

    local_pos = jnp.arange(s_local)

    def block_step(m, l, acc, k_blk, v_blk, kv_rank):
        # MXU matmul in input precision; softmax bookkeeping in f32.
        logits = jnp.einsum(
            "...qd,...kd->...qk", qs, k_blk, preferred_element_type=jnp.float32
        )
        q_pos = r * s_local + local_pos  # global query positions
        k_pos = kv_rank * s_local + local_pos
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        if window is not None:
            mask = mask & (k_pos[None, :] > q_pos[:, None] - window)
        return _block_update(m, l, acc, logits, v_blk, mask)

    # Local block first, then n-1 steps of (rotate, process): exactly
    # 2(n-1) CollectivePermutes — rotating after the LAST block would ship
    # a full K+V around the ring only to be discarded.
    m, l, acc = block_step(m0, l0, acc0, k, v, r)

    def step(carry, t):
        m, l, acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        # after t+1 rotations we hold the block from rank (r - t - 1) mod n
        kv_rank = (r - t - 1) % n
        m, l, acc = block_step(m, l, acc, k_blk, v_blk, kv_rank)
        return (m, l, acc, k_blk, v_blk), None

    if n > 1:
        (m, l, acc, _, _), _ = lax.scan(
            step, (m, l, acc, k, v), jnp.arange(n - 1)
        )
    return (acc / l[..., None]).astype(q.dtype)


def _ring_flash_impl(q, k, v, axis_name, causal, bq, bk, interpret):
    import functools as _ft

    from tpu_dist.ops.flash_attention import flash_attention_lse

    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    s_local = q.shape[-2]
    perm = ring_perm(n)
    flash = _ft.partial(
        flash_attention_lse, bq=bq, bk=bk, interpret=interpret
    )

    def combine(m, l, acc, out_b, lse_b):
        # blocks arrive pre-normalized; lse re-weights them exactly
        m_new = jnp.maximum(m, lse_b)
        c = jnp.exp(m - m_new)
        w = jnp.exp(lse_b - m_new)
        return (
            m_new,
            l * c + w,
            acc * c[..., None] + w[..., None] * out_b.astype(jnp.float32),
        )

    m = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:-1], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    # The DIAGONAL block is always the first processed (kv starts as the
    # local shard), so the causal-within-block kernel variant is selected
    # statically — one flash call per block, never two.
    out_b, lse_b = flash(q, k, v, causal=causal)
    m, l, acc = combine(m, l, acc, out_b, lse_b)

    def step(carry, t):
        m, l, acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        kv_rank = (r - t - 1) % n
        # off-diagonal: fully visible, unless the kv block belongs to a
        # LATER rank under the causal mask — then its weight is zeroed
        # via lse = -inf (SPMD lockstep computes the block regardless)
        out_b, lse_b = flash(q, k_blk, v_blk, causal=False)
        if causal:
            lse_b = jnp.where(kv_rank > r, NEG_INF, lse_b)
        m, l, acc = combine(m, l, acc, out_b, lse_b)
        return (m, l, acc, k_blk, v_blk), None

    if n > 1:
        (m, l, acc, _, _), _ = lax.scan(
            step, (m, l, acc, k, v), jnp.arange(n - 1)
        )
    # fully-masked rows cannot occur: the diagonal block always
    # contributes (causal attends at least to self), so l > 0
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    bq: int = 256,
    bk: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """`ring_attention` with each block computed by the Pallas flash
    kernel: the (s_local, s_local) score block never round-trips HBM —
    the canonical long-context composition (a ring of flash blocks,
    recombined exactly via each block's log-sum-exp).

    Same contract as `ring_attention` (sequence shards, rank-major
    global order, causal over global positions) and numerically equal to
    it (tested).  Differentiable: the VJP recomputes through the
    dense-block ring — the same function, so gradients are exact; the
    flash path pays off on the forward (prefill/eval are forward-only,
    and in training the backward already streams blockwise).
    """
    import functools as _ft

    @_ft.partial(jax.custom_vjp)
    def rf(q, k, v):
        return _ring_flash_impl(q, k, v, axis_name, causal, bq, bk, interpret)

    def rf_fwd(q, k, v):
        return rf(q, k, v), (q, k, v)

    def rf_bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: ring_attention(
                q_, k_, v_, axis_name, causal=causal
            ),
            q, k, v,
        )
        return vjp(g)

    rf.defvjp(rf_fwd, rf_bwd)
    return rf(q, k, v)


class RingMultiHeadAttention:
    """Sequence-parallel MHA module: drop-in for
    `tpu_dist.nn.MultiHeadAttention` inside shard_map'd code whose inputs
    are sequence shards over ``axis_name``.

    QKV/out projections are token-local (no communication); only the
    attention core rotates K/V blocks around the ring.  Init is identical
    to the dense module's, so the same checkpoint runs sharded or not —
    tests assert numerical agreement with the unsharded module.
    """

    def __init__(self, dim: int, heads: int, *, axis_name: str,
                 causal: bool = False, use_rope: bool = False,
                 use_flash: bool = False, interpret: bool = False,
                 core: str = "ring", sliding_window: int | None = None):
        from tpu_dist import nn  # local import: nn must not depend on parallel

        if core not in ("ring", "ulysses"):
            raise ValueError(f"core must be 'ring' or 'ulysses', got {core!r}")
        if sliding_window is not None and use_flash and core != "ulysses":
            # (the ulysses core never consults use_flash — its local
            # attention is full-sequence, so the band applies exactly)
            raise ValueError(
                "sliding_window is not supported with use_flash yet — "
                "the per-block flash kernels have no cross-shard band "
                "offset; use the dense blockwise ring or ulysses cores"
            )
        self.sliding_window = sliding_window
        self.core = core
        self.axis_name = axis_name
        self.causal = causal
        self.use_rope = use_rope
        # use_flash: compute each ring block with the Pallas flash kernel
        # (`ring_attention_flash`) instead of the dense blockwise core —
        # same numbers, no (s_local, s_local) HBM round-trip per block.
        # interpret only matters with use_flash (CPU-sim testing).
        self.use_flash = use_flash
        self.interpret = interpret
        self._dense = nn.MultiHeadAttention(
            dim, heads, causal=causal, use_rope=use_rope
        )
        self.dim = dim
        self.heads = heads
        self.head_dim = dim // heads

    def init(self, key, input_shape):
        return self._dense.init(key, input_shape)

    def out_shape(self, input_shape):
        return input_shape

    def apply(self, params, state, x, *, train=False, key=None):
        d = self._dense
        b, s_local, _ = x.shape
        qkv, _ = d._qkv.apply(params["qkv"], {}, x)
        qkv = qkv.reshape(b, s_local, 3, self.heads, self.head_dim)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        if self.use_rope:
            # rope is a pure function of each token's GLOBAL position, so
            # rotating the local q/k shards before the ring reproduces the
            # dense rope attention exactly (K blocks travel pre-rotated).
            from jax import lax

            from tpu_dist import nn

            r = lax.axis_index(self.axis_name)
            pos = r * s_local + jnp.arange(s_local)
            q, k = nn.rope(q, pos), nn.rope(k, pos)
        if self.core == "ulysses":
            # all-to-all head resharding: full-sequence attention on a
            # head subset (q/k enter pre-rotated by GLOBAL position, so
            # rope survives the resharding exactly)
            from tpu_dist.parallel.ulysses import ulysses_attention

            o = ulysses_attention(
                q, k, v, self.axis_name, causal=self.causal,
                window=self.sliding_window,
            )
        elif self.use_flash:
            o = ring_attention_flash(
                q, k, v, self.axis_name, causal=self.causal,
                interpret=self.interpret,
            )
        else:
            o = ring_attention(
                q, k, v, self.axis_name, causal=self.causal,
                window=self.sliding_window,
            )
        o = jnp.moveaxis(o, 1, 2).reshape(b, s_local, self.dim)
        y, _ = d._out.apply(params["out"], {}, o)
        return y, state
