"""Tensor (model) parallelism helpers.

The reference has no TP (SURVEY.md §2d: "non-goal for parity; design the
mesh API so a `model` axis is expressible").  This module makes that
expressibility concrete with the two canonical sharded-matmul forms, so a
2-D ``('data', 'model')`` mesh is a working configuration, not a claim:

- `column_parallel`: weights split on the OUTPUT dim; each rank computes
  its slice of the output; no communication (activations replicated in,
  sharded out).
- `row_parallel`: weights split on the INPUT dim; each rank contributes a
  partial product; one ``psum`` over the model axis completes the matmul
  (sharded in, replicated out).

The Megatron pattern — column-parallel up-projection, row-parallel
down-projection, one collective per MLP block — is `tp_mlp`, tested
against the unsharded computation on a 2-D mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

MODEL_AXIS = "model"


def shard_dim(w: jax.Array, axis_name: str, dim: int) -> jax.Array:
    """Slice this rank's piece of a replicated weight along ``dim`` —
    helper for entering shard_map'd TP code with replicated params."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if w.shape[dim] % n:
        raise ValueError(
            f"dim {dim} of shape {w.shape} not divisible by axis size {n}"
        )
    piece = w.shape[dim] // n
    return lax.dynamic_slice_in_dim(w, r * piece, piece, dim)


def column_parallel(
    x: jax.Array, w_shard: jax.Array, axis_name: str = MODEL_AXIS
) -> jax.Array:
    """x @ W with W column-sharded: returns this rank's output slice
    (no communication)."""
    return x @ w_shard


def row_parallel(
    x_shard: jax.Array, w_shard: jax.Array, axis_name: str = MODEL_AXIS
) -> jax.Array:
    """x @ W with W row-sharded and x correspondingly column-sharded:
    psum of partial products -> replicated output (ONE collective)."""
    return lax.psum(x_shard @ w_shard, axis_name)


def tp_mlp(
    x: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    axis_name: str = MODEL_AXIS,
    *,
    activation=jax.nn.gelu,
) -> jax.Array:
    """Megatron-style MLP: gelu(x @ W_up) @ W_down with ONE psum total.

    ``w_up``/``w_down`` are passed replicated; each rank slices its shard
    (cols of W_up, rows of W_down).  The activation applies to the
    column-sharded hidden states, so no communication happens between the
    two matmuls.
    """
    up = shard_dim(w_up, axis_name, 1)
    down = shard_dim(w_down, axis_name, 0)
    hidden = activation(column_parallel(x, up, axis_name))
    return row_parallel(hidden, down, axis_name)
