"""Tensor (model) parallelism helpers.

The reference has no TP (SURVEY.md §2d: "non-goal for parity; design the
mesh API so a `model` axis is expressible").  This module makes that
expressibility concrete with the two canonical sharded-matmul forms, so a
2-D ``('data', 'model')`` mesh is a working configuration, not a claim:

- `column_parallel`: weights split on the OUTPUT dim; each rank computes
  its slice of the output; no communication (activations replicated in,
  sharded out).
- `row_parallel`: weights split on the INPUT dim; each rank contributes a
  partial product; one ``psum`` over the model axis completes the matmul
  (sharded in, replicated out).

The Megatron pattern — column-parallel up-projection, row-parallel
down-projection, one collective per MLP block — is `tp_mlp`, tested
against the unsharded computation on a 2-D mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

MODEL_AXIS = "model"


def shard_dim(w: jax.Array, axis_name: str, dim: int) -> jax.Array:
    """Slice this rank's piece of a replicated weight along ``dim`` —
    helper for entering shard_map'd TP code with replicated params."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if w.shape[dim] % n:
        raise ValueError(
            f"dim {dim} of shape {w.shape} not divisible by axis size {n}"
        )
    piece = w.shape[dim] // n
    return lax.dynamic_slice_in_dim(w, r * piece, piece, dim)


def column_parallel(
    x: jax.Array, w_shard: jax.Array, axis_name: str = MODEL_AXIS
) -> jax.Array:
    """x @ W with W column-sharded: returns this rank's output slice
    (no communication)."""
    return x @ w_shard


def row_parallel(
    x_shard: jax.Array, w_shard: jax.Array, axis_name: str = MODEL_AXIS
) -> jax.Array:
    """x @ W with W row-sharded and x correspondingly column-sharded:
    psum of partial products -> replicated output (ONE collective)."""
    return lax.psum(x_shard @ w_shard, axis_name)


def tp_mlp(
    x: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    axis_name: str = MODEL_AXIS,
    *,
    activation=jax.nn.gelu,
) -> jax.Array:
    """Megatron-style MLP: gelu(x @ W_up) @ W_down with ONE psum total.

    ``w_up``/``w_down`` are passed replicated; each rank slices its shard
    (cols of W_up, rows of W_down).  The activation applies to the
    column-sharded hidden states, so no communication happens between the
    two matmuls.
    """
    up = shard_dim(w_up, axis_name, 1)
    down = shard_dim(w_down, axis_name, 0)
    hidden = activation(column_parallel(x, up, axis_name))
    return row_parallel(hidden, down, axis_name)


def tp_mlp_block(
    x: jax.Array,
    mlp_params,
    axis_name: str = MODEL_AXIS,
    *,
    activation=jax.nn.gelu,
) -> jax.Array:
    """`tp_mlp` over the model zoo's MLP param pytree
    (``{"fc1": {"w","b"}, "fc2": {"w","b"}}`` — models/vit.py MLP),
    biases included: fc1's bias is column-sharded with its weights, fc2's
    is added once after the psum.  Still exactly ONE collective."""
    w1 = shard_dim(mlp_params["fc1"]["w"], axis_name, 1)
    b1 = shard_dim(mlp_params["fc1"]["b"], axis_name, 0)
    w2 = shard_dim(mlp_params["fc2"]["w"], axis_name, 0)
    hidden = activation(x @ w1 + b1)
    return lax.psum(hidden @ w2, axis_name) + mlp_params["fc2"]["b"]


def tp_attention(
    x: jax.Array,
    attn_params,
    heads: int,
    axis_name: str = MODEL_AXIS,
    *,
    causal: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Megatron-style sharded-heads attention: each rank runs
    ``heads / axis_size`` complete heads locally and the row-parallel
    output projection finishes with ONE psum.

    ``attn_params`` is `nn.MultiHeadAttention`'s replicated pytree —
    either the fused layout (``{"qkv", "out"}``) or the GQA layout
    (``{"q", "kv", "out"}``).  The Q projection is column-parallel per
    head: the kernel's output layout is ``(3, heads, head_dim)`` /
    ``(heads, head_dim)`` (attention.py reshape), so the per-rank shard
    slices the HEAD axis of the reshaped kernel — a head never straddles
    ranks, which is what keeps softmax communication-free.  Under GQA the
    (small) K/V projection runs replicated on every rank and each local
    query head selects its group's kv head — same single psum.
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if heads % n:
        raise ValueError(f"heads {heads} not divisible by axis size {n}")
    hl = heads // n
    bsz, s, _ = x.shape

    from tpu_dist.nn.attention import dot_product_attention

    if "qkv" in attn_params:
        w = attn_params["qkv"]["w"]
        d = w.shape[0]
        hd = w.shape[1] // (3 * heads)
        w_loc = lax.dynamic_slice_in_dim(
            w.reshape(d, 3, heads, hd), r * hl, hl, 2
        ).reshape(d, 3 * hl * hd)
        b_loc = lax.dynamic_slice_in_dim(
            attn_params["qkv"]["b"].reshape(3, heads, hd), r * hl, hl, 1
        ).reshape(3 * hl * hd)
        qkv = (x @ w_loc + b_loc).reshape(bsz, s, 3, hl, hd)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    else:  # GQA tree {"q", "kv", "out"}
        wq = attn_params["q"]["w"]
        d = wq.shape[0]
        hd = wq.shape[1] // heads
        kv_heads = attn_params["kv"]["w"].shape[1] // (2 * hd)
        group = heads // kv_heads
        wq_loc = lax.dynamic_slice_in_dim(
            wq.reshape(d, heads, hd), r * hl, hl, 1
        ).reshape(d, hl * hd)
        bq_loc = lax.dynamic_slice_in_dim(
            attn_params["q"]["b"].reshape(heads, hd), r * hl, hl, 0
        ).reshape(hl * hd)
        q = jnp.moveaxis(
            (x @ wq_loc + bq_loc).reshape(bsz, s, hl, hd), 1, 2
        )
        kv = (x @ attn_params["kv"]["w"] + attn_params["kv"]["b"]).reshape(
            bsz, s, 2, kv_heads, hd
        )
        k_full, v_full = (jnp.moveaxis(kv[:, :, i], 1, 2) for i in range(2))
        # local query head i (global r*hl + i) reads kv head (global)//group
        kv_idx = (r * hl + jnp.arange(hl)) // group
        k = jnp.take(k_full, kv_idx, axis=1)
        v = jnp.take(v_full, kv_idx, axis=1)

    # full-sequence attention on local heads: the sliding-window band
    # applies exactly as in the dense path
    o = dot_product_attention(q, k, v, causal=causal, window=window)  # (b, hl, s, hd)
    o = jnp.moveaxis(o, 1, 2).reshape(bsz, s, hl * hd)

    wo_loc = lax.dynamic_slice_in_dim(
        attn_params["out"]["w"], r * hl * hd, hl * hd, 0
    )
    return lax.psum(o @ wo_loc, axis_name) + attn_params["out"]["b"]


def tp_attention_cached(
    x: jax.Array,
    attn_params,
    heads: int,
    k_cache: jax.Array,
    v_cache: jax.Array,
    index,
    axis_name: str = MODEL_AXIS,
    *,
    use_rope: bool = False,
    window: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sharded-heads incremental attention for tensor-parallel DECODE:
    each rank runs ``heads / n`` complete heads against its OWN slice of
    the KV cache — cache HBM and attention FLOPs both drop n-fold per
    chip — and the row-parallel output projection finishes with ONE
    psum, exactly like `tp_attention`.  Same math as
    `nn.MultiHeadAttention.apply_cached` restricted to the local heads
    (tests assert the gathered decode matches the dense one).

    Layouts: fused QKV (``{"qkv","out"}``; per-rank cache
    ``(b, heads/n, L, hd)``) or GQA (``{"q","kv","out"}``; requires
    ``kv_heads % n == 0``, per-rank cache ``(b, kv_heads/n, L, hd)`` —
    a query head's kv group never straddles ranks because contiguous
    q-head shards map to contiguous kv-head shards).  Rope rotates the
    local q/k by absolute position, which is head-independent, so both
    position schemes work.

    ``x``: (b, s, d) replicated new tokens at global positions
    ``index..index+s-1``.  Returns ``(y replicated, k_cache, v_cache)``.
    """
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    if heads % n:
        raise ValueError(f"heads {heads} not divisible by axis size {n}")
    hl = heads // n
    b, s, d = x.shape
    if "qkv" in attn_params:
        group = 1  # local kv head j serves local q head j
        w = attn_params["qkv"]["w"]
        hd = w.shape[1] // (3 * heads)
        w_loc = lax.dynamic_slice_in_dim(
            w.reshape(d, 3, heads, hd), r * hl, hl, 2
        ).reshape(d, 3 * hl * hd)
        b_loc = lax.dynamic_slice_in_dim(
            attn_params["qkv"]["b"].reshape(3, heads, hd), r * hl, hl, 1
        ).reshape(3 * hl * hd)
        qkv = (x @ w_loc + b_loc).reshape(b, s, 3, hl, hd)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    else:  # GQA tree {"q", "kv", "out"}
        wq = attn_params["q"]["w"]
        hd = wq.shape[1] // heads
        kv_heads = attn_params["kv"]["w"].shape[1] // (2 * hd)
        if kv_heads % n:
            raise ValueError(
                f"kv_heads {kv_heads} not divisible by axis size {n} — "
                "the per-rank KV cache cannot be head-sharded"
            )
        kvl = kv_heads // n
        group = heads // kv_heads
        wq_loc = lax.dynamic_slice_in_dim(
            wq.reshape(d, heads, hd), r * hl, hl, 1
        ).reshape(d, hl * hd)
        bq_loc = lax.dynamic_slice_in_dim(
            attn_params["q"]["b"].reshape(heads, hd), r * hl, hl, 0
        ).reshape(hl * hd)
        q = jnp.moveaxis((x @ wq_loc + bq_loc).reshape(b, s, hl, hd), 1, 2)
        wkv_loc = lax.dynamic_slice_in_dim(
            attn_params["kv"]["w"].reshape(d, 2, kv_heads, hd),
            r * kvl, kvl, 2,
        ).reshape(d, 2 * kvl * hd)
        bkv_loc = lax.dynamic_slice_in_dim(
            attn_params["kv"]["b"].reshape(2, kv_heads, hd), r * kvl, kvl, 1
        ).reshape(2 * kvl * hd)
        kv = (x @ wkv_loc + bkv_loc).reshape(b, s, 2, kvl, hd)
        k, v = (jnp.moveaxis(kv[:, :, i], 1, 2) for i in range(2))
    if use_rope:
        from tpu_dist.nn.attention import rope

        pos = index + jnp.arange(s)
        q, k = rope(q, pos), rope(k, pos)
    k_cache = lax.dynamic_update_slice_in_dim(
        k_cache, k.astype(k_cache.dtype), index, axis=2
    )
    v_cache = lax.dynamic_update_slice_in_dim(
        v_cache, v.astype(v_cache.dtype), index, axis=2
    )
    cache_len = k_cache.shape[2]
    scale = hd**-0.5
    # GQA: repeat each local kv head for its group of local q heads
    # (local q head j reads local kv head j // group — the contiguous
    # shard slices keep global alignment)
    k_full = jnp.repeat(k_cache, group, axis=1) if group > 1 else k_cache
    v_full = jnp.repeat(v_cache, group, axis=1) if group > 1 else v_cache
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q * scale, k_full.astype(q.dtype)
    )
    pos_k = jnp.arange(cache_len)[None, :]
    qpos = index + jnp.arange(s)[:, None]
    visible = pos_k <= qpos
    if window is not None:
        # same band as the parallel forward (k > q - window): windowed
        # decode matches windowed training exactly
        visible = visible & (pos_k > qpos - window)
    logits = jnp.where(visible, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", weights, v_full.astype(q.dtype))
    o = jnp.moveaxis(o, 1, 2).reshape(b, s, hl * hd)
    wo_loc = lax.dynamic_slice_in_dim(
        attn_params["out"]["w"], r * hl * hd, hl * hd, 0
    )
    y = lax.psum(o @ wo_loc, axis_name) + attn_params["out"]["b"]
    return y, k_cache, v_cache


def tp_vocab_cross_entropy(
    h: jax.Array,
    table: jax.Array,
    targets: jax.Array,
    axis_name: str = MODEL_AXIS,
) -> jax.Array:
    """Vocab-parallel softmax cross-entropy (the Megatron output layer).

    Each rank computes logits for its slice of the vocabulary
    (``h @ table_slice.T``) — the full ``(b, s, V)`` logits tensor is
    NEVER materialized, which is what makes large-vocab TP heads fit.
    The softmax normalizer and the target's logit are reassembled with
    three tiny collectives (pmax for the stable max, two psums), each
    ``O(b·s)`` — not ``O(b·s·V)``.

    Args: ``h`` (b, s, d) replicated activations, ``table`` (V, d)
    replicated (the weight-tied embedding table), ``targets`` (b, s)
    int labels.  Returns the mean cross-entropy, identical to the dense
    computation (tested)."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    V = table.shape[0]
    if V % n:
        raise ValueError(f"vocab {V} not divisible by axis size {n}")
    Vl = V // n
    table_loc = lax.dynamic_slice_in_dim(table, r * Vl, Vl, 0)
    # (b, s, Vl) — only the local slice; f32 like lm_loss (the matmul may
    # be bf16 under a compute dtype, but the softmax reduction must not)
    logits = (h @ table_loc.T).astype(jnp.float32)
    # The max shift is numerics only — logsumexp is shift-invariant, so
    # its gradient contribution cancels analytically; stop_gradient both
    # reflects that and sidesteps pmax's missing differentiation rule.
    # (stop_gradient must wrap pmax's INPUT: a symbolically-zero tangent
    # skips the primitive's missing JVP rule entirely)
    m = lax.pmax(lax.stop_gradient(logits.max(axis=-1)), axis_name)
    z = lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis_name
    )
    in_range = (targets >= r * Vl) & (targets < (r + 1) * Vl)
    local_idx = jnp.clip(targets - r * Vl, 0, Vl - 1)
    picked = jnp.take_along_axis(logits, local_idx[..., None], axis=-1)[
        ..., 0
    ]
    target_logit = lax.psum(jnp.where(in_range, picked, 0.0), axis_name)
    return jnp.mean(-(target_logit - m - jnp.log(z)))


def tp_embedding(
    tokens: jax.Array,
    table: jax.Array,
    axis_name: str = MODEL_AXIS,
) -> jax.Array:
    """Vocab-parallel embedding lookup (Megatron input layer): each rank
    looks up only tokens that fall in its vocabulary slice (out-of-range
    tokens contribute zeros) and one psum assembles the full embeddings —
    the gather never touches more than ``V/n`` rows per rank.  Pairs with
    `tp_vocab_cross_entropy` at the output."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    V = table.shape[0]
    if V % n:
        raise ValueError(f"vocab {V} not divisible by axis size {n}")
    Vl = V // n
    table_loc = lax.dynamic_slice_in_dim(table, r * Vl, Vl, 0)
    in_range = (tokens >= r * Vl) & (tokens < (r + 1) * Vl)
    local = jnp.clip(tokens - r * Vl, 0, Vl - 1)
    emb = table_loc[local] * in_range[..., None]
    return lax.psum(emb, axis_name)


def tp_encoder_block(block, params, x, axis_name: str = MODEL_AXIS):
    """A full pre-norm transformer block (models/vit.py EncoderBlock) in
    tensor parallel: LayerNorms replicated (tiny), attention heads and
    MLP hidden dim sharded — TWO psums per block total, the Megatron
    layout.  ``block`` is the EncoderBlock instance (supplies the
    LayerNorm modules and the heads/causal config); ``params`` its
    replicated pytree.  Numerics match ``block.apply`` to fp tolerance
    (tests/test_tensor_parallel.py)."""
    if getattr(block.attn, "use_rope", False):
        raise ValueError(
            "tp_encoder_block does not apply rotary embeddings — "
            "un-rotated q/k would be silently wrong; use learned positions"
        )
    h, _ = block.ln1.apply(params["ln1"], {}, x)
    x = x + tp_attention(
        h, params["attn"], block.attn.heads, axis_name,
        causal=block.attn.causal,
        window=getattr(block.attn, "sliding_window", None),
    )
    h, _ = block.ln2.apply(params["ln2"], {}, x)
    return x + tp_mlp_block(h, params["mlp"], axis_name)
