"""Ulysses-style sequence parallelism — the all-to-all alternative to ring
attention.

Where `ring_attention` keeps queries resident and rotates K/V blocks
around the ppermute ring (communication ∝ steps, fully overlapped),
Ulysses re-shards: an all-to-all converts sequence-sharded activations
into head-sharded ones, every rank runs ordinary full-sequence attention
over its subset of heads, and a second all-to-all restores sequence
sharding.  Two collectives per attention call, no change to the attention
math — the better trade when heads ≥ world size and ICI all-to-all
bandwidth is plentiful; ring wins at extreme sequence lengths.  Both are
first-class here (the reference has neither — SURVEY.md §2d records
sequence parallelism as absent; the instructions make long-context a
required capability).
"""

from __future__ import annotations

import jax
from jax import lax

from tpu_dist.comm.collectives import all_to_all
from tpu_dist.nn.attention import dot_product_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Attention over sequence shards via head-resharding.

    Args: local shards ``(batch, heads, s_local, head_dim)`` with the
    sequence axis sharded over ``axis_name``; ``heads`` must be divisible
    by the axis size.  Returns the local output shard, numerically equal
    to full attention on the gathered sequence (tests assert this).
    """
    n = lax.axis_size(axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"heads {h} not divisible by sequence-parallel world {n} — "
            f"use ring_attention for head counts below the world size"
        )
    # seq-sharded -> head-sharded: (b, h, s_local, d) -> (b, h/n, S, d)
    reshard = lambda t: all_to_all(  # noqa: E731
        t, axis_name, split_axis=1, concat_axis=2
    )
    # after resharding every head shard holds the FULL sequence, so the
    # window band applies exactly as in the dense path
    o = dot_product_attention(
        reshard(q), reshard(k), reshard(v), causal=causal, window=window
    )
    # head-sharded -> seq-sharded: (b, h/n, S, d) -> (b, h, s_local, d)
    return all_to_all(o, axis_name, split_axis=2, concat_axis=1)
