"""`tpu_dist.resilience` — fault tolerance: chaos injection, retry/
backoff, NaN-guarded training, preemption-safe resume.

The reference stack (and the seed of this rebuild) assumes every rank
boots, every collective completes, and every step is finite; this package
holds everything that relaxes those assumptions:

- `chaos` — deterministic fault injection via ``TPU_DIST_CHAOS`` (delay/
  kill ranks at launch, fail rendezvous attempts, NaN a gradient step,
  truncate a checkpoint) so the failure paths are exercisable anywhere.
- `retry` — bounded exponential backoff with jitter (`retry_call`,
  `RetryPolicy`) and the typed failures `RendezvousTimeout` /
  `WorkerFailed`; wired into `comm.init` and the `comm.launch`
  supervisor.
- `guards` — `nan_guard`: fused non-finite skip-and-count with dynamic
  loss-scale backoff, inside the compiled train step.
- `preempt` — `PreemptionGuard`: SIGTERM/SIGINT → checkpoint at the next
  step boundary (paired with `train.checkpoint.latest_intact`).

See docs/resilience.md for the chaos grammar and the resume contract.

This module stays import-light (stdlib only) because the bootstrap paths
(`comm.init`, `comm.launch._child`) import it before JAX loads; `guards`
(which needs jax) loads lazily on first attribute access.
"""

from __future__ import annotations

from tpu_dist.resilience import chaos, preempt, retry
from tpu_dist.resilience.chaos import ChaosInjected, ChaosSpec
from tpu_dist.resilience.preempt import PreemptionGuard
from tpu_dist.resilience.retry import (
    RendezvousTimeout,
    RetryPolicy,
    WorkerFailed,
    retry_call,
)

__all__ = [
    "ChaosInjected",
    "ChaosSpec",
    "PreemptionGuard",
    "RendezvousTimeout",
    "RetryPolicy",
    "WorkerFailed",
    "bad_steps",
    "chaos",
    "guards",
    "loss_scale",
    "nan_guard",
    "preempt",
    "retry",
    "retry_call",
]


def __getattr__(name: str):
    # `guards` imports jax + train.optim; loading it at package-import
    # time would both slow the pre-JAX bootstrap paths and create an
    # import cycle through tpu_dist.train.  importlib, not a from-import:
    # `from tpu_dist.resilience import guards` re-enters this __getattr__
    # while the submodule is mid-import (infinite recursion).
    if name in ("guards", "nan_guard", "bad_steps", "loss_scale"):
        import importlib

        guards = importlib.import_module("tpu_dist.resilience.guards")
        return guards if name == "guards" else getattr(guards, name)
    raise AttributeError(f"module 'tpu_dist.resilience' has no attribute {name!r}")
