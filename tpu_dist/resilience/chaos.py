"""Deterministic fault injection — the chaos harness.

At pod scale preemption and transient failure are the common case, not
the exception, so the resilience layer (retry/backoff in `comm.init`,
the launch supervisor in `comm.launch`, NaN guards in the train step,
checksum-validated checkpoints) needs a way to be EXERCISED on a laptop:
this module injects the failures those layers exist to absorb, driven by
one env var so the same knobs work in tests, demos, and ad-hoc runs:

    TPU_DIST_CHAOS="<clause>[,<clause>...]"

Clause grammar (all values integers/floats; unknown clauses raise):

    rdzv_fail=N          fail the first N rendezvous attempts in this
                         process (raises `ChaosInjected`; the retry loop
                         in `comm.init` absorbs them with backoff)
    kill=RANK[@ATTEMPT]  at launch, rank RANK hard-exits (``os._exit``)
                         on launch attempt ATTEMPT (default 0) — the
                         supervisor's ``restarts=`` path relaunches the
                         gang, and the killed rank survives attempt 1
    delay=RANK:SECONDS   at launch, rank RANK sleeps SECONDS before
                         init (straggler simulation)
    nan_step=K           poison the gradient pytree at optimizer update
                         K (consumed by `resilience.guards.nan_guard`
                         inside the compiled step — skip-and-count)
    ckpt_truncate=FRAC   truncate the NEXT checkpoint file this process
                         writes to FRAC of its bytes (one-shot) — a
                         mid-write kill, for `checkpoint.latest_intact`
    kill_during_checkpoint=N
                         hard-exit (``os._exit``) after this process has
                         written N shard blobs of its NEXT
                         ``save_sharded`` (one-shot) — the partial
                         sharded DIRECTORY a preemption mid-save leaves
                         behind (some blobs present, the attempt marker
                         still standing); `checkpoint.latest_intact`
                         must never select it for resume
    seed=N               seed recorded on the spec for any randomized
                         knobs (reserved; injection is deterministic)

Everything here is stdlib-only and import-light: the hooks are called
from bootstrap paths (`comm.launch._child`) that run before JAX loads.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path

ENV_VAR = "TPU_DIST_CHAOS"
# Set by the launch supervisor for each relaunch attempt so kill clauses
# can be scoped to one attempt (children read it via `launch_attempt`).
ATTEMPT_ENV_VAR = "TPU_DIST_CHAOS_ATTEMPT"


class ChaosInjected(RuntimeError):
    """An injected (not organic) failure — raised where the spec says a
    real failure would have happened."""


@dataclass(frozen=True)
class ChaosSpec:
    """Parsed `TPU_DIST_CHAOS` clauses (see module docstring grammar)."""

    rdzv_fail: int = 0
    kill: dict[int, int] = field(default_factory=dict)  # rank -> attempt
    delay: dict[int, float] = field(default_factory=dict)  # rank -> seconds
    nan_step: int | None = None
    ckpt_truncate: float | None = None
    kill_during_checkpoint: int | None = None
    seed: int = 0


def parse(spec: str) -> ChaosSpec:
    """Parse a chaos spec string.  Raises ValueError on unknown clauses or
    malformed values — a typo'd chaos spec must fail loudly, not silently
    inject nothing."""
    rdzv_fail, nan_step, ckpt_truncate, seed = 0, None, None, 0
    kill_during_ckpt: int | None = None
    kill: dict[int, int] = {}
    delay: dict[int, float] = {}
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        key, sep, value = clause.partition("=")
        if not sep:
            raise ValueError(f"chaos clause {clause!r} is not key=value")
        try:
            if key == "rdzv_fail":
                rdzv_fail = int(value)
            elif key == "kill":
                rank_s, _, attempt_s = value.partition("@")
                kill[int(rank_s)] = int(attempt_s) if attempt_s else 0
            elif key == "delay":
                rank_s, sep2, sec_s = value.partition(":")
                if not sep2:
                    raise ValueError("delay needs RANK:SECONDS")
                delay[int(rank_s)] = float(sec_s)
            elif key == "nan_step":
                nan_step = int(value)
            elif key == "ckpt_truncate":
                ckpt_truncate = float(value)
                if not 0.0 <= ckpt_truncate < 1.0:
                    raise ValueError("ckpt_truncate must be in [0, 1)")
            elif key == "kill_during_checkpoint":
                kill_during_ckpt = int(value)
                if kill_during_ckpt < 1:
                    raise ValueError(
                        "kill_during_checkpoint needs N >= 1 blobs"
                    )
            elif key == "seed":
                seed = int(value)
            else:
                raise ValueError(f"unknown chaos clause {key!r}")
        except ValueError as e:
            raise ValueError(
                f"bad chaos clause {clause!r} in {ENV_VAR}={spec!r}: {e}"
            ) from None
    return ChaosSpec(
        rdzv_fail=rdzv_fail, kill=kill, delay=delay, nan_step=nan_step,
        ckpt_truncate=ckpt_truncate, kill_during_checkpoint=kill_during_ckpt,
        seed=seed,
    )


def active() -> ChaosSpec | None:
    """The spec from the environment, or None when chaos is off.  Read
    fresh on every call (tests flip the env var between cases)."""
    spec = os.environ.get(ENV_VAR)
    return parse(spec) if spec else None


def launch_attempt() -> int:
    """Which launch/relaunch attempt this process belongs to (set by the
    `comm.launch` supervisor; 0 outside a supervised launch)."""
    try:
        return int(os.environ.get(ATTEMPT_ENV_VAR, "0"))
    except ValueError:
        return 0


# --- hooks -------------------------------------------------------------------


def rendezvous_attempt(attempt: int) -> None:
    """Gate one rendezvous attempt: raises `ChaosInjected` while
    ``attempt < rdzv_fail``.  Called by the retry loop in `comm.init`
    with its attempt index, so every process with the same spec fails
    (and backs off) in lockstep."""
    spec = active()
    if spec is not None and attempt < spec.rdzv_fail:
        raise ChaosInjected(
            f"chaos: rendezvous attempt {attempt} failed "
            f"(rdzv_fail={spec.rdzv_fail})"
        )


def _emit_chaos_event(clause: str, rank: int) -> None:
    """Record an injection into the structured event log (no-op when
    ``TPU_DIST_TELEMETRY`` is unset): a chaos run's events file shows
    WHAT was injected next to what the resilience layer did about it."""
    try:
        from tpu_dist.observe import events as ev_mod

        ev_mod.from_env(rank=rank).emit("chaos", clause=clause)
    except Exception:
        pass  # injection must proceed even if telemetry is broken


def at_launch(rank: int) -> None:
    """Launch-time injection for one child rank: sleep (``delay=``) or
    hard-exit (``kill=``, scoped to `launch_attempt`).  Called by
    `comm.launch._child` before any init work."""
    spec = active()
    if spec is None:
        return
    if rank in spec.delay:
        import time

        _emit_chaos_event(f"delay={rank}:{spec.delay[rank]}", rank)
        time.sleep(spec.delay[rank])
    if spec.kill.get(rank) == launch_attempt():
        # A hard exit, not an exception: the parent must observe a child
        # that died without reporting — the failure mode the supervisor
        # detects via pipe EOF.  The event line is flushed on emit, so it
        # survives the _exit.
        _emit_chaos_event(f"kill={rank}@{launch_attempt()}", rank)
        kill_with_dump(f"kill={rank}@{launch_attempt()}")


def kill_with_dump(clause: str, code: int = 17) -> None:
    """The chaos hard-exit: dump the flight-recorder ring (atexit never
    runs after ``os._exit``, so the dump must happen here), then die.
    Exposed so tests can inject a mid-training kill through the same
    path a launch-time ``kill=`` clause takes."""
    try:
        from tpu_dist.observe import flightrec

        flightrec.get().record("mark", what="chaos_kill", clause=clause)
        flightrec.crash_dump("chaos_kill")
    except Exception:
        pass
    os._exit(code)


def nan_injection_step() -> int | None:
    """The optimizer-update index at which `guards.nan_guard` poisons the
    gradient pytree (None = no injection).  Read once at wrapper build
    time — set the env var before constructing the trainer."""
    spec = active()
    return spec.nan_step if spec is not None else None


_truncate_armed = True


def maybe_truncate_checkpoint(path) -> bool:
    """One-shot hook called by `train.checkpoint.save` after a write: if
    the spec has ``ckpt_truncate``, truncate the file in place (simulating
    a kill mid-write) and disarm.  Returns True when it fired."""
    global _truncate_armed
    spec = active()
    if spec is None or spec.ckpt_truncate is None or not _truncate_armed:
        return False
    _truncate_armed = False
    truncate_file(path, spec.ckpt_truncate)
    return True


_kill_ckpt_armed = True


def checkpoint_blob_written(written: int, total: int) -> None:
    """One-shot hook called by `train.checkpoint._write_sharded` after
    each shard blob lands: with ``kill_during_checkpoint=N``, hard-exit
    once N blobs are written (clamped to this process's blob count, so
    the clause always fires mid-save) — exercising the partial sharded
    directory `checkpoint.latest_intact` must skip."""
    global _kill_ckpt_armed
    spec = active()
    if (
        spec is None
        or spec.kill_during_checkpoint is None
        or not _kill_ckpt_armed
    ):
        return
    if written >= min(spec.kill_during_checkpoint, total):
        _kill_ckpt_armed = False
        clause = f"kill_during_checkpoint={spec.kill_during_checkpoint}"
        try:
            rank = int(os.environ.get("TPU_DIST_TELEMETRY_RANK")
                       or os.environ.get("RANK") or 0)
        except ValueError:
            rank = 0
        _emit_chaos_event(clause, rank)
        kill_with_dump(clause)


def truncate_file(path, frac: float = 0.5) -> None:
    """Truncate ``path`` to ``frac`` of its bytes — the on-disk state a
    preemption mid-write leaves behind."""
    path = Path(path)
    size = path.stat().st_size
    with open(path, "r+b") as f:
        f.truncate(int(size * frac))


def reset() -> None:
    """Re-arm one-shot injections (tests run many cases per process)."""
    global _truncate_armed, _kill_ckpt_armed
    _truncate_armed = True
    _kill_ckpt_armed = True
