"""Step guards — fused non-finite detection with skip-and-count.

One NaN step kills an unguarded run: the optimizer writes NaN into every
parameter and the remaining epochs train garbage.  `nan_guard` wraps any
`tpu_dist.train.optim.Optimizer` so the whole check runs INSIDE the
compiled train step: the gradient pytree is reduced to a single
all-finite predicate, the inner update is computed, and ``where`` selects
old-vs-new params and optimizer state — a bad step is skipped (params and
inner state bit-identical to before), counted (``bad_steps``), and
training continues.

For bf16 compute the guard also carries a dynamic loss scale with
escalating backoff: on every bad step ``scale *= backoff``; after
``growth_interval`` consecutive good steps ``scale *= growth`` (clamped
to ``[min_scale, max_scale]``).  The explicit shard_map step
(`parallel.make_spmd_train_step` and its wrappers) reads the live
scale via ``current_scale`` and threads it through the loss/grad
computation (scaled backward, unscaled grads + reported loss); the
partition engine (`make_partitioned_train_step` — where the trainers'
dp/fsdp/zero1 flags route) provides skip-and-count only and uses the
guard's presence to poison gradients on a non-finite loss before the
compressed wire's all-finite predicate (no scale threading — the
trainers refuse ``loss_scale`` under engine-routed configs; documented
in docs/resilience.md).

Chaos: when ``TPU_DIST_CHAOS`` has a ``nan_step=K`` clause at wrapper
construction time, the guard itself poisons the (post-reduce) gradient
pytree at update K — the injection travels the exact path a real NaN
would, so the skip semantics are testable end to end.

Follows the wrapper precedent of `train.optim.clip_by_global_norm` /
`with_ema`: state nests the inner optimizer's under ``"inner"`` plus the
guard scalars, so checkpointing works unchanged.  Apply `nan_guard`
OUTERMOST (e.g. ``nan_guard(clip_by_global_norm(adamw(...), 1.0))``) —
the step builders discover ``current_scale`` on the top-level optimizer.
"""

from __future__ import annotations

import functools
import operator
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from tpu_dist.train.optim import Optimizer, _inner_sharded


@dataclass(frozen=True)
class GuardedOptimizer(Optimizer):
    """An `Optimizer` whose state carries guard scalars; ``current_scale``
    lets the step builders read the live loss scale from the state."""

    current_scale: Callable[[Any], Any] | None = None


def _all_finite(grads: Any) -> jax.Array:
    """One boolean: every element of every floating leaf is finite."""
    checks = [
        jnp.all(jnp.isfinite(g))
        for g in jax.tree.leaves(grads)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
    ]
    if not checks:
        return jnp.array(True)
    return functools.reduce(operator.and_, checks)


def _select(ok: jax.Array, new: Any, old: Any) -> Any:
    """``where(ok, new, old)`` leafwise — the skip."""
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)


def _poison(grads: Any, cond: jax.Array) -> Any:
    """NaN every floating gradient leaf where ``cond`` (chaos injection)."""
    return jax.tree.map(
        lambda g: jnp.where(cond, jnp.asarray(jnp.nan, g.dtype), g)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
        else g,
        grads,
    )


def nan_guard(
    optimizer: Optimizer,
    *,
    init_scale: float = 1.0,
    backoff: float = 0.5,
    growth: float = 2.0,
    growth_interval: int = 200,
    min_scale: float = 1.0,
    max_scale: float = 2.0**16,
) -> GuardedOptimizer:
    """Wrap ``optimizer`` with fused non-finite skip-and-count plus a
    dynamic loss scale (see module docstring).

    State: ``{"inner": <wrapped state>, "step", "bad_steps",
    "good_streak", "scale"}`` — all scalars device-resident, so the guard
    adds no host sync to the step.  Read the counters back with
    `bad_steps` / `loss_scale` (also re-exported via `train.metrics`).
    """
    if not 0.0 < backoff < 1.0:
        raise ValueError(f"backoff must be in (0, 1), got {backoff}")
    if growth < 1.0:
        raise ValueError(f"growth must be >= 1, got {growth}")
    if growth_interval < 1:
        raise ValueError(
            f"growth_interval must be >= 1, got {growth_interval}"
        )
    if not min_scale <= init_scale <= max_scale:
        raise ValueError(
            f"need min_scale <= init_scale <= max_scale, got "
            f"{min_scale} / {init_scale} / {max_scale}"
        )
    from tpu_dist.resilience import chaos

    # Static at trace time: the injection compiles into the step (or
    # compiles away entirely when chaos is off).
    inject_step = chaos.nan_injection_step()

    def init(params):
        return {
            "inner": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
            "bad_steps": jnp.zeros((), jnp.int32),
            "good_streak": jnp.zeros((), jnp.int32),
            "scale": jnp.asarray(init_scale, jnp.float32),
        }

    def _guard_scalars(state, ok):
        good_streak = jnp.where(ok, state["good_streak"] + 1, 0)
        grow = ok & (good_streak >= growth_interval)
        good_streak = jnp.where(grow, 0, good_streak)
        scale = jnp.where(
            ok,
            jnp.where(grow, state["scale"] * growth, state["scale"]),
            state["scale"] * backoff,
        )
        return {
            "step": state["step"] + 1,
            "bad_steps": state["bad_steps"] + jnp.where(ok, 0, 1),
            "good_streak": good_streak,
            "scale": jnp.clip(scale, min_scale, max_scale),
        }

    def _maybe_inject(grads, state):
        if inject_step is None:
            return grads
        return _poison(grads, state["step"] == inject_step)

    def update(params, grads, state):
        grads = _maybe_inject(grads, state)
        ok = _all_finite(grads)
        # Compute-then-select (the optax.apply_if_finite pattern): the
        # inner update runs unconditionally — NaNs in its outputs are
        # discarded by the select, never stored.
        new_params, new_inner = optimizer.update(params, grads, state["inner"])
        return _select(ok, new_params, params), {
            "inner": _select(ok, new_inner, state["inner"]),
            **_guard_scalars(state, ok),
        }

    # Sharded form: finiteness is a GLOBAL property — one rank's NaN
    # shard must skip the update on every rank, so the non-finite count
    # is psum'd over the data axis before the select (same shape as
    # clip_by_global_norm's psum of squared shard norms).
    inner_sharded = _inner_sharded(optimizer)
    if inner_sharded is not None:
        def shard_update(params, grads, state, axis_name):
            from jax import lax

            grads = _maybe_inject(grads, state)
            bad_local = sum(
                jnp.sum(~jnp.isfinite(g)).astype(jnp.float32)
                for g in jax.tree.leaves(grads)
                if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
            )
            ok = lax.psum(bad_local, axis_name) == 0
            new_params, new_inner = inner_sharded(
                params, grads, state["inner"], axis_name
            )
            return _select(ok, new_params, params), {
                "inner": _select(ok, new_inner, state["inner"]),
                **_guard_scalars(state, ok),
            }
    else:
        shard_update = None

    return GuardedOptimizer(
        init, update, elementwise=False, shard_update=shard_update,
        current_scale=lambda state: state["scale"],
    )


def _guard_state(tree: Any):
    """The `nan_guard` scalar dict inside an optimizer state, or None.
    Anchored on the ``bad_steps`` key (unique to the guard) so parameter
    trees that legitimately contain ``"scale"`` leaves (LayerNorm
    mirrors in adamw's m/v) never false-positive."""
    if isinstance(tree, dict):
        if "bad_steps" in tree and "scale" in tree:
            return tree
        for v in tree.values():
            found = _guard_state(v)
            if found is not None:
                return found
    return None


def bad_steps(opt_state: Any) -> int | None:
    """Cumulative skipped-step count from a `nan_guard` optimizer state
    (None when the state is unguarded)."""
    g = _guard_state(opt_state)
    return None if g is None else int(g["bad_steps"])


def loss_scale(opt_state: Any) -> float | None:
    """Live dynamic loss scale from a `nan_guard` optimizer state (None
    when unguarded)."""
    g = _guard_state(opt_state)
    return None if g is None else float(g["scale"])
