"""Preemption handling — SIGTERM/SIGINT → checkpoint at the next step
boundary.

Preemptible capacity (and every cluster scheduler's drain path) delivers
SIGTERM with a grace window.  `PreemptionGuard` converts that async
signal into a cooperative flag the training loop polls between steps:
the trainers (`train.Trainer.fit` / `train.LMTrainer.fit`) check
``requested`` after every step, write a synchronous checkpoint, and
return cleanly — so ``--resume`` via `checkpoint.latest_intact` always
finds consistent state, never a half-written file.

A SECOND SIGINT raises `KeyboardInterrupt` immediately (the operator's
escape hatch when the checkpoint itself hangs).

Scope: the flag is PER PROCESS, not gang-coordinated.  That matches how
preemption actually arrives — a pod drain / spot reclaim SIGTERMs every
host — and costs no per-step collective.  A signal delivered to only ONE
process of a multi-process gang stops that process alone while its peers
block in the next collective; don't use single-host signals as a gang
stop (kill the launcher / every worker instead).
"""

from __future__ import annotations

import signal
import threading


class PreemptionGuard:
    """Context manager installing cooperative SIGTERM/SIGINT handlers.

    Usable only from the main thread (CPython restriction on
    ``signal.signal``); elsewhere it degrades to an inert flag — training
    in a worker thread simply doesn't get preemption handling, it is
    never broken by it.  Previous handlers are restored on exit.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._previous: dict[int, object] = {}
        self._requested = False
        self._signum: int | None = None

    @property
    def requested(self) -> bool:
        """True once a shutdown signal arrived — checkpoint and stop."""
        return self._requested

    @property
    def signal_name(self) -> str | None:
        return signal.Signals(self._signum).name if self._signum else None

    def _handle(self, signum, frame):
        if self._requested and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self._requested = True
        self._signum = signum

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # inert off the main thread
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handle)
            except (ValueError, OSError):  # unsupported signal/environment
                pass
        return self

    def __exit__(self, *exc_info):
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._previous.clear()
        return False
