"""Retry / timeout / backoff — bounded, jittered, observable.

The reference stack's failure model is fail-stop with no recovery
(SURVEY.md §5: a blocked peer plus ``join()``); at pod scale the launch
path needs the opposite default: transient rendezvous and coordinator
failures are absorbed by bounded exponential backoff with jitter, and
only *persistent* failure surfaces — as a clean typed error
(`RendezvousTimeout`, `WorkerFailed`) instead of a hang.

`retry_call` is deliberately dependency-injectable (``sleep``, ``clock``,
``rng``, ``log``) so the backoff schedule is unit-testable with a fake
clock — no real sleeping in tier-1 tests.

Env knobs (read by `RetryPolicy.from_env`, used by `comm.init`):

    TPU_DIST_RDZV_RETRIES      max attempts (default 5)
    TPU_DIST_RDZV_BASE_DELAY   first backoff in seconds (default 0.25)
    TPU_DIST_RDZV_MAX_DELAY    backoff cap in seconds (default 8.0)
    TPU_DIST_STARTUP_DEADLINE  overall deadline in seconds (default none)
"""

from __future__ import annotations

import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Any, Callable

logger = logging.getLogger("tpu_dist.resilience")


def _emit_retry_event(
    describe: str, attempt: int, policy: "RetryPolicy",
    error: BaseException, backoff_s: float,
) -> None:
    """Mirror one retry/backoff into the structured event log (no-op
    when ``TPU_DIST_TELEMETRY`` is unset) — the ``log`` line above keeps
    the human-readable surface, this keeps the machine-parseable one."""
    try:
        from tpu_dist.observe import events as ev_mod

        ev_mod.from_env().emit(
            "retry",
            what=describe,
            attempt=attempt + 1,
            max_attempts=policy.max_attempts,
            error=f"{type(error).__name__}: {error}",
            backoff_s=round(backoff_s, 3),
        )
    except Exception:
        pass  # telemetry must never turn a retried failure into a fatal one


class RendezvousTimeout(RuntimeError):
    """Bootstrap rendezvous / distributed init did not succeed within the
    retry budget or startup deadline."""


class WorkerFailed(RuntimeError):
    """A launched worker died (or failed) and the supervisor's restart
    budget is exhausted."""


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff: attempt ``i`` sleeps
    ``min(base_delay * multiplier**i, max_delay)``, scaled by a uniform
    jitter factor in ``[1 - jitter, 1 + jitter]`` (decorrelates thundering
    herds — every worker of a gang retries on the same schedule
    otherwise).  ``deadline`` bounds the WHOLE operation in seconds,
    whatever the attempt count."""

    max_attempts: int = 5
    base_delay: float = 0.25
    max_delay: float = 8.0
    multiplier: float = 2.0
    jitter: float = 0.25
    deadline: float | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        d = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * rng.uniform(-1.0, 1.0)
        return d

    @staticmethod
    def from_env() -> "RetryPolicy":
        def _get(name, cast, default):
            raw = os.environ.get(name)
            if raw is None:
                return default
            try:
                return cast(raw)
            except ValueError:
                raise ValueError(f"{name}={raw!r} is not a valid {cast.__name__}")

        return RetryPolicy(
            max_attempts=_get("TPU_DIST_RDZV_RETRIES", int, 5),
            base_delay=_get("TPU_DIST_RDZV_BASE_DELAY", float, 0.25),
            max_delay=_get("TPU_DIST_RDZV_MAX_DELAY", float, 8.0),
            deadline=_get("TPU_DIST_STARTUP_DEADLINE", float, None),
        )


def retry_call(
    fn: Callable[[int], Any],
    *,
    policy: RetryPolicy | None = None,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    describe: str = "operation",
    error_type: type[Exception] | None = None,
    log: Callable[[str], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: random.Random | None = None,
) -> Any:
    """Call ``fn(attempt)`` under ``policy``, backing off between failed
    attempts.  ``fn`` receives the 0-based attempt index (chaos gates and
    logging key off it).

    Gives up when attempts are exhausted OR the policy deadline elapses,
    then raises ``error_type`` (chained to the last failure) when given,
    else re-raises the last failure.  Each backoff emits one ``log`` line
    ("attempt i/n failed ...; backing off d s") — the observable that
    lets an operator distinguish a retrying bootstrap from a hang.
    """
    policy = policy or RetryPolicy()
    log = log or logger.warning
    rng = rng or random.Random()
    start = clock()
    last: BaseException | None = None
    attempt = 0
    for attempt in range(policy.max_attempts):
        try:
            return fn(attempt)
        except retry_on as e:
            last = e
            elapsed = clock() - start
            out_of_time = (
                policy.deadline is not None and elapsed >= policy.deadline
            )
            if attempt + 1 >= policy.max_attempts or out_of_time:
                break
            d = policy.delay(attempt, rng)
            if policy.deadline is not None:
                d = min(d, max(policy.deadline - elapsed, 0.0))
            log(
                f"{describe}: attempt {attempt + 1}/{policy.max_attempts} "
                f"failed ({type(e).__name__}: {e}); backing off {d:.2f}s"
            )
            _emit_retry_event(describe, attempt, policy, e, d)
            sleep(d)
    assert last is not None
    if error_type is not None:
        raise error_type(
            f"{describe} failed after {attempt + 1} attempt(s) in "
            f"{clock() - start:.1f}s: {type(last).__name__}: {last}"
        ) from last
    raise last
