"""``python -m tpu_dist.run`` — the external script launcher.

The reference launches distributed jobs two ways: an in-script fork-join
``__main__`` (train_dist.py:138-147) and an EXTERNAL launcher
(``mpirun -n 4 python myscript.py``, tuto.md:393-398) that sets rank and
world size for an unmodified script.  `tpu_dist.comm.launch` is the
first; this module is the second — the torchrun/mpirun analog:

    python -m tpu_dist.run --nproc 4 myscript.py --arg value

It spawns ``nproc`` copies of the script with the reference's rendezvous
environment contract set (MASTER_ADDR / MASTER_PORT / WORLD_SIZE / RANK
— tuto.md:421-428); the script reads them via `comm.InitConfig.from_env`
(or plain ``os.environ``) exactly like a reference script reads them
under mpirun.  ``--rankless`` omits RANK so ranks are assigned
first-come-first-served by the native rendezvous (the ``mpirun``-style
rank-less init of allreduce.py:54).

Fail-stop semantics (the reference's failure model): the first child
that exits non-zero causes the launcher to terminate the rest and exit
with that code.  Child stdout/stderr pass through, line-buffered, with
a ``[rank N]`` prefix (``--no-tag`` disables).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading


def _stream(proc, rank: int, tag: bool):
    prefix = f"[rank {rank}] " if tag else ""
    for line in proc.stdout:
        sys.stdout.write(f"{prefix}{line}")
        sys.stdout.flush()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.run",
        description="Launch N copies of a script with the distributed "
        "rendezvous environment set (torchrun/mpirun analog).",
    )
    ap.add_argument("--nproc", type=int, required=True, help="world size")
    ap.add_argument("--master-addr", default="127.0.0.1")
    ap.add_argument(
        "--master-port", type=int, default=0,
        help="0 = pick a free port",
    )
    ap.add_argument(
        "--rankless", action="store_true",
        help="omit RANK; ranks assigned FCFS by the native rendezvous",
    )
    ap.add_argument("--no-tag", action="store_true",
                    help="don't prefix child output with [rank N]")
    ap.add_argument("script", help="python script to run per rank")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.nproc < 1:
        ap.error("--nproc must be >= 1")

    port = args.master_port
    if not port:
        from tpu_dist import runtime

        port = runtime.free_port()

    procs: list[subprocess.Popen] = []
    threads = []
    for rank in range(args.nproc):
        env = dict(os.environ)
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = str(port)
        env["WORLD_SIZE"] = str(args.nproc)
        if args.rankless:
            env.pop("RANK", None)
        else:
            env["RANK"] = str(rank)
        p = subprocess.Popen(
            [sys.executable, args.script, *args.script_args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        procs.append(p)
        t = threading.Thread(
            target=_stream, args=(p, rank, not args.no_tag), daemon=True
        )
        t.start()
        threads.append(t)

    # fail-stop: first non-zero exit kills the rest (reference failure
    # model: blocked peers + join, SURVEY.md §5)
    rc = 0
    alive = set(range(args.nproc))
    while alive:
        for r in sorted(alive):
            code = procs[r].poll()
            if code is None:
                continue
            alive.discard(r)
            if code != 0 and rc == 0:
                rc = code
                sys.stderr.write(
                    f"[tpu_dist.run] rank {r} exited with {code}; "
                    f"terminating remaining ranks\n"
                )
                for other in alive:
                    procs[other].terminate()
        if alive:
            try:
                procs[next(iter(alive))].wait(timeout=0.1)
            except subprocess.TimeoutExpired:
                pass
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
