"""`tpu_dist.runtime` — native (C++) runtime components.

The reference's native layer is THD's C++ transport/rendezvous
(tuto.md:404-419); ours is `rendezvous.cc`, loaded via ctypes (no pybind11
in this image).  The library is built lazily with g++ on first use (or
``make -C tpu_dist/runtime``) and cached.

API:
  - `rendezvous(addr, port, world, rank=-1, payload="", timeout_ms=...)`
    → ``(my_rank, {rank: payload})`` — master/worker bootstrap with rank
    assignment and a startup barrier.
  - `free_port()` → an available loopback TCP port.
  - `read_idx(path)` → numpy array via the native mmap reader
    (`idx_reader.cc`) — the data-loading native fast path; the pure-numpy
    parser in `tpu_dist.data.mnist` is the fallback.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).parent
_LIB_PATH = _HERE / "build" / "librendezvous.so"
_lock = threading.Lock()
_lib = None


def _build() -> Path:
    subprocess.run(
        ["make", "-s", "-C", str(_HERE)],
        check=True,
        capture_output=True,
        text=True,
    )
    return _LIB_PATH


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists():
            _build()
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.td_rendezvous.restype = ctypes.c_int
        lib.td_rendezvous.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.td_free_port.restype = ctypes.c_int
        lib.td_last_error.restype = ctypes.c_char_p
        _lib = lib
        return lib


_idx_lib = None


def _load_idx():
    global _idx_lib
    with _lock:
        if _idx_lib is not None:
            return _idx_lib
        path = _HERE / "build" / "libidxreader.so"
        if not path.exists():
            _build()
        lib = ctypes.CDLL(str(path))
        lib.td_idx_open.restype = ctypes.c_void_p
        lib.td_idx_open.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ]
        lib.td_idx_close.argtypes = [ctypes.c_void_p]
        lib.td_idx_last_error.restype = ctypes.c_char_p
        _idx_lib = lib
        return lib


def read_idx(path):
    """Parse an IDX file via the native mmap reader.

    Returns a numpy uint8 array: ``(n, rows, cols)`` for image files,
    ``(n,)`` for label files.  The data is copied out of the mapping
    (so the handle can be closed immediately); for the 60k MNIST train
    set this is one 45 MB memcpy from page cache — no Python-level
    byte shuffling.
    """
    import numpy as np

    lib = _load_idx()
    dims = (ctypes.c_int64 * 4)()
    data = ctypes.POINTER(ctypes.c_ubyte)()
    handle = lib.td_idx_open(str(path).encode(), dims, ctypes.byref(data))
    if not handle:
        err = lib.td_idx_last_error().decode() or "unknown idx error"
        raise ValueError(f"native IDX read failed: {err}")
    try:
        n, rows, cols, payload = dims[0], dims[1], dims[2], dims[3]
        # Read exactly the byte count C++ validated against the mapping —
        # never re-derive it here (an undersized read bound is the only
        # thing standing between a crafted header and a SIGBUS).
        arr = np.ctypeslib.as_array(data, shape=(payload,)).copy()
    finally:
        lib.td_idx_close(handle)
    return arr.reshape((n, rows, cols) if rows else (n,))


def free_port() -> int:
    """A loopback TCP port where BOTH ``port`` and ``port + 1`` are free —
    the bootstrap uses the pair (rendezvous / JAX coordinator)."""
    port = _load().td_free_port()
    if port == 0:
        raise OSError("could not find a free port pair")
    return port


def file_rendezvous(
    path,
    world: int,
    rank: int = -1,
    payload: str = "",
    timeout_s: float = 30.0,
) -> tuple[int, dict[int, str]]:
    """Shared-filesystem rendezvous — the ``file://`` init method
    (tuto.md:430-437): processes coordinate through one file guarded by
    ``fcntl`` advisory locks (the same syscall the reference's C path
    uses; Python's ``fcntl`` module is a direct wrapper).

    Each process appends a ``rank payload`` registration under an
    exclusive lock (``rank=-1`` takes the next free slot, FCFS like the
    TCP master) and then polls until all ``world`` registrations exist.
    Returns ``(my_rank, {rank: payload})``.  Single-host/multi-process
    dev only — multi-host jobs should use the TCP `rendezvous`.
    """
    import fcntl
    import time
    from pathlib import Path as _Path

    path = _Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    deadline = time.monotonic() + timeout_s

    def read_table(f) -> dict[int, str]:
        f.seek(0)
        table: dict[int, str] = {}
        for line in f.read().decode().splitlines():
            r, _, pl = line.partition(" ")
            table[int(r)] = pl
        return table

    my_rank = None
    with open(path, "a+b") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            table = read_table(f)
            if len(table) >= world:
                raise RuntimeError(
                    f"file rendezvous: {path} already has {len(table)} "
                    f"registrations for world {world} (stale file?)"
                )
            if rank >= 0:
                if rank >= world:
                    raise RuntimeError(
                        f"file rendezvous: rank {rank} out of range for "
                        f"world {world}"
                    )
                if rank in table:
                    raise RuntimeError(
                        f"file rendezvous: rank {rank} already registered "
                        f"in {path}"
                    )
                my_rank = rank
            else:
                my_rank = next(
                    (r for r in range(world) if r not in table), None
                )
                if my_rank is None:
                    raise RuntimeError(
                        f"file rendezvous: no free rank slot in {path} "
                        f"for world {world} (stale file?)"
                    )
            f.write(f"{my_rank} {payload}\n".encode())
            f.flush()
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)
    # Startup barrier: wait until every slot is registered.
    while True:
        with open(path, "rb") as f:
            fcntl.flock(f, fcntl.LOCK_SH)
            try:
                table = read_table(f)
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
        if len(table) >= world:
            return my_rank, table
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"file rendezvous: only {len(table)}/{world} processes "
                f"registered in {path} before timeout"
            )
        time.sleep(0.05)


def rendezvous(
    addr: str,
    port: int,
    world: int,
    rank: int = -1,
    payload: str = "",
    timeout_ms: int = 30_000,
) -> tuple[int, dict[int, str]]:
    """Master/worker bootstrap (tuto.md:404-419 contract, natively).

    ``rank=0`` acts as master (binds ``addr:port``); ``rank=-1`` requests
    master-assigned rank (the MPI-style rank-less init of allreduce.py:54).
    Blocks until all ``world`` processes have joined (startup barrier) or
    the timeout elapses — fail-stop, matching the reference's failure model
    (SURVEY.md §5 'Failure detection').

    Returns ``(my_rank, peer_table)`` where ``peer_table[r]`` is rank r's
    registered payload string.
    """
    lib = _load()
    buf = ctypes.create_string_buffer(1 << 16)
    got = lib.td_rendezvous(
        addr.encode(),
        port,
        world,
        rank,
        payload.encode(),
        timeout_ms,
        buf,
        len(buf),
    )
    if got < 0:
        err = lib.td_last_error().decode() or "unknown rendezvous failure"
        raise RuntimeError(f"rendezvous failed (addr={addr}:{port}): {err}")
    lines = buf.value.decode().strip().split("\n")
    peers: dict[int, str] = {}
    for line in lines[1:]:
        r, _, pl = line.partition(" ")
        peers[int(r)] = pl
    return got, peers
