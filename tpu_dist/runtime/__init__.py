"""`tpu_dist.runtime` — native (C++) runtime components.

The reference's native layer is THD's C++ transport/rendezvous
(tuto.md:404-419); ours is `rendezvous.cc`, loaded via ctypes (no pybind11
in this image).  The library is built lazily with g++ on first use (or
``make -C tpu_dist/runtime``) and cached.

API:
  - `rendezvous(addr, port, world, rank=-1, payload="", timeout_ms=...)`
    → ``(my_rank, {rank: payload})`` — master/worker bootstrap with rank
    assignment and a startup barrier.
  - `free_port()` → an available loopback TCP port.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).parent
_LIB_PATH = _HERE / "build" / "librendezvous.so"
_lock = threading.Lock()
_lib = None


def _build() -> Path:
    subprocess.run(
        ["make", "-s", "-C", str(_HERE)],
        check=True,
        capture_output=True,
        text=True,
    )
    return _LIB_PATH


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists():
            _build()
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.td_rendezvous.restype = ctypes.c_int
        lib.td_rendezvous.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.td_free_port.restype = ctypes.c_int
        lib.td_last_error.restype = ctypes.c_char_p
        _lib = lib
        return lib


def free_port() -> int:
    """A loopback TCP port where BOTH ``port`` and ``port + 1`` are free —
    the bootstrap uses the pair (rendezvous / JAX coordinator)."""
    port = _load().td_free_port()
    if port == 0:
        raise OSError("could not find a free port pair")
    return port


def rendezvous(
    addr: str,
    port: int,
    world: int,
    rank: int = -1,
    payload: str = "",
    timeout_ms: int = 30_000,
) -> tuple[int, dict[int, str]]:
    """Master/worker bootstrap (tuto.md:404-419 contract, natively).

    ``rank=0`` acts as master (binds ``addr:port``); ``rank=-1`` requests
    master-assigned rank (the MPI-style rank-less init of allreduce.py:54).
    Blocks until all ``world`` processes have joined (startup barrier) or
    the timeout elapses — fail-stop, matching the reference's failure model
    (SURVEY.md §5 'Failure detection').

    Returns ``(my_rank, peer_table)`` where ``peer_table[r]`` is rank r's
    registered payload string.
    """
    lib = _load()
    buf = ctypes.create_string_buffer(1 << 16)
    got = lib.td_rendezvous(
        addr.encode(),
        port,
        world,
        rank,
        payload.encode(),
        timeout_ms,
        buf,
        len(buf),
    )
    if got < 0:
        err = lib.td_last_error().decode() or "unknown rendezvous failure"
        raise RuntimeError(f"rendezvous failed (addr={addr}:{port}): {err}")
    lines = buf.value.decode().strip().split("\n")
    peers: dict[int, str] = {}
    for line in lines[1:]:
        r, _, pl = line.partition(" ")
        peers[int(r)] = pl
    return got, peers
