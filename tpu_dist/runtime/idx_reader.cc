// Native IDX-format reader — the data-loading half of the runtime.
//
// The reference's input pipeline rides torch's native DataLoader machinery
// (C++ worker pool feeding the Python loop, train_dist.py:89); tpu_dist's
// Python path is already vectorized numpy, and this component provides the
// native fast path: mmap the IDX file (zero-copy page-cache reads), parse
// the header, and hand Python a pointer it wraps as a numpy array without
// a userspace copy.  ctypes-bound like rendezvous.cc (no pybind11).
//
// IDX format (as written by the original MNIST distribution):
//   u32 magic (0x801 labels / 0x803 images, big-endian)
//   u32 count [, u32 rows, u32 cols for images]
//   payload bytes
//
// Build: make -C tpu_dist/runtime

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {
thread_local char g_err[256] = {0};

uint32_t be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
}  // namespace

extern "C" {

const char* td_idx_last_error() { return g_err; }

// Maps the file and parses the header.
// On success returns a handle pointer and fills:
//   dims_out[0..2] = count, rows, cols (rows/cols 0 for labels)
//   dims_out[3]    = validated payload size in bytes (what Python may
//                    safely read — never re-derive it host-side)
//   data_out = pointer to payload (valid until td_idx_close)
// Returns nullptr on failure (see td_idx_last_error).
void* td_idx_open(const char* path, int64_t* dims_out,
                  const unsigned char** data_out) {
  g_err[0] = 0;
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    snprintf(g_err, sizeof(g_err), "open %s: %s", path, strerror(errno));
    return nullptr;
  }
  struct stat st{};
  if (fstat(fd, &st) < 0 || st.st_size < 8) {
    snprintf(g_err, sizeof(g_err), "stat %s: bad size", path);
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                   MAP_PRIVATE, fd, 0);
  close(fd);  // mapping keeps the file alive
  if (map == MAP_FAILED) {
    snprintf(g_err, sizeof(g_err), "mmap %s: %s", path, strerror(errno));
    return nullptr;
  }
  const unsigned char* p = static_cast<const unsigned char*>(map);
  uint32_t magic = be32(p);
  // Unsigned 64-bit size math: u32 inputs make every product below at
  // most 2^96, so check step-by-step against the real file size instead
  // of trusting any multiplication (a crafted header must not be able to
  // wrap the bound — Python reads exactly payload_bytes, and an
  // undersized mapping means SIGBUS, not an exception).
  uint64_t count = be32(p + 4), rows = 0, cols = 0;
  uint64_t header = 8, item = 1;
  if (magic == 0x803) {  // images
    if (st.st_size < 16) {
      snprintf(g_err, sizeof(g_err), "%s: truncated image header", path);
      munmap(map, static_cast<size_t>(st.st_size));
      return nullptr;
    }
    rows = be32(p + 8);
    cols = be32(p + 12);
    header = 16;
    if (rows == 0 || cols == 0) {
      snprintf(g_err, sizeof(g_err), "%s: zero image dimensions", path);
      munmap(map, static_cast<size_t>(st.st_size));
      return nullptr;
    }
    item = rows * cols;  // <= 2^64 / safe: both factors < 2^32
  } else if (magic != 0x801) {
    snprintf(g_err, sizeof(g_err), "%s: bad IDX magic 0x%x", path, magic);
    munmap(map, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  uint64_t avail = static_cast<uint64_t>(st.st_size) - header;
  // count * item <= avail, without computing a wrappable product:
  if (count != 0 && item > avail / count) {
    snprintf(g_err, sizeof(g_err), "%s: truncated payload", path);
    munmap(map, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  uint64_t payload = count * item;  // now provably <= avail <= file size
  dims_out[0] = static_cast<int64_t>(count);
  dims_out[1] = static_cast<int64_t>(rows);
  dims_out[2] = static_cast<int64_t>(cols);
  dims_out[3] = static_cast<int64_t>(payload);
  *data_out = p + header;
  // Handle = the mapping base + size packed into a small struct.
  auto* h = new int64_t[2];
  h[0] = reinterpret_cast<int64_t>(map);
  h[1] = st.st_size;
  return h;
}

void td_idx_close(void* handle) {
  if (!handle) return;
  auto* h = static_cast<int64_t*>(handle);
  munmap(reinterpret_cast<void*>(h[0]), static_cast<size_t>(h[1]));
  delete[] h;
}

}  // extern "C"
