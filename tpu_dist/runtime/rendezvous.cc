// Native rendezvous — the framework's bootstrap transport.
//
// TPU-native equivalent of the reference's THD TCP-channel rendezvous
// (documented at /root/reference/tuto.md:404-419): rank 0 acts as master,
// binds MASTER_ADDR:MASTER_PORT, waits for exactly world_size-1 workers,
// collects each worker's location record, and sends every participant the
// full peer table; workers connect, register, and receive the table.  It
// also covers the MPI-style rank-less init (the reference's
// allreduce.py:54 path, where the launcher assigns ranks): processes that
// pass rank = -1 are assigned ranks first-come-first-served by the master.
//
// The Python layer (tpu_dist/runtime/__init__.py) uses this to realize the
// MASTER_ADDR/PORT/WORLD_SIZE/RANK env-var contract before handing the
// established process set to jax.distributed.initialize (whose coordinator
// then plays the steady-state role; this component owns process bootstrap,
// rank assignment, and the startup barrier).
//
// Build: make -C tpu_dist/runtime   (produces librendezvous.so, loaded via
// ctypes — no pybind11 dependency).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int kMaxMsg = 1 << 16;

// Last error message, readable from Python via td_last_error().
thread_local char g_err[512] = {0};

void set_err(const char* where) {
  snprintf(g_err, sizeof(g_err), "%s: %s", where, strerror(errno));
}

void set_errmsg(const char* msg) { snprintf(g_err, sizeof(g_err), "%s", msg); }

int set_timeout(int fd, int timeout_ms) {
  timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv)) < 0) return -1;
  if (setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv)) < 0) return -1;
  return 0;
}

// Length-prefixed message framing (4-byte big-endian length + payload).
int send_msg(int fd, const std::string& payload) {
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  if (write(fd, &len, 4) != 4) return -1;
  size_t off = 0;
  while (off < payload.size()) {
    ssize_t n = write(fd, payload.data() + off, payload.size() - off);
    if (n <= 0) return -1;
    off += static_cast<size_t>(n);
  }
  return 0;
}

int recv_msg(int fd, std::string* out) {
  uint32_t len_be = 0;
  size_t got = 0;
  char* p = reinterpret_cast<char*>(&len_be);
  while (got < 4) {
    ssize_t n = read(fd, p + got, 4 - got);
    if (n <= 0) return -1;
    got += static_cast<size_t>(n);
  }
  uint32_t len = ntohl(len_be);
  if (len > kMaxMsg) return -1;
  out->resize(len);
  got = 0;
  while (got < len) {
    ssize_t n = read(fd, out->data() + got, len - got);
    if (n <= 0) return -1;
    got += static_cast<size_t>(n);
  }
  return 0;
}

int connect_to(const char* addr, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    set_err("socket");
    return -1;
  }
  set_timeout(fd, timeout_ms);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) {
    set_errmsg("inet_pton: bad address");
    close(fd);
    return -1;
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
    set_err("connect");
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

}  // namespace

extern "C" {

static bool port_bindable(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(port));
  bool ok = bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) == 0;
  close(fd);
  return ok;
}

// Returns a free TCP port on the loopback interface (0 on failure).
// The bootstrap contract uses TWO consecutive ports (MASTER_PORT for the
// native rendezvous, MASTER_PORT+1 for the JAX coordination service — see
// tpu_dist.comm.init), so both must be free.
int td_free_port() {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 0;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = 0;
  if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
    close(fd);
    return 0;
  }
  socklen_t len = sizeof(sa);
  getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &len);
  int port = ntohs(sa.sin_port);
  close(fd);
  for (int attempt = 0; attempt < 64; ++attempt, ++port) {
    if (port + 1 < 65536 && port_bindable(port) && port_bindable(port + 1))
      return port;
  }
  return 0;
}

const char* td_last_error() { return g_err; }

// Master side: bind addr:port, accept (world-1) workers, assign ranks,
// broadcast the peer table.  Returns 0 on success.
//
// Peer table format (what lands in peers_out for every participant):
//   "<world>\n<rank> <payload>\n..." — payload is the opaque per-process
//   string each participant registered (e.g. "host:port" or a coordinator
//   hint); master's payload is its own `payload` argument.
static int run_master(const char* addr, int port, int world,
                      const char* payload, int timeout_ms, char* peers_out,
                      int cap) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) {
    set_err("socket");
    return -1;
  }
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, addr, &sa.sin_addr) != 1) {
    set_errmsg("inet_pton: bad address");
    close(lfd);
    return -1;
  }
  if (bind(lfd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
    bool in_use = errno == EADDRINUSE;
    set_err("bind");
    close(lfd);
    // -2 tells the rank-less caller "someone else is master — be a
    // worker"; any other failure is terminal.
    return in_use ? -2 : -1;
  }
  if (listen(lfd, world) < 0) {
    set_err("listen");
    close(lfd);
    return -1;
  }
  set_timeout(lfd, timeout_ms);

  std::vector<std::string> payloads(static_cast<size_t>(world));
  // Occupancy is tracked separately from the payload text: payloads may
  // legitimately be empty strings, so emptiness must not double as the
  // "slot free" sentinel (duplicate-rank requests have to collide).
  std::vector<bool> occupied(static_cast<size_t>(world), false);
  payloads[0] = payload;
  occupied[0] = true;
  std::vector<int> fds;
  std::vector<int> ranks;
  int next_rank = 1;
  // Wait for exactly world-1 workers (the reference master "waits for all
  // processes to connect", tuto.md:412-414) — fail-stop on timeout.
  for (int i = 0; i < world - 1; ++i) {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      set_err("accept (startup barrier timeout?)");
      for (int fd : fds) close(fd);
      close(lfd);
      return -1;
    }
    set_timeout(cfd, timeout_ms);
    std::string hello;
    if (recv_msg(cfd, &hello) != 0) {
      set_errmsg("recv hello failed");
      close(cfd);
      for (int fd : fds) close(fd);
      close(lfd);
      return -1;
    }
    // hello = "<requested_rank> <payload>"
    int req = -1;
    size_t sp = hello.find(' ');
    std::string wpayload = sp == std::string::npos ? "" : hello.substr(sp + 1);
    req = atoi(hello.c_str());
    int r = req >= 0 ? req : next_rank++;
    while (req < 0 && r < world && occupied[static_cast<size_t>(r)])
      r = next_rank++;
    if (r <= 0 || r >= world || occupied[static_cast<size_t>(r)]) {
      set_errmsg("rank collision or out of range during rendezvous");
      close(cfd);
      for (int fd : fds) close(fd);
      close(lfd);
      return -1;
    }
    payloads[static_cast<size_t>(r)] = wpayload;
    occupied[static_cast<size_t>(r)] = true;
    fds.push_back(cfd);
    ranks.push_back(r);
  }
  std::string table = std::to_string(world) + "\n";
  for (int r = 0; r < world; ++r)
    table += std::to_string(r) + " " + payloads[static_cast<size_t>(r)] + "\n";
  for (size_t i = 0; i < fds.size(); ++i) {
    std::string msg = std::to_string(ranks[i]) + "\n" + table;
    if (send_msg(fds[i], msg) != 0) {
      set_errmsg("send table failed");
      for (int fd : fds) close(fd);
      close(lfd);
      return -1;
    }
  }
  for (int fd : fds) close(fd);
  close(lfd);
  if (static_cast<int>(table.size()) + 1 > cap) {
    set_errmsg("peers_out buffer too small");
    return -1;
  }
  memcpy(peers_out, table.c_str(), table.size() + 1);
  return 0;  // master is rank 0
}

// td_rendezvous: returns the caller's rank (>= 0) on success, -1 on error.
//   rank: requested rank; 0 = act as master; -1 = let the master assign
//         (MPI-style rank-less init, allreduce.py:54 analog).
//   payload: opaque per-process record shared with all peers.
//   peers_out/cap: receives the peer table (see run_master).
int td_rendezvous(const char* addr, int port, int world, int rank,
                  const char* payload, int timeout_ms, char* peers_out,
                  int cap) {
  g_err[0] = 0;
  if (world < 1) {
    set_errmsg("world must be >= 1");
    return -1;
  }
  if (world == 1) {
    std::string table = "1\n0 " + std::string(payload) + "\n";
    if (static_cast<int>(table.size()) + 1 > cap) {
      set_errmsg("peers_out buffer too small");
      return -1;
    }
    memcpy(peers_out, table.c_str(), table.size() + 1);
    return 0;
  }
  if (rank == 0) {
    int got = run_master(addr, port, world, payload, timeout_ms, peers_out, cap);
    return got == -2 ? -1 : got;  // explicit rank 0 must own the port
  }
  // Worker: retry connecting until the master is up (or timeout).
  // Rank-less (MPI-style) processes additionally ELECT a master if none
  // appears: after a short grace period (which lets an explicit rank-0,
  // if one exists, bind first — no race in mixed launches), they compete
  // to bind the port; exactly one wins and becomes rank 0, the rest see
  // EADDRINUSE and keep connecting.  Without the election an
  // all-rank-less job would deadlock with every process waiting for a
  // master nobody becomes.
  timeval start{};
  gettimeofday(&start, nullptr);
  long grace_ms = timeout_ms / 4 < 1000 ? timeout_ms / 4 : 1000;
  int fd = -1;
  for (;;) {
    fd = connect_to(addr, port, 200);
    if (fd >= 0) break;
    timeval now{};
    gettimeofday(&now, nullptr);
    long elapsed_ms = (now.tv_sec - start.tv_sec) * 1000 +
                      (now.tv_usec - start.tv_usec) / 1000;
    if (rank < 0 && elapsed_ms > grace_ms) {
      int got =
          run_master(addr, port, world, payload, timeout_ms, peers_out, cap);
      if (got != -2) return got;  // won the election (or terminal error)
    }
    if (elapsed_ms > timeout_ms) {
      set_errmsg("worker: master did not come up before timeout");
      return -1;
    }
    usleep(50 * 1000);
  }
  std::string hello = std::to_string(rank) + " " + payload;
  if (send_msg(fd, hello) != 0) {
    set_errmsg("worker: send hello failed");
    close(fd);
    return -1;
  }
  std::string reply;
  if (recv_msg(fd, &reply) != 0) {
    set_errmsg("worker: recv table failed (startup barrier timeout?)");
    close(fd);
    return -1;
  }
  close(fd);
  size_t nl = reply.find('\n');
  if (nl == std::string::npos) {
    set_errmsg("worker: malformed reply");
    return -1;
  }
  int my_rank = atoi(reply.c_str());
  std::string table = reply.substr(nl + 1);
  if (static_cast<int>(table.size()) + 1 > cap) {
    set_errmsg("peers_out buffer too small");
    return -1;
  }
  memcpy(peers_out, table.c_str(), table.size() + 1);
  return my_rank;
}

}  // extern "C"
