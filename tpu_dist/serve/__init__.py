"""tpu_dist.serve — the continuous-batching decode server.

The serving half of the north star: a paged/blocked KV cache
(`paged_kv` — fixed-size blocks in a preallocated pool, per-request
block tables, bit-compatible with the dense `apply_cached` decode), a
continuous-batching engine (`engine` — admit/evict at step granularity
with a chunked prefill/decode split), runtime-parameter sampling
(`sampling` — per-slot and per-call temperature/top_k/top_p as traced
values), and a request front-end (`server`).  Benchmarked by
``make bench-serve`` (Poisson load, continuous vs static batching);
demoed by ``make serve-demo``.
"""

from tpu_dist.serve.engine import (
    Request,
    RequestResult,
    SamplingParams,
    ServeConfig,
    ServeEngine,
)
from tpu_dist.serve.paged_kv import (
    BlockAllocator,
    init_paged_cache,
    paged_apply_cached,
)
from tpu_dist.serve.sampling import (
    generate_runtime,
    sample_logits,
    sample_slots,
    slot_keys,
)
from tpu_dist.serve.server import LMServer

__all__ = [
    "BlockAllocator",
    "LMServer",
    "Request",
    "RequestResult",
    "SamplingParams",
    "ServeConfig",
    "ServeEngine",
    "generate_runtime",
    "init_paged_cache",
    "paged_apply_cached",
    "sample_logits",
    "sample_slots",
    "slot_keys",
]
