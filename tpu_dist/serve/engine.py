"""Continuous-batching decode engine — admit/evict at STEP granularity.

`TransformerLM.generate` is static batching: a fixed batch enters
together, every stream runs the full step count, and a finished stream
burns its slot until the longest one ends.  Under mixed output lengths
that is the serving throughput cliff (most of the batch is padding most
of the time).  This engine is the standard fix:

- a fixed number of DECODE SLOTS (``max_batch``) backed by the paged KV
  pool (`serve.paged_kv`) — blocks allocated at admission, freed at
  eviction;
- a step loop that, EVERY step, evicts finished requests, admits queued
  ones into the freed slots (FIFO; head-of-line blocks on pool
  exhaustion, so admission order is deterministic), runs at most one
  chunked PREFILL (prompt ingestion never stalls in-flight decodes for
  more than one chunk), then one batched DECODE step over every active
  slot;
- per-request sampling params (`sample_slots` — temperature/top_k/top_p
  are per-slot runtime values, so one compiled step program serves any
  request mix), per-request PRNG streams keyed by (seed, token index);
- request-lifecycle telemetry: ``request_admit`` / ``prefill`` /
  ``decode_step`` / ``request_finish`` events (`observe.events`
  schema), occupancy / queue-depth / KV-pool gauges and TTFT / TPOT
  histograms in `observe.registry.REGISTRY`.

Greedy decode through the engine is token-identical to the dense
`generate` (tested across block sizes) — continuous batching changes
WHEN a request computes, never WHAT it computes.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from tpu_dist.observe import events as ev_mod
from tpu_dist.observe.registry import REGISTRY
from tpu_dist.serve.paged_kv import (
    BlockAllocator,
    init_paged_cache,
    paged_apply_cached,
)
from tpu_dist.serve.sampling import sample_slots, slot_keys


@dataclass
class SamplingParams:
    """Per-request sampling config (the runtime analog of `generate`'s
    static kwargs).  ``temperature=0`` is greedy; ``seed`` keys the
    request's private PRNG stream."""

    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0


@dataclass
class ServeConfig:
    """Engine sizing.  ``max_seq`` caps prompt + output per request (it
    must fit the model's ``max_seq``); the pool holds ``num_blocks``
    blocks of ``block_size`` positions each, shared by all slots;
    ``prefill_chunk`` is the prompt-ingestion quantum (one chunk per
    engine step, interleaved with decode)."""

    max_batch: int = 8
    block_size: int = 16
    num_blocks: int = 128
    max_seq: int = 256
    prefill_chunk: int = 32
    prefill_batch: int = 4
    decode_event_every: int = 8
    cache_dtype: object = None
    # HBM budget for the admission memory check (bytes).  None = read
    # the live device limit (`observe.memory.memory_snapshot`; absent
    # on CPU-sim).  A grant that would push weights + granted KV blocks
    # past this emits a `warning` event — tests inject a fake limit.
    bytes_limit: int | None = None


@dataclass
class Request:
    """Internal request record (front-ends construct via
    `ServeEngine.submit`)."""

    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    sampling: SamplingParams
    stop_token: int | None = None
    # runtime state
    state: str = "queued"  # queued | prefill | decode | finished
    slot: int = -1
    blocks: list = field(default_factory=list)
    prefill_pos: int = 0
    tokens: list = field(default_factory=list)
    arrival_time: float = 0.0
    first_token_time: float | None = None
    finish_time: float | None = None
    token_times: list = field(default_factory=list)
    finish_reason: str | None = None


@dataclass
class RequestResult:
    """What the front-end hands back: the emitted tokens plus the
    latency observables the serving benches report."""

    request_id: int
    tokens: np.ndarray
    finish_reason: str
    prompt_len: int
    arrival_time: float
    first_token_time: float | None
    finish_time: float
    token_times: list

    @property
    def emitted(self) -> int:
        return int(self.tokens.size)

    @property
    def ttft(self) -> float | None:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def tpot_mean(self) -> float | None:
        """Mean time per output token after the first (None for
        single-token or unstarted requests)."""
        if self.first_token_time is None or self.emitted < 2:
            return None
        return (self.finish_time - self.first_token_time) / (self.emitted - 1)


class ServeEngine:
    """The continuous-batching step loop over one model + paged pool.

    ``now``: injectable clock (tests pass a fake for deterministic
    latency fields; benches pass ``time.perf_counter``).  The engine is
    single-threaded by design — callers drive `step()` (or
    `run_until_drained()`); thread-safety belongs to the front-end.
    """

    def __init__(self, lm, params, config: ServeConfig | None = None, *,
                 now=time.monotonic, events=None):
        cfg = config or ServeConfig()
        if cfg.max_seq > lm.max_seq:
            raise ValueError(
                f"config max_seq {cfg.max_seq} exceeds model max_seq "
                f"{lm.max_seq}"
            )
        if cfg.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {cfg.prefill_chunk}"
            )
        if cfg.prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {cfg.prefill_batch}"
            )
        self.lm, self.params, self.cfg = lm, params, cfg
        self._now = now
        self.events = events if events is not None else ev_mod.from_env()
        from tpu_dist.observe import flightrec as _flightrec_mod
        from tpu_dist.observe import memory as _memory_mod

        self._flight = _flightrec_mod.get()
        self._memory = _memory_mod.WatermarkSampler(flight=self._flight)
        self.blocks_per_seq = math.ceil(cfg.max_seq / cfg.block_size)
        self.context_len = self.blocks_per_seq * cfg.block_size
        self.allocator = BlockAllocator(cfg.num_blocks)
        dtype = cfg.cache_dtype or params["embed"]["table"].dtype
        self.cache = init_paged_cache(
            lm, cfg.num_blocks, cfg.block_size, dtype
        )
        self.scratch = cfg.num_blocks

        S, MB = cfg.max_batch, self.blocks_per_seq
        self.block_tables = np.full((S, MB), self.scratch, np.int32)
        self.index = np.zeros((S,), np.int32)
        self.active = np.zeros((S,), bool)
        self.last_tok = np.zeros((S,), np.int32)
        self.temperature = np.zeros((S,), np.float32)
        self.top_k = np.zeros((S,), np.int32)
        self.top_p = np.ones((S,), np.float32)
        self.seeds = np.zeros((S,), np.int32)
        self.counters = np.zeros((S,), np.int32)

        self.slots: list[Request | None] = [None] * S
        self.queue: deque[Request] = deque()
        self._prefillq: deque[int] = deque()
        self._cancelled: set[int] = set()
        self.results: dict[int, RequestResult] = {}
        self.step_count = 0
        self.steps_with_decode = 0
        self.steps_with_prefill = 0
        self._next_id = 0
        # (kind, ...) tuples, appended in processing order — the
        # determinism tests' observable
        self.audit: list[tuple] = []

        self._decode_fn = self._build_decode_fn(greedy=False)
        self._decode_fn_greedy = self._build_decode_fn(greedy=True)
        self._prefill_fn = self._build_prefill_fn()
        # device-resident decode state: the per-slot scheduling arrays
        # ride the jitted step's output back into the next step's input
        # as ONE packed int32 array (block tables, active mask, sampling
        # ints, last token, position, token counter) plus one small f32
        # array (temperature, top_p) — a steady-state decode step
        # transfers nothing host->device, and a slot-map change (admit /
        # activate / evict) rebuilds both with two device_puts
        self._dint = None
        self._dflt = None
        self._dirty = True
        self._warming = False
        self._g_occ = REGISTRY.gauge(
            "tpu_dist_serve_batch_occupancy",
            "active decode slots in the serving batch",
        )
        self._g_queue = REGISTRY.gauge(
            "tpu_dist_serve_queue_depth", "requests waiting for admission"
        )
        self._g_blocks = REGISTRY.gauge(
            "tpu_dist_serve_kv_blocks_used", "allocated KV pool blocks"
        )
        self._g_util = REGISTRY.gauge(
            "tpu_dist_serve_kv_block_utilization",
            "allocated fraction of the KV block pool",
        )
        self._h_ttft = REGISTRY.histogram(
            "tpu_dist_serve_ttft_seconds", "time to first token"
        )
        self._h_tpot = REGISTRY.histogram(
            "tpu_dist_serve_tpot_seconds", "per-token decode latency"
        )
        # Memory breakdown: what this engine keeps resident — weights
        # vs KV pool (allocated in full at init; blocks are GRANTS of
        # that pool) vs whatever headroom the device has left for
        # activations.  `bytes_limit` comes from the config (tests/
        # operators) or the live device limit (None on CPU-sim).
        from tpu_dist.parallel import per_device_bytes

        self.weights_bytes = int(per_device_bytes(self.params))
        self.kv_pool_bytes = int(per_device_bytes(self.cache))
        # the pool holds num_blocks grantable blocks + 1 scratch block
        self.kv_block_bytes = self.kv_pool_bytes // (cfg.num_blocks + 1)
        self.bytes_limit = (
            cfg.bytes_limit
            if cfg.bytes_limit is not None
            else self._memory.snapshot().get("bytes_limit")
        )
        REGISTRY.gauge(
            "tpu_dist_serve_weights_bytes", "model weight bytes resident"
        ).set(self.weights_bytes)
        REGISTRY.gauge(
            "tpu_dist_serve_kv_pool_bytes",
            "paged KV pool bytes resident (allocated at init)",
        ).set(self.kv_pool_bytes)

    # ------------------------------------------------------------- jit fns

    # packed int-state column layout (after the MB block-table columns)
    _ACTIVE, _TOPK, _SEED, _LASTTOK, _INDEX, _COUNTER = range(6)

    def _pack_state(self):
        MB = self.blocks_per_seq
        ints = np.empty((self.cfg.max_batch, MB + 6), np.int32)
        ints[:, :MB] = self.block_tables
        ints[:, MB + self._ACTIVE] = self.active
        ints[:, MB + self._TOPK] = self.top_k
        ints[:, MB + self._SEED] = self.seeds
        ints[:, MB + self._LASTTOK] = self.last_tok
        ints[:, MB + self._INDEX] = self.index
        ints[:, MB + self._COUNTER] = self.counters
        flt = np.stack([self.temperature, self.top_p], axis=1)
        return ints, flt.astype(np.float32)

    def _build_decode_fn(self, *, greedy: bool):
        """One batched decode step over the packed state.
        ``greedy=True`` is the fast path taken when every active slot
        has temperature 0 — no sorts, no key derivation, plain argmax
        (exactly `generate`'s greedy op)."""
        lm, bs, MB = self.lm, self.cfg.block_size, self.blocks_per_seq

        def fn(params, cache, ints, flt):
            block_tables = ints[:, :MB]
            active = ints[:, MB + self._ACTIVE].astype(bool)
            last_tok = ints[:, MB + self._LASTTOK]
            index = ints[:, MB + self._INDEX]
            logits, cache = paged_apply_cached(
                lm, params, last_tok[:, None], cache, block_tables,
                index[:, None], active[:, None], bs,
            )
            if greedy:
                toks = jnp.argmax(logits[:, 0], axis=-1).astype(
                    last_tok.dtype
                )
            else:
                keys = slot_keys(
                    ints[:, MB + self._SEED], ints[:, MB + self._COUNTER]
                )
                toks = sample_slots(
                    logits[:, 0], keys, flt[:, 0],
                    ints[:, MB + self._TOPK], flt[:, 1], last_tok.dtype,
                )
            inc = active.astype(jnp.int32)
            ints = ints.at[:, MB + self._LASTTOK].set(
                jnp.where(active, toks, last_tok)
            )
            ints = ints.at[:, MB + self._INDEX].add(inc)
            ints = ints.at[:, MB + self._COUNTER].add(inc)
            return toks, ints, cache

        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_prefill_fn(self):
        """One prompt chunk for EACH of P pending requests (P = however
        many rows the host passes, retraced per distinct P up to
        ``prefill_batch``) — distinct requests only, since a request's
        later chunks attend its earlier ones.  Also samples each row's
        would-be first output token from its last real position (the
        host uses it only for rows whose prompt just completed)."""
        lm, bs, C = self.lm, self.cfg.block_size, self.cfg.prefill_chunk
        MB = self.blocks_per_seq

        def fn(params, cache, ints, flt):
            # ints columns: [tokens(C) | block_table(MB) | start |
            #                real_len | top_k | seed]
            tokens = ints[:, :C]
            block_tables = ints[:, C : C + MB]
            start = ints[:, C + MB]
            real_len = ints[:, C + MB + 1]
            positions = start[:, None] + jnp.arange(C, dtype=jnp.int32)
            write_mask = jnp.arange(C)[None, :] < real_len[:, None]
            logits, cache = paged_apply_cached(
                lm, params, tokens, cache, block_tables, positions,
                write_mask, bs,
            )
            last = jnp.take_along_axis(
                logits,
                jnp.maximum(real_len, 1)[:, None, None] - 1,
                axis=1,
            )[:, 0]
            keys = slot_keys(
                ints[:, C + MB + 3], jnp.zeros_like(real_len)
            )
            toks = sample_slots(
                last, keys, flt[:, 0], ints[:, C + MB + 2], flt[:, 1],
                tokens.dtype,
            )
            return toks, cache

        return jax.jit(fn, donate_argnums=(1,))

    # ------------------------------------------------------ static analysis

    def analysis_programs(self) -> dict:
        """The engine's hot compiled programs, exposed for
        `tpu_dist.analysis`: ``{name: (jitted_fn, example_args)}`` with
        `jax.ShapeDtypeStruct` arguments — lowering them compiles the
        REAL serving step (same shapes, same donation) without touching
        (or donating) any live buffer.

        ``serve_decode`` is the steady-state sampled decode step (the
        per-token hot path; cache + packed state donated);
        ``serve_prefill`` is one full-width chunked-prefill round."""
        sds = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.ShapeDtypeStruct(
                tuple(np.shape(x)), np.asarray(x).dtype
                if not hasattr(x, "dtype") else x.dtype
            ),
            t,
        )
        params, cache = sds(self.params), sds(self.cache)
        ints, flt = self._pack_state()
        C, MB, Pb = (
            self.cfg.prefill_chunk, self.blocks_per_seq,
            self.cfg.prefill_batch,
        )
        p_ints = jax.ShapeDtypeStruct((Pb, C + MB + 4), np.int32)
        p_flt = jax.ShapeDtypeStruct((Pb, 2), np.float32)
        return {
            "serve_decode": (
                self._decode_fn, (params, cache, sds(ints), sds(flt))
            ),
            "serve_prefill": (
                self._prefill_fn, (params, cache, p_ints, p_flt)
            ),
        }

    # ------------------------------------------------------------- memory

    def memory_breakdown(self) -> dict:
        """The serve-side resident story: weights vs KV pool (split
        into granted and free blocks) vs activation headroom against
        ``bytes_limit`` (None when no limit is known — CPU-sim without
        a configured budget).  The `observe.memory` snapshot rides
        along so plan (this breakdown) and live (HBM/RSS) are one
        record."""
        granted = self.allocator.used * self.kv_block_bytes
        headroom = (
            int(self.bytes_limit) - self.weights_bytes - self.kv_pool_bytes
            if self.bytes_limit is not None else None
        )
        return {
            "weights_bytes": self.weights_bytes,
            "kv_pool_bytes": self.kv_pool_bytes,
            "kv_granted_bytes": int(granted),
            "kv_block_bytes": self.kv_block_bytes,
            "bytes_limit": self.bytes_limit,
            "activation_headroom_bytes": headroom,
            "live": self._memory.snapshot(),
        }

    def _resident_rows(self) -> list[dict]:
        return [
            {"class": "weights", "bytes": self.weights_bytes},
            {"class": "kv_pool", "bytes": self.kv_pool_bytes},
        ]

    def _check_block_grant(self, req: Request, need: int) -> None:
        """Admission memory check: warn (once per request) when this
        grant pushes weights + granted KV blocks past ``bytes_limit``
        — the pool itself is preallocated, so the grant cannot OOM by
        itself, but a plan whose grants exceed the budget means the
        pool was sized past the device and the NEXT activation spike
        will be the thing that dies.  Called AFTER ``alloc(need)``, so
        ``allocator.used`` already includes this grant; admission runs
        once per request, so no dedup is needed."""
        if self.bytes_limit is None:
            return
        projected = (
            self.weights_bytes + self.allocator.used * self.kv_block_bytes
        )
        if projected <= self.bytes_limit:
            return
        self._flight.record(
            "memory", phase="admit", projected_bytes=int(projected),
            bytes_limit=int(self.bytes_limit),
        )
        self.events.emit(
            "warning",
            reason="kv_grant_over_limit",
            request_id=req.request_id,
            blocks=need,
            projected_bytes=int(projected),
            bytes_limit=int(self.bytes_limit),
            over_bytes=int(projected - self.bytes_limit),
        )

    def _oom(self, exc: BaseException, phase: str) -> None:
        """RESOURCE_EXHAUSTED on a serving step path: plan-vs-live OOM
        forensics through the flight recorder (`observe.memory`)."""
        from tpu_dist.observe import memory as _memory_mod

        if not _memory_mod.is_resource_exhausted(exc):
            return
        _memory_mod.record_oom(
            exc,
            phase=phase,
            sampler=self._memory,
            resident=self._resident_rows(),
            plan=self.memory_breakdown(),
            events_logger=self.events,
        )

    # ---------------------------------------------------------- front door

    def submit(self, prompt, max_new_tokens: int, *,
               sampling: SamplingParams | None = None,
               stop_token: int | None = None) -> int:
        """Queue one request; returns its id.  Admission happens inside
        `step()` (a submit never blocks on pool space)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds serve max_seq {self.cfg.max_seq}"
            )
        need = math.ceil(
            (prompt.size + max_new_tokens) / self.cfg.block_size
        )
        if need > self.cfg.num_blocks:
            # admitting is impossible even with an empty pool; queueing
            # it would livelock the FIFO head forever
            raise ValueError(
                f"request needs {need} KV blocks but the pool holds "
                f"only {self.cfg.num_blocks}"
            )
        rid = self._next_id
        self._next_id += 1
        req = Request(
            request_id=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            sampling=sampling or SamplingParams(), stop_token=stop_token,
            arrival_time=self._now(),
        )
        self.queue.append(req)
        return rid

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request.  Queued: removed
        immediately.  Running: evicted at the start of the next step
        (its partial tokens are returned with ``finish_reason
        'cancelled'``).  Returns False for unknown/finished ids."""
        for i, req in enumerate(self.queue):
            if req.request_id == request_id:
                del self.queue[i]
                self._finalize(req, "cancelled", self._now())
                return True
        for req in self.slots:
            if req is not None and req.request_id == request_id:
                self._cancelled.add(request_id)
                return True
        return False

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.slots)

    def run_until_drained(self, max_steps: int = 100_000):
        """Drive `step()` until queue and slots are empty; returns the
        results dict (id -> `RequestResult`)."""
        steps = 0
        while self.pending:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"engine not drained after {max_steps} steps "
                    f"(queue={len(self.queue)}, "
                    f"occupied={sum(r is not None for r in self.slots)})"
                )
        return self.results

    # ------------------------------------------------------------ the step

    def step(self) -> None:
        """One engine step: evict cancels, admit, one decode step plus
        one batched prefill round — DISPATCHED back-to-back before
        either is read back, so the host's bookkeeping for one overlaps
        the device's compute for the other — then publish telemetry.

        Prefill-priority at low occupancy: while more prefills would
        remain after this round and no more than half the decode slots
        are active, the decode step is skipped for this engine step —
        filling slots fast raises the occupancy every later decode step
        amortizes over, at the bounded cost of delaying at most half a
        batch by one prefill round."""
        self._process_cancels()
        self._admit()
        prefer_prefill = (
            len(self._prefillq) > self.cfg.prefill_batch
            and self.occupancy() <= self.cfg.max_batch // 2
        )
        try:
            decode_toks = None if prefer_prefill else self._decode_dispatch()
        except Exception as e:
            self._oom(e, "decode")
            raise
        try:
            prefill_ctx = self._prefill_dispatch()
        except Exception as e:
            self._oom(e, "prefill")
            raise
        try:
            did_decode = self._decode_complete(decode_toks)
        except Exception as e:
            self._oom(e, "decode")
            raise
        try:
            did_prefill = self._prefill_complete(prefill_ctx)
        except Exception as e:
            self._oom(e, "prefill")
            raise
        if self.events.enabled and not self._warming:
            if did_decode:
                self._memory.sample("decode")
            if did_prefill:
                self._memory.sample("prefill")
        self.steps_with_prefill += bool(did_prefill)
        self.steps_with_decode += bool(did_decode)
        if did_prefill or did_decode:
            # Flight ring (observe.flightrec): one deque append per
            # working step, so a wedged decode gang's post-mortem dump
            # shows the serving loop's last completed steps too.
            self._flight.record(
                "step", step=self.step_count, phase="readback",
                occupancy=self.occupancy(),
            )
        self._publish(did_prefill or did_decode)
        self.step_count += 1

    def _process_cancels(self) -> None:
        if not self._cancelled:
            return
        tnow = self._now()
        for s, req in enumerate(self.slots):
            if req is not None and req.request_id in self._cancelled:
                self._cancelled.discard(req.request_id)
                if s in self._prefillq:
                    self._prefillq.remove(s)
                self._evict(s, "cancelled", tnow)
        self._cancelled.clear()  # ids that were already finished

    def _admit(self) -> None:
        while self.queue:
            free = [s for s, r in enumerate(self.slots) if r is None]
            if not free:
                break
            req = self.queue[0]
            need = math.ceil(
                (req.prompt.size + req.max_new_tokens) / self.cfg.block_size
            )
            blocks = self.allocator.alloc(need)
            if blocks is None:
                break  # head-of-line blocks; FIFO stays deterministic
            self._check_block_grant(req, need)
            self.queue.popleft()
            s = free[0]
            req.slot, req.blocks, req.state = s, blocks, "prefill"
            self.slots[s] = req
            self.block_tables[s, :] = self.scratch
            self.block_tables[s, : len(blocks)] = blocks
            self.index[s] = 0
            self.active[s] = False
            sp = req.sampling
            self.temperature[s] = sp.temperature
            self.top_k[s] = 0 if sp.top_k is None else sp.top_k
            self.top_p[s] = 1.0 if sp.top_p is None else sp.top_p
            # seed rides the packed int32 state: keep the low 32 bits
            # (two's complement) so any Python int is a valid seed
            s32 = sp.seed & 0xFFFFFFFF
            self.seeds[s] = s32 - (1 << 32) if s32 >= 1 << 31 else s32
            self.counters[s] = 0
            self._dirty = True
            self._prefillq.append(s)
            self.audit.append(
                ("admit", req.request_id, s, tuple(blocks), self.step_count)
            )
            self.events.emit(
                "request_admit",
                request_id=req.request_id,
                prompt_tokens=int(req.prompt.size),
                max_new_tokens=int(req.max_new_tokens),
                queue_depth=len(self.queue),
            )

    def _prefill_dispatch(self):
        """Assemble + dispatch one chunk for each of (up to
        ``prefill_batch``) oldest prefilling requests in ONE batched
        call — distinct requests only, since a request's later chunks
        attend its earlier ones.  Returns the (chunks, first-token
        device handle) context for `_prefill_complete`, or None."""
        if not self._prefillq:
            return None
        C, MB = self.cfg.prefill_chunk, self.blocks_per_seq
        take = list(self._prefillq)[: self.cfg.prefill_batch]
        P = len(take)
        ints = np.zeros((P, C + MB + 4), np.int32)
        flt = np.zeros((P, 2), np.float32)
        chunks = []
        for r, s in enumerate(take):
            req = self.slots[s]
            start = req.prefill_pos
            chunk = req.prompt[start : start + C]
            chunks.append((s, req, start, chunk.size))
            ints[r, : chunk.size] = chunk
            ints[r, C : C + MB] = self.block_tables[s]
            ints[r, C + MB] = start
            ints[r, C + MB + 1] = chunk.size
            ints[r, C + MB + 2] = self.top_k[s]
            ints[r, C + MB + 3] = self.seeds[s]
            flt[r, 0] = self.temperature[s]
            flt[r, 1] = self.top_p[s]
        first_toks, self.cache = self._prefill_fn(
            self.params, self.cache, ints, flt
        )
        return chunks, first_toks

    def _prefill_complete(self, ctx) -> bool:
        """Apply a dispatched prefill round: advance positions; rows
        whose prompt completed get their first output token (sampled
        from the chunk's last logits exactly as `generate` samples from
        its prefill logits — this is the TTFT moment) and join the
        decode batch."""
        if ctx is None:
            return False
        chunks, first_toks = ctx
        finishing = [
            r for r, (s, req, start, size) in enumerate(chunks)
            if start + size >= req.prompt.size
        ]
        toks_np = np.asarray(first_toks) if finishing else None
        tnow = self._now()
        for r, (s, req, start, size) in enumerate(chunks):
            req.prefill_pos += size
            self.events.emit(
                "prefill",
                request_id=req.request_id,
                chunk=start // self.cfg.prefill_chunk,
                tokens=size,
                done=req.prefill_pos >= req.prompt.size,
            )
            if req.prefill_pos < req.prompt.size:
                continue
            self._prefillq.remove(s)
            tok = int(toks_np[r])
            req.tokens.append(tok)
            req.token_times.append(tnow)
            req.first_token_time = tnow
            if not self._warming:
                self._h_ttft.observe(tnow - req.arrival_time)
            self.counters[s] += 1
            self.last_tok[s] = tok
            self.index[s] = req.prompt.size
            req.state = "decode"
            self.active[s] = True
            self._dirty = True
            if self._finished_by(req, tok):
                self._evict(s, self._finish_reason(req, tok), tnow)
        return True

    def _decode_dispatch(self):
        """Dispatch one batched token for every active slot (no
        readback yet).  Returns the tokens' device handle, or None."""
        if not self.active.any():
            return None
        if self._dirty:
            self._dint, self._dflt = self._pack_state()
            self._dirty = False
        fn = (
            self._decode_fn_greedy
            if not self.temperature[self.active].any()
            else self._decode_fn
        )
        toks, self._dint, self.cache = fn(
            self.params, self.cache, self._dint, self._dflt
        )
        return toks

    def _decode_complete(self, toks) -> bool:
        """Read back a dispatched decode step, then finish/evict the
        streams that completed — THE every-step admit/evict cycle's
        compute half."""
        if toks is None:
            return False
        toks_np = np.asarray(toks)  # host sync: the step boundary
        tnow = self._now()
        active = np.nonzero(self.active)[0]
        self.last_tok[active] = toks_np[active]
        self.index[active] += 1
        self.counters[active] += 1
        for s in active:
            req = self.slots[s]
            tok = int(toks_np[s])
            if req.token_times and not self._warming:
                self._h_tpot.observe(tnow - req.token_times[-1])
            req.tokens.append(tok)
            req.token_times.append(tnow)
            if self._finished_by(req, tok):
                self._evict(s, self._finish_reason(req, tok), tnow)
        return True

    @staticmethod
    def _finished_by(req: Request, tok: int) -> bool:
        return (
            len(req.tokens) >= req.max_new_tokens
            or (req.stop_token is not None and tok == req.stop_token)
        )

    @staticmethod
    def _finish_reason(req: Request, tok: int) -> str:
        if req.stop_token is not None and tok == req.stop_token:
            return "stop"
        return "length"

    def _evict(self, s: int, reason: str, tnow: float) -> None:
        req = self.slots[s]
        self.allocator.free(req.blocks)
        req.blocks = []
        self.slots[s] = None
        self.block_tables[s, :] = self.scratch
        self.active[s] = False
        self._dirty = True
        self._finalize(req, reason, tnow)

    def _finalize(self, req: Request, reason: str, tnow: float) -> None:
        req.state, req.finish_reason, req.finish_time = (
            "finished", reason, tnow,
        )
        result = RequestResult(
            request_id=req.request_id,
            tokens=np.asarray(req.tokens, np.int32),
            finish_reason=reason,
            prompt_len=int(req.prompt.size),
            arrival_time=req.arrival_time,
            first_token_time=req.first_token_time,
            finish_time=tnow,
            token_times=list(req.token_times),
        )
        self.results[req.request_id] = result
        self.audit.append(
            ("finish", req.request_id, reason, len(req.tokens),
             self.step_count)
        )
        self.events.emit(
            "request_finish",
            request_id=req.request_id,
            emitted=len(req.tokens),
            finish_reason=reason,
            ttft=result.ttft,
            tpot_mean=result.tpot_mean,
        )

    def _publish(self, worked: bool) -> None:
        occ = int(self.active.sum())
        self._g_occ.set(occ)
        self._g_queue.set(len(self.queue))
        self._g_blocks.set(self.allocator.used)
        self._g_util.set(self.allocator.utilization())
        if worked and self.step_count % self.cfg.decode_event_every == 0:
            self.events.emit(
                "decode_step",
                step=self.step_count,
                occupancy=occ,
                queue_depth=len(self.queue),
                kv_blocks_used=self.allocator.used,
                kv_block_utilization=self.allocator.utilization(),
            )

    # ----------------------------------------------------------- accessors

    def occupancy(self) -> int:
        return int(self.active.sum())

    def warmup(self) -> None:
        """Compile the serving programs with throwaway requests so the
        first real request does not pay compile time (benches call this
        before starting their clocks): each prefill row count P in
        1..prefill_batch (retraced per P), the greedy decode fast path,
        AND the sampled decode path (one tempered request).  Telemetry
        is suppressed for the duration — no lifecycle events, no
        TTFT/TPOT observations — so dashboards never see the throwaway
        requests or their compile-dominated latencies."""
        events, self.events = self.events, ev_mod.NULL
        self._warming = True
        try:
            for p in range(1, min(self.cfg.prefill_batch,
                                  self.cfg.max_batch) + 1):
                rids = [
                    self.submit(np.zeros((1,), np.int32), 2)
                    for _ in range(p)
                ]
                self.run_until_drained()
                for rid in rids:
                    del self.results[rid]
            rid = self.submit(
                np.zeros((1,), np.int32), 2,
                sampling=SamplingParams(
                    temperature=0.5, top_k=2, top_p=0.9
                ),
            )
            self.run_until_drained()
            del self.results[rid]
        finally:
            self.events = events
            self._warming = False
        self.audit.clear()
        self.step_count = 0
        self.steps_with_decode = 0
        self.steps_with_prefill = 0
