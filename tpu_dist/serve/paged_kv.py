"""Paged KV cache — fixed-size blocks in a preallocated pool.

The dense decode path (`TransformerLM.init_cache` / `apply_cached`)
allocates one contiguous ``(batch, kv_heads, cache_len, head_dim)``
cache per request slot, so a slot's HBM is pinned for the slot's
longest possible sequence whether or not it is used.  Serving under
continuous batching wants the opposite: KV memory is a POOL of
fixed-size blocks (``block_size`` token positions each), and every
request owns just the blocks its tokens actually fill, mapped through a
per-request **block table** (logical block index -> physical block id)
— the vLLM/PagedAttention layout.  Blocks are handed out by the
host-side `BlockAllocator` at admission and returned at eviction; the
device never sees the free list, only the tables.

The device side is `paged_apply_cached`: the SAME math as
`TransformerLM.apply_cached` (tests assert greedy decode through it is
token-identical to the dense `generate`) with two differences:

- **write**: a token's k/v rows scatter into
  ``pool[table[pos // block_size], :, pos % block_size]`` instead of a
  ``dynamic_update_slice`` into a contiguous cache (masked-off tokens —
  pads, inactive slots — write to a reserved scratch block);
- **read**: the per-slot tables gather the pool back into a contiguous
  ``(slots, kv_heads, L, head_dim)`` view, after which the attention
  (scale, position mask, -1e30 fill, softmax) is exactly the dense
  incremental attention, per-slot positions included.

Everything is static-shape: one compiled program serves every decode
step and every prefill chunk regardless of which requests occupy which
slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class BlockAllocator:
    """Host-side free-list over the physical KV blocks.

    Deterministic (LIFO free list, ids handed out in ascending order
    from a fresh pool) so a seeded arrival trace produces an identical
    block-table history run to run — the engine's determinism tests
    rely on it.  Double-free and foreign ids raise instead of silently
    corrupting the pool."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        # pop() yields 0, 1, 2, ... for a fresh pool
        self._free = list(range(num_blocks - 1, -1, -1))
        self._allocated: set[int] = set()
        self.high_water = 0

    @property
    def used(self) -> int:
        return len(self._allocated)

    @property
    def available(self) -> int:
        return len(self._free)

    def utilization(self) -> float:
        return self.used / self.num_blocks

    def alloc(self, n: int) -> list[int] | None:
        """``n`` block ids, or None if the pool cannot satisfy the
        request (caller keeps the request queued — no partial grants)."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        self.high_water = max(self.high_water, self.used)
        return blocks

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(f"freeing unallocated block {b}")
            self._allocated.remove(b)
            self._free.append(b)


def init_paged_cache(lm, num_blocks: int, block_size: int, dtype=None):
    """The device pool: per transformer block one ``{"k", "v"}`` pair of
    ``(num_blocks + 1, kv_heads, block_size, head_dim)`` arrays.  Index
    ``num_blocks`` is the SCRATCH block — masked writes (pad tokens,
    inactive slots) land there and nothing ever reads it through a real
    block table."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    hd = lm.dim // lm.heads
    dt = dtype or jnp.float32
    shape = (num_blocks + 1, lm.kv_heads, block_size, hd)
    # distinct buffers per block/side: the engine donates the whole
    # cache pytree into its jitted steps, and donation rejects aliased
    # buffers
    return [
        {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        for _ in lm.blocks
    ]


def _rope_slots(x, positions, *, base: float = 10000.0):
    """`nn.attention.rope` with PER-SLOT positions: ``x`` is
    ``(slots, heads, s, head_dim)`` and ``positions`` is ``(slots, s)``
    — each decode slot sits at its own global position.  Elementwise
    identical to the shared-positions rope for equal position values."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _paged_attention(attn, params, x, k_pool, v_pool, block_tables,
                     positions, write_mask, block_size: int):
    """One block's incremental attention against the paged pool.

    ``x``: ``(S, s, dim)`` new-token activations for S slots;
    ``positions``: ``(S, s)`` global positions; ``write_mask``:
    ``(S, s)`` — True rows write their k/v into the pool, False rows
    (pads / inactive slots) write to the scratch block.  Returns
    ``(y, k_pool, v_pool)`` — same contract as
    `MultiHeadAttention.apply_cached`, with the contiguous cache
    replaced by the (scatter, gather) pair."""
    S, s, _ = x.shape
    q, k, v = attn._project(params, x)
    if attn.use_rope:
        q, k = _rope_slots(q, positions), _rope_slots(k, positions)

    scratch = k_pool.shape[0] - 1
    blk = jnp.take_along_axis(block_tables, positions // block_size, axis=1)
    blk = jnp.where(write_mask, blk, scratch).reshape(-1)
    off = (positions % block_size).reshape(-1)
    k_w = jnp.moveaxis(k.astype(k_pool.dtype), 1, 2).reshape(
        S * s, attn.kv_heads, attn.head_dim
    )
    v_w = jnp.moveaxis(v.astype(v_pool.dtype), 1, 2).reshape(
        S * s, attn.kv_heads, attn.head_dim
    )
    k_pool = k_pool.at[blk, :, off].set(k_w)
    v_pool = v_pool.at[blk, :, off].set(v_w)

    # gather the per-slot tables back into the contiguous dense-cache
    # layout; from here on the math is exactly apply_cached's
    L = block_tables.shape[1] * block_size
    k_full = jnp.moveaxis(k_pool[block_tables], 2, 1).reshape(
        S, attn.kv_heads, L, attn.head_dim
    )
    v_full = jnp.moveaxis(v_pool[block_tables], 2, 1).reshape(
        S, attn.kv_heads, L, attn.head_dim
    )
    scale = attn.head_dim**-0.5
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q * scale, attn._expand_kv(k_full).astype(q.dtype)
    )
    pos_k = jnp.arange(L)[None, None, :]
    qpos = positions[:, :, None]
    visible = pos_k <= qpos  # (S, s, L), per-slot positions
    if attn.sliding_window is not None:
        visible = visible & (pos_k > qpos - attn.sliding_window)
    logits = jnp.where(visible[:, None], logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", weights, attn._expand_kv(v_full).astype(q.dtype)
    )
    o = jnp.moveaxis(o, 1, 2).reshape(S, s, attn.dim)
    y, _ = attn._out.apply(params["out"], {}, o)
    return y, k_pool, v_pool


def paged_apply_cached(lm, params, tokens, cache, block_tables, positions,
                       write_mask, block_size: int):
    """`TransformerLM.apply_cached` against the paged pool.

    ``tokens``: ``(S, s)`` new tokens for S slots (s = 1 for decode
    steps, s = chunk for prefill); ``positions``: ``(S, s)`` each
    token's global position; ``block_tables``: ``(S, max_blocks)``
    physical block ids per slot; ``write_mask``: ``(S, s)`` True where
    the token is real (False rows read/write scratch and their logits
    are garbage the caller ignores).  Returns
    ``(logits (S, s, vocab), new_cache)``.

    Token-identical to the dense path by construction: the gathered
    pool view equals the dense contiguous cache for every visible
    position, and every op after the gather is the dense op."""
    L = block_tables.shape[1] * block_size
    positions = jnp.clip(positions, 0, min(lm.max_seq, L) - 1)
    h = params["embed"]["table"][tokens]
    if lm.pos_embedding == "learned":
        h = h + params["pos"][0][positions]
    new_cache = []
    for blk, pb, c in zip(lm.blocks, params["blocks"], cache):
        x1, _ = blk.ln1.apply(pb["ln1"], {}, h)
        o, ck, cv = _paged_attention(
            blk.attn, pb["attn"], x1, c["k"], c["v"], block_tables,
            positions, write_mask, block_size,
        )
        h = h + o
        x2, _ = blk.ln2.apply(pb["ln2"], {}, h)
        h = h + lm._mlp_or_moe(blk, pb, x2)
        new_cache.append({"k": ck, "v": cv})
    h, _ = lm.ln.apply(params["ln"], {}, h)
    logits = h @ params["embed"]["table"].T
    return logits, new_cache
