"""Sampling with RUNTIME parameters — per-slot and per-call.

`models._make_sampler` specializes the compiled program on its sampling
config (temperature/top_k/top_p are Python statics), which is right for
a single stream but wrong for a serving batch: every decode slot holds
a different request with different sampling params, and recompiling per
combination is out of the question.  These samplers take the params as
TRACED values instead — one compiled program covers every request mix:

- `sample_slots`: per-slot arrays ``(S,)`` of temperature/top_k/top_p
  plus per-slot PRNG keys — the continuous-batching engine's sampler.
- `sample_logits`: scalar traced params, one key for the whole batch —
  the per-call analog of `_make_sampler` used by
  `export.export_generate(runtime_sampling=True)`; for equal settings
  it reproduces the static sampler exactly (tested).
- `generate_runtime`: `TransformerLM.generate` with the sampling
  params threaded through as runtime inputs.

Disabled encodings (the traced stand-ins for ``None``): ``top_k <= 0``
and ``top_p >= 1.0`` are no-ops; ``temperature == 0`` selects greedy
argmax exactly like the static sampler.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG = -1e30


def _mask_top_k(scaled, top_k):
    """Keep each row's ``top_k`` highest logits (``top_k <= 0`` = off).
    Same kth-value rule as the static sampler: ties at the threshold
    survive (``< kth`` is masked, ``== kth`` is not)."""
    V = scaled.shape[-1]
    top_k = jnp.broadcast_to(jnp.asarray(top_k), scaled.shape[:-1])
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[..., None], axis=-1)
    enabled = (top_k > 0)[..., None]
    return jnp.where(enabled & (scaled < kth), _NEG, scaled)


def _mask_top_p(scaled, top_p):
    """Nucleus truncation at runtime ``top_p`` (``>= 1.0`` = off): drop
    tokens in the tail beyond cumulative probability ``top_p``; the
    highest-probability token always survives (its exclusive cumsum is
    0).  Applied AFTER top-k, matching the static sampler's order."""
    top_p = jnp.broadcast_to(
        jnp.asarray(top_p, scaled.dtype), scaled.shape[:-1]
    )
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive
    cutoff_idx = jnp.sum(cum < top_p[..., None], axis=-1, keepdims=True) - 1
    cutoff_idx = jnp.clip(cutoff_idx, 0, scaled.shape[-1] - 1)
    cutoff = jnp.take_along_axis(sorted_desc, cutoff_idx, axis=-1)
    enabled = (top_p < 1.0)[..., None]
    return jnp.where(enabled & (scaled < cutoff), _NEG, scaled)


def _masked(logits, temperature, top_k, top_p):
    t = jnp.asarray(temperature, logits.dtype)
    safe_t = jnp.where(t == 0, jnp.ones_like(t), t)
    if safe_t.ndim:  # per-slot (S,) against (S, V) logits
        safe_t = safe_t[..., None]
    scaled = logits / safe_t
    return _mask_top_p(_mask_top_k(scaled, top_k), top_p)


def sample_logits(logits, key, temperature, top_k, top_p,
                  dtype=jnp.int32):
    """One batch draw from ``(b, vocab)`` logits, scalar traced params,
    single key (the whole batch shares the categorical draw, exactly
    like `_make_sampler`).  ``temperature == 0`` is greedy argmax."""
    greedy = jnp.argmax(logits, axis=-1).astype(dtype)
    sampled = jax.random.categorical(
        key, _masked(logits, temperature, top_k, top_p)
    ).astype(dtype)
    return jnp.where(jnp.asarray(temperature) == 0, greedy, sampled)


def sample_slots(logits, keys, temperature, top_k, top_p,
                 dtype=jnp.int32):
    """Per-slot sampling for the serving batch: ``logits (S, vocab)``,
    per-slot ``keys (S,)`` typed PRNG keys and ``(S,)`` params.  Each
    slot draws independently with its own key, so a request's token
    stream depends only on its own (seed, token index) — deterministic
    regardless of which slot it lands in or who shares the batch."""
    greedy = jnp.argmax(logits, axis=-1).astype(dtype)
    sampled = jax.vmap(jax.random.categorical)(
        keys, _masked(logits, temperature, top_k, top_p)
    ).astype(dtype)
    return jnp.where(jnp.asarray(temperature) == 0, greedy, sampled)


def slot_keys(seeds, counters):
    """Per-slot PRNG keys: ``fold_in(key(seed), counter)`` — seed is
    the request's, counter is its token index, so the stream is a pure
    function of the request, not of scheduling."""
    def one(seed, counter):
        return jax.random.fold_in(jax.random.key(seed), counter)

    return jax.vmap(one)(seeds, counters)


def generate_runtime(lm, params, prompt, steps: int, *, key=None,
                     temperature=0.0, top_k=0, top_p=1.0,
                     cache_len: int | None = None,
                     stop_token: int | None = None):
    """`TransformerLM.generate` with RUNTIME sampling params:
    ``temperature``/``top_k``/``top_p`` are traced scalars — one
    compiled program (or one exported artifact) serves every sampling
    configuration.  ``top_k=0`` / ``top_p=1.0`` disable the
    truncations (the traced stand-ins for ``None``);
    ``temperature=0`` is greedy.  For equal settings the tokens match
    `generate` exactly (tested) — this IS `generate`'s decode loop,
    entered through its ``sampler`` hook, so `stop_token` freeze
    semantics carry over unchanged."""
    top_k = 0 if top_k is None else top_k
    top_p = 1.0 if top_p is None else top_p

    def sampler(logits, k):
        return sample_logits(
            logits, k, temperature, top_k, top_p, prompt.dtype
        )

    return lm.generate(
        params, prompt, steps, key=key, cache_len=cache_len,
        stop_token=stop_token, sampler=sampler,
    )
