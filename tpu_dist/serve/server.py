"""Request front-end over the continuous-batching engine.

`LMServer` is the deployment-shaped surface: construct it from live
params or from a weight artifact on disk (`export.save_params` /
`load_params` — the raw-weights counterpart of the sealed
`export_generate` artifact, see export.py's docstring for when each is
right), `submit()` requests with per-request sampling params, and
drive the engine with `step()` / `run_until_drained()`.  Telemetry
flows through the engine (`TPU_DIST_TELEMETRY` request-lifecycle
events, Prometheus gauges on ``TPU_DIST_METRICS_PORT``), so a served
process is observable with the same `tools/tpu_top.py` dashboard as a
training run.
"""

from __future__ import annotations

import time

from tpu_dist.serve.engine import (
    RequestResult,
    SamplingParams,
    ServeConfig,
    ServeEngine,
)


class LMServer:
    """One model, one paged KV pool, one admission queue."""

    def __init__(self, lm, params, config: ServeConfig | None = None, *,
                 now=time.monotonic, events=None):
        self.lm = lm
        self.engine = ServeEngine(
            lm, params, config, now=now, events=events
        )

    @classmethod
    def from_artifact(cls, lm, path, config: ServeConfig | None = None,
                      *, init_key=None, **kw) -> "LMServer":
        """Load raw weights saved with `export.save_params` (the server
        keeps sampling a RUNTIME concern — per request — instead of
        serving a sealed `export_generate` artifact whose sampling
        config is frozen at export time)."""
        import jax

        from tpu_dist import export

        # restore only needs the tree STRUCTURE — eval_shape gives it
        # without materializing a throwaway set of random weights
        like, _ = jax.eval_shape(
            lm.init,
            init_key if init_key is not None else jax.random.key(0),
        )
        params = export.load_params(path, like)
        return cls(lm, params, config, **kw)

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int | None = None,
               top_p: float | None = None, seed: int = 0,
               stop_token: int | None = None) -> int:
        """Queue a request; returns its id (see `result`)."""
        return self.engine.submit(
            prompt, max_new_tokens,
            sampling=SamplingParams(
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed,
            ),
            stop_token=stop_token,
        )

    def cancel(self, request_id: int) -> bool:
        return self.engine.cancel(request_id)

    def step(self) -> None:
        self.engine.step()

    def run_until_drained(self, **kw) -> dict[int, RequestResult]:
        return self.engine.run_until_drained(**kw)

    def result(self, request_id: int) -> RequestResult | None:
        return self.engine.results.get(request_id)

    @property
    def pending(self) -> bool:
        return self.engine.pending
