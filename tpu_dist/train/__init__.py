"""`tpu_dist.train` — optimizers, training loop, checkpointing, metrics."""

from tpu_dist.train.optim import Optimizer, adamw, sgd

__all__ = ["Optimizer", "adamw", "sgd"]
