"""`tpu_dist.train` — optimizers, trainer, checkpointing, metrics."""

from tpu_dist.train import checkpoint, flops, metrics, schedule
from tpu_dist.train.optim import Optimizer, adamw, sgd
from tpu_dist.train.trainer import EpochStats, TrainConfig, Trainer

__all__ = [
    "EpochStats",
    "Optimizer",
    "TrainConfig",
    "Trainer",
    "adamw",
    "checkpoint",
    "flops",
    "metrics",
    "schedule",
    "sgd",
]
