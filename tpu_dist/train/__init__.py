"""`tpu_dist.train` — optimizers, trainer, checkpointing, metrics."""

from tpu_dist.train import checkpoint, flops, metrics, schedule
from tpu_dist.train.optim import (
    Optimizer,
    adafactor,
    adamw,
    clip_by_global_norm,
    decay_mask_default,
    ema_params,
    from_optax,
    global_norm,
    sgd,
    with_ema,
)
from tpu_dist.train.pipeline_driver import CompletedStep, PipelineDriver
from tpu_dist.train.trainer import EpochStats, TrainConfig, Trainer
from tpu_dist.train.lm_trainer import LMEpochStats, LMTrainConfig, LMTrainer

__all__ = [
    "CompletedStep",
    "EpochStats",
    "PipelineDriver",
    "LMEpochStats",
    "LMTrainConfig",
    "LMTrainer",
    "Optimizer",
    "TrainConfig",
    "Trainer",
    "adafactor",
    "adamw",
    "clip_by_global_norm",
    "decay_mask_default",
    "ema_params",
    "from_optax",
    "global_norm",
    "checkpoint",
    "flops",
    "metrics",
    "schedule",
    "sgd",
    "with_ema",
]
