"""Checkpoint / resume.

Absent from the reference (SURVEY.md §5: no save/load anywhere; training
always starts fresh and runs exactly 10 epochs) — provided here as the
lightweight single-writer checkpoint the survey prescribes: DP state is
identical across replicas, so one host writes the pytree once, and resume
is by epoch index.  Kept off the parity-critical path.

Format: one ``.npz`` per checkpoint holding flattened leaves plus a JSON
treedef descriptor — no framework-specific serialization, readable with
plain numpy.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _flatten_with_paths(tree: Any):
    flat = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [(jax.tree_util.keystr(path), leaf) for path, leaf in flat[0]]
    return leaves, flat[1]


def _tree_digest(paths: list[str], arrays: list[np.ndarray]) -> str:
    """sha256 over keypaths, shapes, and raw leaf bytes, in leaf order.

    Deliberately dtype-blind: extension dtypes (bfloat16/fp8) round-trip
    through npz as raw void with the same bytes but a different dtype
    name, and the digest must survive that — the bytes are the payload.
    """
    import hashlib

    h = hashlib.sha256()
    for k, a in zip(paths, arrays, strict=True):
        a = np.asarray(a)
        h.update(k.encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def save(
    path: str | Path, tree: Any, *, step: int = 0, partition: dict | None = None
) -> None:
    """Single-writer save of a (replicated) pytree.  Only process 0 writes
    in a multi-process setting — replicas are identical (SURVEY.md §2c.6).

    ``__meta__`` carries a sha256 digest of the leaf bytes; `restore`
    verifies it, and `latest_intact` uses it to skip truncated/corrupt
    snapshots when picking a resume point.  ``partition`` (the resolved
    partition-rule provenance, `parallel.partition_summary`) rides the
    meta so restore can validate mesh compatibility (`check_partition`)."""
    if jax.process_index() != 0:
        return
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    leaves, _ = _flatten_with_paths(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, (_, x) in enumerate(leaves)}
    paths_ = [k for k, _ in leaves]
    meta = {
        "step": step,
        "paths": paths_,
        "digest": _tree_digest(paths_, list(arrays.values())),
    }
    if partition is not None:
        meta["partition"] = partition
    tmp = path.with_suffix(".tmp.npz")
    np.savez(tmp, __meta__=json.dumps(meta), **arrays)
    tmp.rename(path)
    # Chaos (`TPU_DIST_CHAOS=ckpt_truncate=F`): simulate a kill mid-write
    # by truncating the file we just published — the state latest_intact
    # must detect and skip.  No-op when chaos is off.
    from tpu_dist.resilience import chaos as _chaos

    _chaos.maybe_truncate_checkpoint(path)


def save_orbax(path: str | Path, tree: Any, *, step: int = 0) -> None:
    """Alternative backend: orbax (async-capable, sharding-aware) for
    users standardized on it.  Same single-writer contract as `save`."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"tree": tree, "step": step}, force=True)


def restore_orbax(path: str | Path, like: Any) -> tuple[Any, int]:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path, {"tree": like, "step": 0})
    return state["tree"], int(state["step"])


class AsyncCheckpointer:
    """Overlap checkpoint IO with training.

    ``save()`` snapshots the pytree to host memory synchronously (cheap:
    one device→host copy; on TPU this is the only part that must block
    the step loop) and hands serialization + file IO to a background
    thread.  The next ``save()``/``wait()`` joins the previous write
    first, so at most one write is in flight and completed files appear
    in submission order.  The written format is exactly `save`'s — the
    two are interchangeable for `restore`.

    Single-writer contract as `save` (process 0 writes; other processes'
    calls are no-ops but still snapshot-free and cheap).  Always call
    ``wait()`` (or use as a context manager) before reading the file or
    exiting, and re-raise of background errors happens there.
    """

    def __init__(self):
        self._thread = None
        self._exc = None

    def _submit(self, write_fn) -> None:
        """Join any in-flight write, then run ``write_fn`` (pure file IO —
        all device→host snapshotting must happen in the caller, BEFORE
        this, so buffers may be donated immediately after submission)."""
        import threading

        self.wait()
        self._exc = None

        def _write():
            try:
                write_fn()
            except BaseException as e:  # surfaced on wait()
                self._exc = e

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def save(
        self, path: str | Path, tree: Any, *, step: int = 0,
        partition: dict | None = None,
    ) -> None:
        self.wait()
        if jax.process_index() != 0:
            return
        # Device→host transfer happens NOW; everything after is file IO.
        host_tree = jax.tree.map(np.asarray, jax.device_get(tree))
        self._submit(
            lambda: save(path, host_tree, step=step, partition=partition)
        )

    def save_sharded(
        self, path: str | Path, tree: Any, *, step: int = 0,
        partition: dict | None = None,
    ) -> None:
        """Async `save_sharded`: the device→host shard snapshot happens
        now (so buffers may be donated immediately after); file IO runs
        on the background thread.  Unlike `save`, EVERY process writes
        (its own shards) — the single-writer gate does not apply."""
        self.wait()
        p = Path(path)
        meta_leaves, blobs = _plan_sharded_save(tree, step)
        meta = {"step": step, "leaves": meta_leaves}
        if partition is not None:
            meta["partition"] = partition

        def _write():
            p.mkdir(parents=True, exist_ok=True)
            _write_sharded(p, meta, blobs)

        self._submit(_write)

    def wait(self) -> None:
        """Join the in-flight write (if any); re-raise its error here."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
            exc = getattr(self, "_exc", None)
            self._exc = None
            if exc is not None:
                raise exc

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.wait()
        return False


# --- sharded checkpointing --------------------------------------------------
#
# The single-writer `save` above materializes every leaf on one host —
# right for replicated DP state (SURVEY.md §5: identical replicas), wrong
# for FSDP/TP state, where no host holds (or can hold) the global array.
# `save_sharded` writes each *device shard* as its own file, written by
# the process that owns the shard's primary replica, and `restore_sharded`
# rebuilds arrays under ANY target sharding via
# ``jax.make_array_from_callback`` — so a checkpoint saved FSDP-8 can be
# restored FSDP-4, tensor-parallel, or fully replicated, and each process
# reads only the bytes its devices need.


def _blob_digest(raw: bytes) -> str:
    """sha256 of one shard blob's raw bytes — embedded in the blob file
    itself (see `_write_sharded`) so every process's shards are
    independently verifiable without a global digest pass."""
    import hashlib

    return hashlib.sha256(raw).hexdigest()


def _verify_blob(file: Path, dtype: np.dtype) -> bool:
    """One shard blob is present, sized to its recorded shape, and —
    when it carries an embedded digest (every blob written since elastic
    resume landed) — byte-identical to what was written.  Digest-less
    legacy blobs pass on the size check alone.  Never raises."""
    try:
        with np.load(file) as z:
            data, shape = z["data"], z["shape"]
            if data.size != int(np.prod(shape)) * dtype.itemsize:
                return False
            if "digest" in z.files:
                return _blob_digest(data.tobytes()) == bytes(z["digest"]).decode()
        return True
    except Exception:
        return False


def _norm_index(index: tuple, shape: tuple[int, ...]) -> tuple[tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices, possibly fewer than ndim
    and with None bounds) to per-dim (start, stop) over ``shape``."""
    out = []
    for d, dim in enumerate(shape):
        sl = index[d] if d < len(index) else slice(None)
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _shard_filename(starts: tuple[int, ...], step: int = 0) -> str:
    # The step prefix makes RE-saving a NEW step to an existing path
    # crash-safe: the new meta.json only references s<newstep>_ files, so
    # an interruption mid-save can never leave meta pointing at a mix of
    # old- and new-step blobs (old files satisfy only the old meta).
    # Filenames must be computable identically on EVERY process (each
    # writes its own shards; process 0 writes the global meta), so the
    # discriminator is the caller's step — nothing process-local.  The
    # same-step-re-save case is handled in `_write_sharded` by
    # retracting meta.json before overwriting (loud, not silent).
    tail = "_".join(str(s) for s in starts) if starts else ""
    return f"s{step}_shard_{tail}.npz"


def _leaf_shard_table(leaf: Any, step: int = 0) -> list[dict]:
    """Global shard table for one leaf: every (offset, shape, file) in the
    leaf's sharding — known on EVERY process (shardings are global even
    when the data is not), so process 0 can record the full table."""
    shape = tuple(leaf.shape)
    table, seen = [], set()
    for _dev, index in leaf.sharding.devices_indices_map(shape).items():
        bounds = _norm_index(index, shape)
        starts = tuple(b[0] for b in bounds)
        if starts in seen:  # replicas map to the same file
            continue
        seen.add(starts)
        table.append(
            {
                "offset": list(starts),
                "shape": [b[1] - b[0] for b in bounds],
                "file": _shard_filename(starts, step),
            }
        )
    return table


def _plan_sharded_save(
    tree: Any, step: int = 0
) -> tuple[list[dict], list[tuple[str, tuple, bytes]]]:
    """Split a sharded save into (meta, blobs-this-process-writes).

    The snapshot to host bytes happens HERE (synchronously), so callers
    may donate/mutate device buffers afterwards; blob writing is pure IO.
    """
    import jax

    leaves, _ = _flatten_with_paths(tree)
    meta_leaves, blobs = [], []
    for i, (keypath, leaf) in enumerate(leaves):
        if not isinstance(leaf, jax.Array):
            # host-side leaf (numpy/python scalar): replicated by
            # construction; process 0 writes it as a single full shard.
            arr = np.asarray(leaf)
            table = [
                {
                    "offset": [0] * arr.ndim,
                    "shape": list(arr.shape),
                    "file": _shard_filename((0,) * arr.ndim, step),
                }
            ]
            meta_leaves.append(
                {
                    "path": keypath,
                    "shape": list(arr.shape),
                    "dtype": arr.dtype.name,
                    "shards": table,
                }
            )
            if jax.process_index() == 0:
                blobs.append((f"leaf_{i}/{table[0]['file']}", arr.shape, arr.tobytes()))
            continue
        meta_leaves.append(
            {
                "path": keypath,
                "shape": list(leaf.shape),
                "dtype": np.dtype(leaf.dtype).name,
                "shards": _leaf_shard_table(leaf, step),
            }
        )
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:  # exactly one owner per shard
                continue
            starts = tuple(
                b[0] for b in _norm_index(shard.index, tuple(leaf.shape))
            )
            # NB: tobytes() copies in C order from any layout; don't use
            # ascontiguousarray here — it promotes 0-d shards to (1,),
            # corrupting the recorded shape for scalar leaves.
            data = np.asarray(shard.data)
            blobs.append(
                (
                    f"leaf_{i}/{_shard_filename(starts, step)}",
                    data.shape,
                    data.tobytes(),
                )
            )
    return meta_leaves, blobs


def _write_sharded(
    path: Path,
    meta: dict,
    blobs: list[tuple[str, tuple, bytes]],
    *,
    publish_timeout_s: float = 120.0,
) -> None:
    import jax

    marker = path / "save_inprogress.json"
    if jax.process_index() == 0:
        # Re-saving the SAME step over an existing same-step checkpoint
        # reuses the s<step>_ filenames, so a crash mid-overwrite could
        # leave the old meta pointing at a mix of old and half-replaced
        # blobs.  Retract meta.json first: the checkpoint is loudly
        # in-progress (restore fails) instead of silently inconsistent.
        old_meta = path / "meta.json"
        if old_meta.exists():
            try:
                if json.loads(old_meta.read_text()).get("step") == meta["step"]:
                    old_meta.unlink()
            except (OSError, ValueError):
                old_meta.unlink(missing_ok=True)
        # Attempt marker, written strictly AFTER the retraction: its
        # presence tells the other processes the old same-step meta is
        # gone (so overwriting s<step>_ blobs can no longer corrupt a
        # live checkpoint), and its mtime is the freshness bar every
        # referenced blob must meet before meta republishes — old blobs
        # at the same filenames no longer satisfy the publish wait.
        path.mkdir(parents=True, exist_ok=True)
        mtmp = marker.with_name(marker.name + ".tmp")
        mtmp.write_text(json.dumps({"step": meta["step"]}))
        mtmp.rename(marker)
    elif blobs:
        # Cluster-wide ordering for the retraction: do not overwrite
        # possibly-live same-step blobs until process 0 has (a) written
        # this attempt's marker and (b) any same-step meta.json is gone.
        # A crash while we wait leaves the old checkpoint fully intact.
        # A process with NO blobs to write skips the gate entirely — it
        # cannot corrupt anything, and by the time it looks, process 0
        # may already have published this attempt's meta and removed the
        # marker (which would read as a spurious timeout here).
        deadline = time.monotonic() + publish_timeout_s
        while True:
            meta_f = path / "meta.json"
            blocked = False
            if meta_f.exists():
                try:
                    blocked = (
                        json.loads(meta_f.read_text()).get("step")
                        == meta["step"]
                    )
                except (OSError, ValueError):
                    blocked = True  # mid-change/garbage: wait for clarity
            marker_ok = False
            if marker.exists():
                try:
                    marker_ok = (
                        json.loads(marker.read_text()).get("step")
                        == meta["step"]
                    )
                except (OSError, ValueError):
                    marker_ok = False
            if marker_ok and not blocked:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"sharded checkpoint {path}: process 0 did not retract "
                    f"the step-{meta['step']} meta.json and publish a save "
                    f"marker within {publish_timeout_s:.0f}s — refusing to "
                    "overwrite blobs a live meta may still reference"
                )
            time.sleep(0.05)
    from tpu_dist.resilience import chaos as _chaos

    for blob_i, (rel, shape, raw) in enumerate(blobs):
        f = path / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        tmp = f.with_name(f.name + ".tmp")
        # flat uint8 + explicit shape: np.save round-trips extension
        # dtypes (bfloat16, fp8) as raw void, losing the dtype — bytes +
        # meta dtype is lossless for every dtype.  Write via a handle:
        # np.savez appends ".npz" to bare paths, breaking the tmp-rename.
        # Each blob embeds its own sha256 (`digest`): the shard table in
        # meta.json is written by process 0, which never sees the other
        # processes' bytes, so per-shard integrity must travel with the
        # shard file itself (`_verify_blob`, and the reshard engine's
        # verify-before-commit pass).
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                data=np.frombuffer(raw, np.uint8),
                shape=np.asarray(shape, np.int64),
                digest=np.frombuffer(_blob_digest(raw).encode(), np.uint8),
            )
        tmp.rename(f)
        # Chaos (`TPU_DIST_CHAOS=kill_during_checkpoint=N`): hard-exit
        # after the Nth blob — the partial sharded directory a real
        # preemption mid-save leaves behind.  No-op when chaos is off.
        _chaos.checkpoint_blob_written(blob_i + 1, len(blobs))
    if jax.process_index() == 0:
        # Publish meta.json only once every shard file it references is
        # visible (multi-host: other processes write their own blobs to
        # the shared filesystem on their own schedule).  Polling — not a
        # collective — so this is safe from the async writer thread.
        referenced = [
            path / f"leaf_{i}" / shard["file"]
            for i, rec in enumerate(meta["leaves"])
            for shard in rec["shards"]
        ]
        try:
            bar = marker.stat().st_mtime
        except OSError:
            bar = 0.0

        def _stale(f: Path) -> bool:
            # Same-step re-saves reuse filenames, so existence is not
            # enough: a blob counts only once its mtime reaches this
            # attempt's marker (same filesystem clock stamps both).
            try:
                return f.stat().st_mtime < bar
            except OSError:
                return True  # absent

        deadline = time.monotonic() + publish_timeout_s
        missing = [f for f in referenced if _stale(f)]
        while missing and time.monotonic() < deadline:
            time.sleep(0.05)
            missing = [f for f in missing if _stale(f)]
        if missing:
            raise RuntimeError(
                f"sharded checkpoint {path}: {len(missing)} shard file(s) "
                f"still missing or stale after {publish_timeout_s:.0f}s "
                f"(e.g. {missing[0]}) — not publishing meta.json over an "
                "incomplete checkpoint"
            )
        tmp = path / "meta.json.tmp"
        tmp.write_text(json.dumps(meta))
        tmp.rename(path / "meta.json")
        # Best-effort GC of blobs no meta references anymore (earlier
        # steps re-saved to the same path).  Files in the new meta were
        # verified present above, so this only removes stale-step blobs.
        keep = {str(f) for f in referenced}
        for leaf_dir in path.glob("leaf_*"):
            for f in leaf_dir.glob("*.npz"):
                if str(f) not in keep:
                    try:
                        f.unlink()
                    except OSError:
                        pass
        marker.unlink(missing_ok=True)  # attempt complete
    elif blobs:
        # Wait for process 0 to publish this attempt's meta, re-touching
        # our blobs whenever the marker postdates them.  This closes the
        # stale-marker race: a marker left by a CRASHED same-step attempt
        # can let this process pass the retraction gate and write blobs
        # BEFORE process 0 rewrites the marker — those blobs would then
        # sit below the publish wait's freshness bar forever.  Process 0
        # writes the marker exactly once per attempt, so one re-touch
        # after that settles every file.
        mine = [path / rel for rel, _, _ in blobs]
        deadline = time.monotonic() + publish_timeout_s
        while True:
            try:
                if (
                    json.loads((path / "meta.json").read_text()).get("step")
                    == meta["step"]
                ):
                    break
            except (OSError, ValueError):
                pass
            try:
                bar = marker.stat().st_mtime
            except OSError:
                bar = None
            if bar is not None:
                for f in mine:
                    try:
                        if f.stat().st_mtime < bar:
                            os.utime(f)
                    except OSError:
                        pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"sharded checkpoint {path}: process 0 did not publish "
                    f"the step-{meta['step']} meta.json within "
                    f"{publish_timeout_s:.0f}s of this process writing its "
                    "shards — checkpoint is incomplete"
                )
            time.sleep(0.05)


def save_sharded(
    path: str | Path, tree: Any, *, step: int = 0, partition: dict | None = None
) -> None:
    """Checkpoint a pytree of (possibly sharded) ``jax.Array``s without
    ever materializing a global array on any host.

    Layout: ``path/meta.json`` (structure, shapes, dtypes, full shard
    table — written by process 0) + ``path/leaf_<i>/shard_<offsets>.npz``
    (one file per unique shard, written by the process holding the
    shard's primary replica; replicated leaves produce exactly one file).

    Multi-host: every process must call this (each writes its own
    shards to the shared filesystem — the ``file://`` rendezvous
    assumption, tuto.md:430-437); synchronize before reading the
    checkpoint back (e.g. the next collective, or a barrier)."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    meta_leaves, blobs = _plan_sharded_save(tree, step)
    meta = {"step": step, "leaves": meta_leaves}
    if partition is not None:
        meta["partition"] = partition
    _write_sharded(path, meta, blobs)


def _read_region(
    leaf_dir: Path,
    meta_leaf: dict,
    bounds: tuple[tuple[int, int], ...],
    dtype: np.dtype,
) -> np.ndarray:
    """Assemble the half-open region ``bounds`` of one leaf from whichever
    saved shard files intersect it (the resharding core: target shards
    need not align with saved shards)."""
    out = np.empty(tuple(b[1] - b[0] for b in bounds), dtype)
    covered = 0
    for rec in meta_leaf["shards"]:
        src = tuple(
            (o, o + s) for o, s in zip(rec["offset"], rec["shape"], strict=True)
        )
        inter = tuple(
            (max(a0, b0), min(a1, b1)) for (a0, a1), (b0, b1) in zip(src, bounds)
        )
        if any(lo >= hi for lo, hi in inter):
            continue
        with np.load(leaf_dir / rec["file"]) as z:
            block = (
                z["data"].view(dtype).reshape(tuple(int(s) for s in z["shape"]))
            )
        src_sel = tuple(
            slice(lo - s0, hi - s0) for (lo, hi), (s0, _) in zip(inter, src)
        )
        dst_sel = tuple(
            slice(lo - b0, hi - b0) for (lo, hi), (b0, _) in zip(inter, bounds)
        )
        out[dst_sel] = block[src_sel]
        covered += int(np.prod([hi - lo for lo, hi in inter]))
    if covered != out.size:  # saved shards must tile the global domain
        raise ValueError(
            f"checkpoint {leaf_dir} does not cover region {bounds} "
            f"({covered}/{out.size} elements found)"
        )
    return out


def read_meta(path: str | Path) -> dict:
    """The sharded checkpoint's metadata: ``{"step", "leaves": [{"path",
    "shape", "dtype", "shards": [...]}, ...]}`` — lets callers inspect
    saved shapes/dtypes before choosing a restore template (e.g. the
    FSDP world-resize path in `Trainer.restore`).  Checkpoints written
    by the partition-engine trainers additionally carry ``"partition"``
    (rule-set name + mesh axis names/sizes, `check_partition`)."""
    return json.loads((Path(path) / "meta.json").read_text())


def partition_mismatch(
    meta: dict, expected: dict, *, where: str = "checkpoint"
) -> list[str]:
    """The incompatibilities between a checkpoint's recorded partition
    provenance and the restoring run's resolved rule set + mesh (both in
    `parallel.partition_summary` form).

    An empty list means the checkpoint restores directly (identical
    provenance, or a same-rules/same-axis-name world resize —
    `restore_sharded` handles that natively: engine checkpoints store
    logical-shape leaves, and per-rank state like the EF residual is
    shape-checked and reset separately by
    `compress.reset_resized_residual`).  A non-empty list is the elastic
    resume case: different rule set or topology, routed through
    `train.reshard.redistribute` by the engine trainers.  Raises only
    when the checkpoint carries no provenance at all."""
    saved = meta.get("partition")
    if saved is None:
        raise ValueError(
            f"{where}: no partition metadata recorded — this checkpoint "
            "predates the partition engine (it was written by the "
            "retired pre-PR-12 strategy builders or by a bare "
            "save_sharded call).  Load it explicitly with "
            "checkpoint.restore_sharded/restore_fsdp against templates "
            "matching its saved layout, or re-export it from the run "
            "that wrote it"
        )
    saved_axes = dict(saved.get("axes", {}))
    want_axes = dict(expected.get("axes", {}))
    problems = []
    if saved.get("rules") != expected.get("rules"):
        problems.append(
            f"rule set {saved.get('rules')!r} (saved) vs "
            f"{expected.get('rules')!r} (this run)"
        )
    if tuple(saved_axes) != tuple(want_axes):
        problems.append(
            f"mesh axes {saved_axes} (saved) vs {want_axes} (this run)"
        )
    return problems


def check_partition(
    meta: dict, expected: dict, *, where: str = "checkpoint"
) -> None:
    """Validate a checkpoint's recorded partition provenance against the
    restoring run's resolved rule set + mesh (both in
    `parallel.partition_summary` form).  Mismatches raise a clear error
    instead of the silent mis-shard a blind restore would risk; callers
    that want to HANDLE the mismatch (the engine trainers' elastic
    resume) use `partition_mismatch` and route to
    `train.reshard.redistribute` instead."""
    problems = partition_mismatch(meta, expected, where=where)
    if problems:
        raise ValueError(
            f"{where}: partition mismatch — " + "; ".join(problems)
            + ".  Redistribute the checkpoint onto this run's mesh and "
            "rule set with tpu_dist.train.reshard.redistribute (elastic "
            "resume: saved shards are streamed onto the new "
            "PartitionSpecs in memory-bounded buckets) — the "
            "partition-engine trainers' restore() routes there "
            "automatically."
        )


def restore_sharded(path: str | Path, like: Any) -> tuple[Any, int]:
    """Restore a sharded checkpoint into the structure AND shardings of
    ``like`` (e.g. the freshly-initialized sharded train state).

    Each ``jax.Array`` leaf is rebuilt with
    ``jax.make_array_from_callback`` under the template's sharding, so
    each process opens only the shard FILES that intersect the regions
    its own devices need (aligned or coarser target shardings read a
    subset; a fully cross-sharded target — e.g. row-saved, column-
    restored — intersects every file) — and the target sharding is free
    to differ from the one saved (FSDP-n ↔ FSDP-m ↔ replicated ↔ TP).
    Non-``jax.Array`` template leaves get the fully-assembled numpy
    array.  Returns ``(tree, step)``."""
    import jax

    path = Path(path)
    meta = read_meta(path)
    leaves_like, treedef = _flatten_with_paths(like)
    saved_paths = [rec["path"] for rec in meta["leaves"]]
    if [k for k, _ in leaves_like] != saved_paths:
        raise ValueError(
            f"sharded checkpoint {path} structure mismatch: "
            f"{saved_paths[:3]}... vs {[k for k, _ in leaves_like][:3]}..."
        )
    out = []
    for i, ((keypath, tmpl), rec) in enumerate(
        zip(leaves_like, meta["leaves"], strict=True)
    ):
        shape, dtype = tuple(rec["shape"]), np.dtype(rec["dtype"])
        if tuple(tmpl.shape) != shape or np.dtype(tmpl.dtype) != dtype:
            raise ValueError(
                f"leaf {keypath}: checkpoint has shape={shape} dtype={dtype}, "
                f"template has shape={tuple(tmpl.shape)} dtype={np.dtype(tmpl.dtype)}"
            )
        leaf_dir = path / f"leaf_{i}"
        if isinstance(tmpl, jax.Array) or hasattr(tmpl, "sharding"):
            sharding = tmpl.sharding

            def cb(index, _dir=leaf_dir, _rec=rec, _shape=shape, _dtype=dtype):
                return _read_region(_dir, _rec, _norm_index(index, _shape), _dtype)

            out.append(jax.make_array_from_callback(shape, sharding, cb))
        else:
            full = _read_region(
                leaf_dir, rec, tuple((0, d) for d in shape), dtype
            )
            out.append(full)
    return jax.tree_util.tree_unflatten(treedef, out), int(meta["step"])


def restore_fsdp(path: str | Path, like: Any) -> tuple[Any, int]:
    """Restore a sharded checkpoint of FSDP/ZeRO state, translating
    between WORLD SIZES when needed.

    FSDP leaves are physically ``(n, k)``: the flattened logical leaf
    zero-padded to ``n·k`` and row-sharded (`fsdp_shard_params`), and the
    padding stays exactly zero through training (padded grads are zero).
    So when the checkpoint's ``n`` differs from the template's, the
    translation is a flat copy of ``min(n·k, n'·k')`` elements (any
    truncated or added tail is padding) followed by a re-shard under the
    template's sharding.  Same-shape checkpoints take the plain
    `restore_sharded` path (per-region reads, no full host assembly).

    The tree STRUCTURE (keypaths) must match exactly either way — a
    different model's checkpoint raises instead of silently flat-copying
    into garbage."""
    import jax

    path = Path(path)
    meta = read_meta(path)
    recs = meta["leaves"]
    with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    paths = [jax.tree_util.keystr(p) for p, _ in with_paths]
    if paths != [rec["path"] for rec in recs]:
        raise ValueError(
            f"fsdp checkpoint {path} structure mismatch: "
            f"{[rec['path'] for rec in recs][:3]}... vs {paths[:3]}..."
        )
    leaves = [leaf for _, leaf in with_paths]
    if all(
        tuple(rec["shape"]) == tuple(leaf.shape)
        for rec, leaf in zip(recs, leaves)
    ):
        return restore_sharded(path, like)

    # World-size translation: assemble each saved leaf fully on host
    # (stub templates carry the SAVED shapes), then flat-copy.
    stubs = [
        np.broadcast_to(np.zeros((), np.dtype(rec["dtype"])), tuple(rec["shape"]))
        for rec in recs
    ]
    full_tree, epoch = restore_sharded(
        path, jax.tree_util.tree_unflatten(treedef, stubs)
    )
    out = []
    for full, tmpl, rec in zip(
        jax.tree_util.tree_flatten(full_tree)[0], leaves, recs, strict=True
    ):
        if not isinstance(tmpl, jax.Array):
            out.append(full)
            continue
        if np.dtype(rec["dtype"]) != np.dtype(tmpl.dtype):
            raise ValueError(
                f"leaf {rec['path']}: dtype {rec['dtype']} in checkpoint "
                f"vs {np.dtype(tmpl.dtype)} in the template"
            )
        src = np.asarray(full).reshape(-1)
        tgt = np.zeros(int(np.prod(tmpl.shape)), src.dtype)
        m = min(src.size, tgt.size)
        tgt[:m] = src[:m]
        out.append(jax.device_put(tgt.reshape(tmpl.shape), tmpl.sharding))
    return jax.tree_util.tree_unflatten(treedef, out), epoch


def restore(path: str | Path, like: Any) -> tuple[Any, int]:
    """Restore into the structure of ``like`` (a template pytree with the
    same treedef, e.g. freshly-initialized params).  Returns
    ``(tree, step)``.  Checkpoints carrying a digest (everything written
    by `save` since the resilience layer landed) are checksum-verified —
    a truncated or bit-corrupted file raises instead of silently loading
    garbage; digest-less legacy files load unverified."""
    path = Path(path)
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves_like, treedef = _flatten_with_paths(like)
        if [k for k, _ in leaves_like] != meta["paths"]:
            raise ValueError(
                f"checkpoint {path} structure mismatch: "
                f"{meta['paths'][:3]}... vs {[k for k, _ in leaves_like][:3]}..."
            )
        leaves = [data[f"leaf_{i}"] for i in range(len(meta["paths"]))]
    digest = meta.get("digest")
    if digest is not None and _tree_digest(meta["paths"], leaves) != digest:
        raise ValueError(
            f"checkpoint {path} failed checksum validation (truncated or "
            f"corrupt) — use latest_intact() to find the newest valid "
            f"snapshot"
        )
    return jax.tree_util.tree_unflatten(treedef, leaves), meta["step"]


def _inspect(path: Path) -> int | None:
    """One-pass integrity check: the stored step when ``path`` is a
    readable, internally-consistent checkpoint, else None.

    ``.npz`` files: the archive must parse, every referenced leaf must be
    present, and the stored digest (when present) must match the bytes.
    Sharded DIRECTORY checkpoints: no in-progress attempt marker may be
    standing (a kill mid-``save_sharded`` leaves it), ``meta.json`` must
    parse, every referenced shard blob must verify (`_verify_blob`:
    size + embedded sha256), and per leaf the shards must account for
    the full domain — so a kill mid-sharded-write can never be selected
    for resume.  Any failure mode — truncation, a missing shard, bit
    rot under the digest — maps to None, never an exception."""
    try:
        if path.is_dir():
            if (path / "save_inprogress.json").exists():
                return None  # a save attempt died (or is live) mid-write
            meta = read_meta(path)
            for i, rec in enumerate(meta["leaves"]):
                dtype = np.dtype(rec["dtype"])
                covered = 0
                for shard in rec["shards"]:
                    if not _verify_blob(path / f"leaf_{i}" / shard["file"], dtype):
                        return None
                    covered += int(np.prod(shard["shape"]))
                if covered != int(np.prod(rec["shape"])):
                    return None  # shards do not tile the leaf's domain
            return int(meta["step"])
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(str(data["__meta__"]))
            leaves = [data[f"leaf_{i}"] for i in range(len(meta["paths"]))]
        digest = meta.get("digest")
        if digest is not None and _tree_digest(meta["paths"], leaves) != digest:
            return None
        return int(meta["step"])
    except Exception:
        return None


def verify(path: str | Path) -> bool:
    """True iff ``path`` is a readable, internally-consistent checkpoint
    (see `_inspect` for what is checked) — the predicate `latest_intact`
    scans with."""
    return _inspect(Path(path)) is not None


def latest_intact(
    directory: str | Path, pattern: str = "*ckpt_*"
) -> Path | None:
    """The newest VALID checkpoint under ``directory`` — the `--resume`
    entry point that survives preemption mid-write.

    Scans entries matching ``pattern`` (both ``ckpt_<n>.npz`` files and
    sharded ``ckpt_<n>`` directories), validating each in one pass;
    candidates are ranked by stored step (descending), then mtime — so a
    truncated newest snapshot is skipped and resume lands on the
    freshest state that actually loads.  Returns None when nothing valid
    exists.
    """
    directory = Path(directory)
    if not directory.is_dir():
        return None
    best: tuple[int, float, Path] | None = None
    for cand in directory.glob(pattern):
        if cand.name.endswith((".tmp", ".tmp.npz")):
            continue  # in-flight writes are not candidates
        step = _inspect(cand)
        if step is None:
            continue
        try:
            mtime = cand.stat().st_mtime
        except OSError:
            continue
        key = (step, mtime, cand)
        if best is None or key[:2] > best[:2]:
            best = key
    return best[2] if best is not None else None
