"""Checkpoint / resume.

Absent from the reference (SURVEY.md §5: no save/load anywhere; training
always starts fresh and runs exactly 10 epochs) — provided here as the
lightweight single-writer checkpoint the survey prescribes: DP state is
identical across replicas, so one host writes the pytree once, and resume
is by epoch index.  Kept off the parity-critical path.

Format: one ``.npz`` per checkpoint holding flattened leaves plus a JSON
treedef descriptor — no framework-specific serialization, readable with
plain numpy.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _flatten_with_paths(tree: Any):
    flat = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [(jax.tree_util.keystr(path), leaf) for path, leaf in flat[0]]
    return leaves, flat[1]


def save(path: str | Path, tree: Any, *, step: int = 0) -> None:
    """Single-writer save of a (replicated) pytree.  Only process 0 writes
    in a multi-process setting — replicas are identical (SURVEY.md §2c.6)."""
    if jax.process_index() != 0:
        return
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    leaves, _ = _flatten_with_paths(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, (_, x) in enumerate(leaves)}
    meta = {"step": step, "paths": [k for k, _ in leaves]}
    tmp = path.with_suffix(".tmp.npz")
    np.savez(tmp, __meta__=json.dumps(meta), **arrays)
    tmp.rename(path)


def save_orbax(path: str | Path, tree: Any, *, step: int = 0) -> None:
    """Alternative backend: orbax (async-capable, sharding-aware) for
    users standardized on it.  Same single-writer contract as `save`."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"tree": tree, "step": step}, force=True)


def restore_orbax(path: str | Path, like: Any) -> tuple[Any, int]:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path, {"tree": like, "step": 0})
    return state["tree"], int(state["step"])


class AsyncCheckpointer:
    """Overlap checkpoint IO with training.

    ``save()`` snapshots the pytree to host memory synchronously (cheap:
    one device→host copy; on TPU this is the only part that must block
    the step loop) and hands serialization + file IO to a background
    thread.  The next ``save()``/``wait()`` joins the previous write
    first, so at most one write is in flight and completed files appear
    in submission order.  The written format is exactly `save`'s — the
    two are interchangeable for `restore`.

    Single-writer contract as `save` (process 0 writes; other processes'
    calls are no-ops but still snapshot-free and cheap).  Always call
    ``wait()`` (or use as a context manager) before reading the file or
    exiting, and re-raise of background errors happens there.
    """

    def __init__(self):
        self._thread = None
        self._exc = None

    def save(self, path: str | Path, tree: Any, *, step: int = 0) -> None:
        import threading

        self.wait()
        if jax.process_index() != 0:
            return
        # Device→host transfer happens NOW (so the caller may freely
        # donate/mutate device buffers); everything after runs off-thread.
        host_tree = jax.tree.map(np.asarray, jax.device_get(tree))
        self._exc = None

        def _write():
            try:
                save(path, host_tree, step=step)
            except BaseException as e:  # surfaced on wait()
                self._exc = e

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight write (if any); re-raise its error here."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
            exc = getattr(self, "_exc", None)
            self._exc = None
            if exc is not None:
                raise exc

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.wait()
        return False


def restore(path: str | Path, like: Any) -> tuple[Any, int]:
    """Restore into the structure of ``like`` (a template pytree with the
    same treedef, e.g. freshly-initialized params).  Returns
    ``(tree, step)``."""
    path = Path(path)
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves_like, treedef = _flatten_with_paths(like)
        if [k for k, _ in leaves_like] != meta["paths"]:
            raise ValueError(
                f"checkpoint {path} structure mismatch: "
                f"{meta['paths'][:3]}... vs {[k for k, _ in leaves_like][:3]}..."
            )
        leaves = [data[f"leaf_{i}"] for i in range(len(meta["paths"]))]
    return jax.tree_util.tree_unflatten(treedef, leaves), meta["step"]
