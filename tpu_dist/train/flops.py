"""Model-FLOPs accounting and MFU (model FLOPs utilization).

The reference has no perf instrumentation beyond a hand-throttled timing
loop (allreduce.py:41-42); BASELINE.md's targets are throughput-shaped.
Throughput alone can't be judged against hardware — the missing figure is
achieved-FLOP/s as a fraction of the chip's peak (MFU).  Two counters:

1. ``xla_flops`` — the ground truth: XLA's own cost analysis of the
   compiled program (covers fwd+bwd+optimizer, fused exactly as
   executed).
2. Analytic per-layer counters (``conv2d_flops``/``linear_flops``/
   ``attention_flops``) — hardware-independent cross-checks and the
   conventional "model FLOPs" numerator (MFU counts model math only, so
   the XLA number — which includes optimizer/allreduce arithmetic — is a
   slight overestimate of the conventional numerator; both are exposed).

Peak numbers are the public per-chip bf16 (dense) specs.
"""

from __future__ import annotations

from typing import Any, Callable

# Public per-chip dense peak, FLOP/s.  bf16 is the MXU's native matmul
# dtype (fp32 inputs are handled via bf16x3 passes — far below this peak,
# so fp32 runs will legitimately show low MFU vs the bf16 figure).
_PEAK_BF16: dict[str, float] = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}


# Public per-chip HBM bandwidth, bytes/s — the decode-side roofline
# (autoregressive decode re-reads weights + KV cache every step, so
# tok/s is bounded by bandwidth long before the MXU matters).
_HBM_BW: dict[str, float] = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,  # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,  # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,  # v6e / Trillium
    "TPU v6e": 1640e9,
}


def _longest_prefix_match(table: dict[str, float], kind: str) -> float | None:
    """Most-specific (longest) prefix match: 'TPU v5 lite' must win over
    'TPU v5' for a v5e regardless of dict insertion order."""
    best: float | None = None
    best_len = -1
    for name, value in table.items():
        if kind.lower().startswith(name.lower()) and len(name) > best_len:
            best, best_len = value, len(name)
    return best


def hbm_bandwidth(device: Any | None = None) -> float | None:
    """Per-chip HBM bandwidth (bytes/s); None when unknown (CPU-sim)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    return _longest_prefix_match(_HBM_BW, kind)


def peak_flops(device: Any | None = None) -> float | None:
    """Per-chip bf16 peak FLOP/s for ``device`` (default: first device).

    Returns None for platforms without a known peak (CPU-sim) so callers
    report MFU only when it is meaningful.
    """
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    return _longest_prefix_match(_PEAK_BF16, kind)


def xla_flops(fn: Callable, *args: Any) -> float | None:
    """FLOPs of ONE invocation of ``fn(*args)`` per XLA cost analysis.

    ``fn`` may be a plain callable or an existing ``jax.jit`` object; it
    is lowered/compiled for the given example args (cached by jit, so
    calling this around a benchmark costs one compile at most).

    NOTE: for a program partitioned over N devices (pjit/shard_map), XLA
    reports the PER-DEVICE partitioned program's flops — multiply by the
    device count for a world total, or pass ``n_devices=1`` to `mfu` to
    get per-chip utilization (per-chip MFU equals whole-world MFU for an
    evenly sharded SPMD program).
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    try:
        compiled = jitted.lower(*args).compile()
        return compiled_flops(compiled)
    except Exception:
        return None


def compiled_flops(compiled: Any) -> float | None:
    """Extract the 'flops' entry from a compiled executable's cost
    analysis (handles the dict and list-of-dicts shapes across JAX
    versions)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    val = ca.get("flops")
    return float(val) if val else None


def mfu(
    flops_per_step: float | None,
    step_seconds: float,
    *,
    n_devices: int = 1,
    device: Any | None = None,
) -> float | None:
    """Achieved / peak FLOP-rate over ``n_devices`` chips; None when
    either side is unknown."""
    if not flops_per_step or step_seconds <= 0:
        return None
    peak = peak_flops(device)
    if not peak:
        return None
    return (flops_per_step / step_seconds) / (peak * n_devices)


# ---------------------------------------------------------------- analytic

def conv2d_flops(
    batch: int, h_out: int, w_out: int, c_in: int, c_out: int, k: int
) -> float:
    """2 · MACs for a k×k valid conv producing (h_out, w_out, c_out)."""
    return 2.0 * batch * h_out * w_out * c_in * c_out * k * k


def linear_flops(batch: int, d_in: int, d_out: int) -> float:
    return 2.0 * batch * d_in * d_out


def attention_flops(
    batch: int, heads: int, seq_q: int, seq_k: int, head_dim: int, *, causal: bool = False
) -> float:
    """QK^T + PV matmul FLOPs (the conventional 4·b·h·sq·sk·d).

    ``causal`` counts only the realizable score entries under the
    bottom-right (suffix) alignment `tpu_dist.nn.dot_product_attention`
    documents: query i (of sq, ending at key position sk) sees
    ``sk - sq + i + 1`` keys, so the fraction is
    ``(sq·sk - sq·(sq-1)/2) / (sq·sk)`` — ≈½ for sq == sk, but ~1 for
    decode-style sq ≪ sk, where halving would badly undercount."""
    f = 2.0 * batch * heads * seq_q * seq_k * head_dim * 2
    if not causal:
        return f
    realizable = seq_q * seq_k - seq_q * (seq_q - 1) / 2
    return f * realizable / (seq_q * seq_k)


def mnist_net_forward_flops(batch: int) -> float:
    """Analytic forward FLOPs of the reference ConvNet
    (train_dist.py:53-71): conv(1→10,k5) on 28² → 24², pool → 12²,
    conv(10→20,k5) → 8², pool → 4², fc 320→50, fc 50→10.
    Matmul/conv terms only (elementwise ops are noise on the MXU)."""
    return (
        conv2d_flops(batch, 24, 24, 1, 10, 5)
        + conv2d_flops(batch, 8, 8, 10, 20, 5)
        + linear_flops(batch, 320, 50)
        + linear_flops(batch, 50, 10)
    )


def train_step_flops_estimate(forward_flops: float) -> float:
    """Standard fwd+bwd estimate: backward ≈ 2× forward ⇒ 3× total."""
    return 3.0 * forward_flops
