"""LMTrainer — the language-model counterpart of `Trainer`.

The reference's training loop is image classification (train_dist.py:
103-127); `Trainer` reproduces it.  The LM family needs the same
conveniences with different plumbing — token batches, next-token loss,
perplexity instead of accuracy — so this is a sibling, built from the
same parts: `parallel.make_partitioned_train_step` (the engine's one
GSPMD step for dp/zero1/fsdp/tp rule sets, with accumulation and the
optional compressed gradient wire; the model-sharded sequence/pipeline/
moe modes ride `parallel.make_spmd_train_step`), the optimizer library
(clipping/EMA/optax all compose), and `train.checkpoint` (async
per-epoch writes).

Determinism contract matches the reference (SURVEY.md §2c.6): seeded
init, seeded per-epoch shuffles identical on every host, replicas
bit-identical.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp

from tpu_dist import parallel
from tpu_dist.models.transformer_lm import lm_loss, lm_perplexity
from tpu_dist.train.optim import Optimizer, adamw, clip_by_global_norm


@dataclass
class LMTrainConfig:
    epochs: int = 3
    global_batch: int = 64
    lr: float = 3e-3
    seed: int = 1234
    accum_steps: int = 1
    compute_dtype: str | None = None  # e.g. "bfloat16"
    # ZeRO-3: params/grads/opt state sharded 1/n — routed through the
    # partition engine (the 'fsdp' rule set bound to this mesh's 'data'
    # axis; the legacy shard_map builder is retired).  Checkpoints use
    # the sharded directory format with partition provenance; val
    # perplexity / generate gather params as needed.  Composes with
    # accum_steps and tensor_parallel (engine fsdp×tp rules).
    fsdp: bool = False
    # ZeRO-1: params replicated, optimizer state sharded 1/n — the
    # engine's 'zero1:dp' rule set.  Mutually exclusive with fsdp; same
    # sharded checkpoint format; composes with accum_steps and
    # tensor_parallel (like fsdp).
    zero1: bool = False
    # Tensor parallelism over a 2-D (data x model) mesh: "psum" = the
    # classic Megatron layout (replicated activations, two psums per
    # block, vocab-parallel head — loss_tensor_parallel); "sp" = the
    # Megatron-SP collective-matmul layout (activations sequence-sharded
    # between sublayers, all-gathers/reduce-scatters folded into the
    # matmuls — loss_tensor_parallel_sp).  Params stay replicated either
    # way (row-sharded when composed with fsdp), so checkpoints/eval/
    # generate are unchanged.
    tensor_parallel: str | None = None
    model_axis: str = "model"
    # Sequence/context-parallel TRAINING over a (data x seq) mesh:
    # "ring" = ring attention (K/V blocks rotate via ppermute while each
    # rank holds its sequence shard), "ulysses" = all-to-all head
    # resharding.  Tokens arrive (B/dp, S/seq); the boundary-correct
    # `lm_loss_seq_parallel` makes the seq-axis pmean equal the dense
    # loss.  Params replicated.  Mutually exclusive with the other
    # model-sharding modes.
    sequence_parallel: str | None = None
    seq_axis: str = "seq"
    # Pipeline-parallel training over a (data x pipe) mesh: "gpipe" =
    # the GPipe microbatch schedule (forward-only scheduling, autodiff
    # replays the scan — O(M) activation residuals), "1f1b" = the TRUE
    # 1F1B schedule-driven engine (`parallel.pipeline_engine_loss`):
    # backward ticks interleave with forward ticks, the activation
    # stash is O(n·v) with `pipe_interleave` virtual-stage chunks per
    # rank, and the measured schedule bubble fraction is reported per
    # step through telemetry.  Blocks are staged over the pipe axis
    # inside the compiled step (`TransformerLM.loss_pipeline`, grads
    # psum'd over 'pipe'); params replicated, so checkpoints/eval/
    # generate are unchanged.  Mutually exclusive with the other
    # model-sharding modes.
    pipeline: str | None = None
    pipe_axis: str = "pipe"
    pipe_microbatches: int = 4
    pipe_interleave: int = 2
    # Expert-parallel MoE training: the model must be built with
    # ``moe_experts == data-axis size`` (one expert per rank); the batch
    # shards over 'data' as usual and every MoE layer all_to_all-dispatches
    # tokens to their routed experts (`TransformerLM.loss_moe_ep`, with
    # the balance-loss regularizer).  The gradient contract is the
    # uniform data-axis pmean the step already applies; composes with
    # accum_steps.  NOT combinable with fsdp/zero1 anymore (those route
    # through the engine, and expert dispatch is not a rule vocabulary
    # yet); mutually exclusive with the other model-sharding modes.
    moe: bool = False
    # Bucketed error-feedback compressed gradient sync, INSIDE the
    # partition engine's GSPMD step (comm.compress): a wire spec like
    # 'int8' / 'fp8' / 'float8_e5m2' / 'bf16'.  Works on every
    # engine-routed config — dp, fsdp, zero1, composed mesh_axes
    # (dp×fsdp, dp×tp: model-sharded grads compress at their shard
    # shape over the data axes).  The EF residual rides the
    # optimizer-state checkpoint.  None = follow TPU_DIST_COMPRESS;
    # 'off' = force-disable.  Refused by the shard_map-only modes
    # (sequence/pipeline/moe, and the tensor_parallel flag without
    # fsdp/zero1 — use mesh_axes 'dp=A,tp=B' instead).
    grad_compress: str | None = None
    # Global-norm gradient clipping (LM-training staple).  Wraps the
    # optimizer in `train.clip_by_global_norm`, whose shard_update psums
    # squared shard norms — so clipping is by the TRUE global norm under
    # fsdp/zero1 too, and every mode's trajectory still matches dense.
    grad_clip: float | None = None
    # NaN guard (resilience.nan_guard): non-finite loss/grad steps are
    # skipped in-compile (params/opt state unchanged), counted
    # (LMEpochStats.bad_steps), and training continues.  loss_scale arms
    # the dynamic bf16 loss scale (escalating backoff on overflow) —
    # replicated modes only; under fsdp/zero1 the guard is
    # skip-and-count without scaling.
    nan_guard: bool = False
    loss_scale: float | None = None
    # Step-pipeline depth (see train.pipeline_driver): up to this many
    # dispatched-but-unread steps in flight; 0 = synchronous loop.
    # Drained at every observable boundary, so epoch stats / bad_steps /
    # checkpoints are depth-invariant.
    inflight_steps: int = 2
    # Partition engine (parallel.partition): a mesh-axes spec like
    # "dp=8", "zero1:dp=8", "dp=2,fsdp=4", or "dp=2,tp=2" selects a
    # rule set (regex path -> PartitionSpec, Megatron tp vocabulary for
    # the transformer layers) and routes training through ONE GSPMD
    # step: params/opt state sharded per the rules, the weight update
    # sharded over the data axes, composed 2-D/3-D meshes from one
    # knob.  The mesh must carry exactly these axes
    # (partition.build_mesh).  Mutually exclusive with every strategy
    # flag (fsdp/zero1/tensor/sequence/pipeline/moe); grad_compress
    # composes (the quantized wire rides inside the engine step).
    mesh_axes: str | None = None
    # Per-model overrides for the engine: (regex, spec) pairs matched
    # ahead of the built-ins (TPU_DIST_RULES env rules come first).
    partition_rules: list | None = None
    log: Callable[[str], None] = print


@dataclass
class LMEpochStats:
    epoch: int
    mean_loss: float
    seconds: float
    tokens_per_sec: float
    val_loss: float | None = None
    val_perplexity: float | None = None
    # cumulative non-finite steps skipped by the NaN guard (None = guard off)
    bad_steps: int | None = None


class LMTrainer:
    """Data-parallel LM training over ``(N, S)`` token windows."""

    def __init__(
        self,
        lm,
        mesh,
        config: LMTrainConfig | None = None,
        *,
        optimizer: Optimizer | None = None,
    ):
        self.lm = lm
        self.mesh = mesh
        self.config = config or LMTrainConfig()
        self.world = int(np.prod(mesh.devices.shape))
        self.optimizer = optimizer or adamw(self.config.lr)
        if self.config.grad_clip is not None:
            self.optimizer = clip_by_global_norm(
                self.optimizer, self.config.grad_clip
            )

        # Compressed gradient sync: resolved (and VALIDATED — a typo'd
        # wire dtype fails here, not at trace time) from config or the
        # TPU_DIST_COMPRESS env var.  The wire itself lives INSIDE the
        # partition engine (`make_partitioned_train_step(compress=)`).
        from tpu_dist.comm import compress as compress_mod

        self._compress = compress_mod.resolve(self.config.grad_compress)
        self._wrap_ef = (
            self._compress is not None and self._compress.error_feedback
        )
        if self.config.fsdp and self.config.zero1:
            raise ValueError("fsdp and zero1 are mutually exclusive")
        tp = self.config.tensor_parallel
        sp = self.config.sequence_parallel
        pp = self.config.pipeline
        moe = self.config.moe
        if sum(x is not None for x in (tp, sp, pp)) + bool(moe) > 1:
            raise ValueError(
                "tensor_parallel, sequence_parallel, pipeline, and moe "
                "are mutually exclusive trainer modes"
            )
        if tp is not None and tp not in ("psum", "sp"):
            raise ValueError(
                f"tensor_parallel must be 'psum' or 'sp', got {tp!r}"
            )
        if (
            tp is not None
            and self.config.mesh_axes is None
            and self.config.model_axis not in mesh.axis_names
        ):
            raise ValueError(
                f"tensor_parallel needs a {self.config.model_axis!r} "
                f"mesh axis; mesh has {mesh.axis_names}"
            )
        # Partition-engine routing: mesh_axes explicitly, or the legacy
        # fsdp/zero1/dp flags (± tensor_parallel) bound onto this mesh's
        # own axis names — ONE GSPMD step, one rule language (ROADMAP
        # item 2(d)).  The model-sharded LM modes that are not yet a
        # rule vocabulary (sequence/pipeline/moe, and tensor_parallel on
        # replicated params) keep the explicit shard_map step.
        self._ruleset = None
        self._partition_meta = None
        engine_spec, engine_bind = None, None
        if self.config.mesh_axes is not None:
            if self.config.fsdp or self.config.zero1:
                raise ValueError(
                    "mesh_axes selects a partition rule set — it replaces "
                    "the fsdp/zero1 strategy flags, do not combine them"
                )
            if tp is not None or sp is not None or pp is not None or moe:
                raise ValueError(
                    "mesh_axes is a rule-set mode of its own — tensor/"
                    "sequence/pipeline/moe flags select the explicit "
                    "shard_map step instead; express tp composition as a "
                    "'tp' axis in mesh_axes (e.g. 'dp=2,tp=2')"
                )
            if self.config.loss_scale is not None:
                raise ValueError(
                    "loss_scale is not threaded through the partitioned "
                    "step — use nan_guard without loss_scale under "
                    "mesh_axes"
                )
            engine_spec = self.config.mesh_axes
        elif self.config.fsdp or self.config.zero1:
            which = "fsdp" if self.config.fsdp else "zero1"
            if sp is not None:
                raise ValueError(
                    "sequence_parallel is not combinable with fsdp/zero1 "
                    "in the trainer (compose via "
                    "parallel.make_spmd_train_step's batch_spec instead)"
                )
            if pp is not None:
                raise ValueError(
                    "pipeline is not combinable with fsdp/zero1 in the "
                    "trainer (stage params already partition the model)"
                )
            if moe:
                raise ValueError(
                    f"moe is not combinable with {which} anymore: "
                    "fsdp/zero1 route through the partition engine, and "
                    "the expert all_to_all dispatch is not a rule "
                    "vocabulary yet — drop moe or the sharding flag"
                )
            if self.config.loss_scale is not None:
                raise ValueError(
                    "loss_scale is not threaded through the fsdp/zero1 "
                    "engine step — use nan_guard without loss_scale "
                    "there (skip-and-count still applies)"
                )
            data_ax = parallel.DATA_AXIS
            if data_ax not in mesh.axis_names:
                raise ValueError(
                    f"{which} expects a {data_ax!r} mesh axis; mesh has "
                    f"{tuple(mesh.axis_names)} — use mesh_axes to name "
                    "axes explicitly"
                )
            if tp is None and len(mesh.axis_names) != 1:
                raise ValueError(
                    f"{which} without tensor_parallel expects a 1-D "
                    f"{data_ax!r} mesh (got {tuple(mesh.axis_names)}); "
                    "use mesh_axes for composed meshes"
                )
            # fsdp/zero1 × tensor_parallel: the engine's tp rule
            # vocabulary takes over (both the 'psum' and 'sp' layouts
            # are GSPMD's call now — same global math).
            engine_spec, engine_bind = parallel.strategy_engine_spec(
                mesh, fsdp=self.config.fsdp, zero1=self.config.zero1,
                data_axis=data_ax,
                tp_axis=self.config.model_axis if tp is not None else None,
            )
        elif (
            tp is None and sp is None and pp is None and not moe
            and self.config.loss_scale is None
            and tuple(mesh.axis_names) == (parallel.DATA_AXIS,)
        ):
            # plain dp on the standard 1-D mesh → engine
            engine_spec, engine_bind = parallel.strategy_engine_spec(
                mesh, data_axis=parallel.DATA_AXIS
            )
        self._engine_mode = engine_spec is not None
        self._sharded_mode = (
            self.config.fsdp or self.config.zero1
            or self.config.mesh_axes is not None
        )
        # Compressed training checkpoints via the SHARDED directory
        # format too: the error-feedback residual is per-rank (sharded
        # over the data axes), which the single-writer npz cannot hold
        # on a multi-process mesh.
        self._sharded_ckpt = self._sharded_mode or self._wrap_ef
        if self._engine_mode:
            self._ruleset, self._partition_meta = (
                parallel.resolve_trainer_rules(
                    "LMTrainer", mesh, engine_spec,
                    user_rules=self.config.partition_rules,
                    bind=engine_bind,
                )
            )
        if self.config.loss_scale is not None and not self.config.nan_guard:
            raise ValueError("loss_scale requires nan_guard=True")
        if self.config.nan_guard:
            from tpu_dist.resilience.guards import nan_guard

            # Outermost wrapper (over grad_clip): the step builder reads
            # current_scale from the top-level optimizer, and a NaN grad
            # must be skipped before clipping touches it.  Without
            # loss_scale the guard is skip-and-count ONLY — pin the scale
            # to 1.0 (max_scale clamps growth) so no scaling ever arms
            # itself.
            if self.config.loss_scale is None:
                self.optimizer = nan_guard(self.optimizer, max_scale=1.0)
            else:
                self.optimizer = nan_guard(
                    self.optimizer, init_scale=self.config.loss_scale
                )
        if self._compress is not None and not self._engine_mode:
            # The compressed wire IS the engine's now: the model-sharded
            # LM modes that still run the explicit shard_map step cannot
            # carry it.  tensor_parallel could — through the engine —
            # so its refusal points there; sequence/pipeline/moe
            # genuinely lack a compressed path.
            if tp is not None:
                compress_mod.refuse_model_axes(
                    "LMTrainer", [self.config.model_axis],
                    rules=f"tensor_parallel={tp!r}",
                    hint="mesh_axes engine mode (e.g. 'dp=2,tp=2') "
                    "carries the compressed wire over the data axes of "
                    "a tp mesh — use it instead of the tensor_parallel "
                    "flag.",
                )
            if sp is not None or pp is not None or moe:
                mode_axes, mode = [], None
                if sp is not None:
                    mode_axes, mode = (
                        [self.config.seq_axis], f"sequence_parallel={sp!r}"
                    )
                elif pp is not None:
                    mode_axes, mode = (
                        [self.config.pipe_axis], f"pipeline={pp!r}"
                    )
                else:
                    mode = "moe=True (expert all_to_all over the data axis)"
                compress_mod.refuse_model_axes(
                    "LMTrainer", mode_axes, rules=mode,
                    hint="No engine rule vocabulary exists for this mode "
                    "yet (ROADMAP item 2), so there is no compressed "
                    "wire for it either.",
                )
            raise ValueError(
                "LMTrainer: grad_compress rides the partition engine's "
                "quantized wire — this configuration routes through the "
                "explicit shard_map step (loss_scale or a non-'data' "
                "mesh); drop the conflicting option or use mesh_axes "
                "engine mode"
            )
        if moe:
            world_data = mesh.shape.get(parallel.DATA_AXIS)
            if getattr(lm, "moe_experts", 0) != world_data:
                raise ValueError(
                    f"moe mode needs lm.moe_experts == data-axis size "
                    f"({world_data}), got {getattr(lm, 'moe_experts', 0)}"
                )
        if sp is not None:
            if sp not in ("ring", "ulysses"):
                raise ValueError(
                    f"sequence_parallel must be 'ring' or 'ulysses', "
                    f"got {sp!r}"
                )
            if self.config.seq_axis not in mesh.axis_names:
                raise ValueError(
                    f"sequence_parallel needs a {self.config.seq_axis!r} "
                    f"mesh axis; mesh has {mesh.axis_names}"
                )
        self._pipe_schedule = None
        if pp is not None:
            if pp not in ("gpipe", "1f1b"):
                raise ValueError(
                    f"pipeline must be 'gpipe' or '1f1b', got {pp!r}"
                )
            if self.config.pipe_axis not in mesh.axis_names:
                raise ValueError(
                    f"pipeline needs a {self.config.pipe_axis!r} mesh "
                    f"axis; mesh has {mesh.axis_names}"
                )
            from tpu_dist.parallel.pipeline import (
                build_schedule,
                default_schedule_kind,
            )

            n_pipe = int(mesh.shape[self.config.pipe_axis])
            v = self.config.pipe_interleave if pp == "1f1b" else 1
            kind = "gpipe" if pp == "gpipe" else default_schedule_kind(v)
            # Built here for two reasons: a bad (n, M, v) combination
            # fails at CONFIG time (not at trace time), and the table's
            # measured bubble fraction feeds the per-step telemetry.
            # The gpipe trainer path still executes via the scan-replay
            # `apply_pipeline` (kept until engine parity is the default
            # everywhere); its table has the identical tick structure,
            # so the reported bubble is the executed one either way.
            self._pipe_schedule = build_schedule(
                n_pipe, self.config.pipe_microbatches, v, kind
            )
        params, _ = lm.init(jax.random.key(self.config.seed))
        from tpu_dist.utils.debug import assert_no_aliasing

        compute = (
            jnp.dtype(self.config.compute_dtype)
            if self.config.compute_dtype
            else None
        )

        def cast(p):
            if compute is None:
                return p
            return jax.tree.map(
                lambda a: a.astype(compute)
                if jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                p,
            )

        def mode_loss(p, tokens):
            """The per-rank loss for the active model-sharding mode."""
            if tp == "sp":
                # tokens arrive (B/dp, S/tp): batch AND sequence sharded
                return self.lm.loss_tensor_parallel_sp(
                    cast(p), tokens, self.config.model_axis
                )
            if tp == "psum":
                return self.lm.loss_tensor_parallel(
                    cast(p), tokens, self.config.model_axis
                )
            if sp is not None:
                # tokens arrive (B/dp, S/seq): the boundary-correct loss
                logits = self.lm.apply_seq_parallel(
                    cast(p), tokens, self.config.seq_axis, attention=sp
                )
                from tpu_dist.models.transformer_lm import (
                    lm_loss_seq_parallel,
                )

                return lm_loss_seq_parallel(
                    logits.astype(jnp.float32), tokens, self.config.seq_axis
                )
            if pp is not None:
                # "1f1b" = the schedule-driven engine (true backward
                # interleaving); "gpipe" = the scan-replay path.  The
                # engine re-executes the SAME table the trainer built
                # at config time (kind threaded through, so the
                # telemetry bubble always describes the executed
                # schedule).
                return self.lm.loss_pipeline(
                    cast(p), tokens, self.config.pipe_axis,
                    n_microbatches=self.config.pipe_microbatches,
                    interleave=(
                        self.config.pipe_interleave if pp == "1f1b" else 1
                    ),
                    engine=(pp == "1f1b"),
                    schedule_kind=(
                        self._pipe_schedule.kind if pp == "1f1b" else None
                    ),
                )
            if moe:
                return self.lm.loss_moe_ep(
                    cast(p), tokens, parallel.DATA_AXIS
                )
            logits, _ = self.lm.apply(cast(p), {}, tokens)
            return lm_loss(logits.astype(jnp.float32), tokens)

        def loss_fn(p, s, batch, key):
            (tokens,) = batch
            return mode_loss(p, tokens), ({}, {})

        from jax.sharding import PartitionSpec as P

        # One source of truth for how token batches shard: over batch
        # AND sequence for the Megatron-SP and sequence-parallel modes,
        # batch only otherwise.  fit()/both step builders all use this.
        self._batch_spec = (
            self._ruleset.batch_spec()
            if self._ruleset is not None
            else P(parallel.DATA_AXIS, self.config.model_axis)
            if tp == "sp"
            else P(parallel.DATA_AXIS, self.config.seq_axis)
            if sp is not None
            else None
        )
        if self._engine_mode:
            # Partition-engine path: the DENSE loss on the global batch;
            # XLA's SPMD partitioner derives the per-device program and
            # collectives from the rule-matched shardings (tp rules give
            # the Megatron layout without a tensor-parallel loss fn).
            def engine_loss(p, batch, key):
                (tokens,) = batch
                logits, _ = self.lm.apply(cast(p), {}, tokens)
                return lm_loss(logits.astype(jnp.float32), tokens), {}

            built = parallel.make_partitioned_train_step(
                engine_loss, self.optimizer, mesh, params, self._ruleset,
                accum_steps=self.config.accum_steps,
                compress=self._compress,
            )
            self.params, self.opt_state = built.params, built.opt_state
            self._param_template = jax.tree.map(
                lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params
            )
            self._partition = built

            def engine_step(p, ms, os_, batch, key):
                p2, o2, loss, aux = built.step(p, os_, batch, key)
                return p2, ms, o2, loss, aux

            self.step = engine_step
        else:
            extra = ()
            if tp is not None:
                extra = (self.config.model_axis,)
            elif sp is not None:
                extra = (self.config.seq_axis,)
            self.params = parallel.replicate(params, mesh)
            self.opt_state = parallel.replicate(
                self.optimizer.init(params), mesh
            )
            assert_no_aliasing(self.params, self.opt_state)
            self.step = parallel.make_spmd_train_step(
                loss_fn, self.optimizer, mesh,
                accum_steps=self.config.accum_steps,
                extra_grad_axes=extra,
                # pipeline: per-rank grads PARTITION the dense gradient
                # over stages — sum, don't average
                grad_psum_axes=(
                    (self.config.pipe_axis,) if pp is not None else ()
                ),
                batch_spec=self._batch_spec,
            )
        self._model_state = parallel.replicate({}, mesh)
        # Pipeline-schedule accounting for telemetry (static per step):
        # the measured bubble fraction of the executed table.
        self._pipe_summary = None
        if self._pipe_schedule is not None:
            sched = self._pipe_schedule
            self._pipe_summary = {
                "kind": sched.kind,
                "n": sched.n,
                "microbatches": sched.n_microbatches,
                "chunks": sched.n_chunks,
                "ticks": sched.ticks,
                "bubble_fraction": round(sched.bubble_fraction(), 6),
                "stash_depth": sched.stash_depth,
            }
        # Wire accounting for telemetry (static per step): what the
        # engine's compressed sync ships vs what exact fp32 would.
        self._compress_summary = None
        if self._compress is not None:
            self._compress_summary = self._partition.flat_plan.wire_summary(
                "all_reduce"
            )

    def _full_params(self):
        """Full (logical-shape) parameters for eval/decode — identity for
        the replicated path, a compiled all-gather for rule-sharded
        engine state on multi-process meshes (fully-addressable engine
        shards pass through — jnp reads them directly)."""
        if self._engine_mode:
            return parallel.gather_replicated(self.params, self.mesh)
        return self.params

    def fit(
        self,
        windows,
        *,
        epochs: int | None = None,
        val_windows=None,
        checkpoint_dir: str | None = None,
        start_epoch: int = 0,
    ) -> list[LMEpochStats]:
        """``windows``: ``(N, S)`` int tokens (e.g. stacked
        `data.TextCorpus` windows or `models.synthetic_tokens`)."""
        cfg = self.config
        windows = np.asarray(windows)
        n, s = windows.shape
        gb = cfg.global_batch
        if n < gb:
            raise ValueError(
                f"{n} windows < global batch {gb} — shrink the batch or "
                f"use more data"
            )
        steps_per_epoch = n // gb
        from tpu_dist.train import metrics as metrics_mod
        from tpu_dist.train.checkpoint import AsyncCheckpointer

        writer = AsyncCheckpointer() if checkpoint_dir else None
        # Opt-in telemetry (TPU_DIST_TELEMETRY): manifest + per-step JSONL
        # events, heartbeat, host spans, goodput — see docs/observability.md.
        telemetry = metrics_mod.TrainTelemetry(
            world=self.world, mesh=self.mesh, config=cfg, trainer="LMTrainer",
            partition=self._partition_meta,
        )
        telemetry.set_compress(self._compress_summary)
        telemetry.set_pipeline(self._pipe_summary)
        ok = False
        try:
            history = self._fit_loop(
                cfg, windows, n, s, gb, steps_per_epoch, epochs, start_epoch,
                val_windows, checkpoint_dir, writer, telemetry,
            )
            if writer is not None:
                writer.wait()
            ok = True
            return history
        finally:
            # Always runs — a fit that raises must still flush the span
            # trace and mark this rank's heartbeat (crashed, not silent).
            telemetry.finish(ok=ok)

    def _fit_loop(
        self, cfg, windows, n, s, gb, steps_per_epoch, epochs, start_epoch,
        val_windows, checkpoint_dir, writer, telemetry,
    ) -> list[LMEpochStats]:
        """The epoch/step loop of `fit` (split out so fit can wrap it in
        the telemetry try/finally)."""
        from tpu_dist.comm import compress as compress_mod
        from tpu_dist.data.loader import HostLoader
        from tpu_dist.resilience.preempt import PreemptionGuard
        from tpu_dist.train import checkpoint as ckpt_mod
        from tpu_dist.train import metrics as metrics_mod
        from tpu_dist.train.pipeline_driver import PipelineDriver

        history = []
        # `with`: a fit that raises mid-epoch still drains the ring, so
        # already-dispatched steps keep their readbacks/telemetry.
        with PipelineDriver(telemetry, depth=cfg.inflight_steps) as driver, \
                PreemptionGuard() as preempt:
            for epoch in range(
                start_epoch, epochs if epochs is not None else cfg.epochs
            ):
                rng = np.random.default_rng(cfg.seed + epoch)  # host-identical
                order = rng.permutation(n)
                t0 = time.perf_counter()
                total, steps_done = 0.0, 0

                def host_batches(order=order):
                    for b in range(steps_per_epoch):
                        yield (windows[order[b * gb : (b + 1) * gb]],)

                # Background host loader: the fancy-index window gather +
                # sharded device_put run off the critical path, feeding
                # the in-flight ring.
                with HostLoader(
                    host_batches(), self.mesh, spec=self._batch_spec
                ) as batches:
                    for b in range(steps_per_epoch):
                        with telemetry.spans.span(
                            "data_next", step=telemetry.next_step_id
                        ):
                            batch = next(batches, None)
                        telemetry.sample_memory("data")
                        if batch is None:
                            break
                        key = jax.random.fold_in(
                            jax.random.fold_in(
                                jax.random.key(cfg.seed + 1), epoch
                            ), b
                        )
                        (
                            self.params,
                            self._model_state,
                            self.opt_state,
                            completed,
                        ) = driver.step(
                            self.step,
                            (self.params, self._model_state, self.opt_state,
                             batch, key),
                            epoch=epoch,
                            batch_size=gb,
                            nan_guard=cfg.nan_guard,
                            extra=lambda step_s: {
                                "tokens_per_sec_per_chip": round(
                                    gb * s / step_s / self.world, 3
                                ),
                            },
                        )
                        for c in completed:
                            total += c.loss
                            steps_done += 1
                        if preempt.requested:
                            break
                # Observable boundary: every dispatched step's loss lands
                # in this epoch's mean before eval/checkpoint/preempt
                # touch the state.
                for c in driver.drain():
                    total += c.loss
                    steps_done += 1
                if preempt.requested:
                    telemetry.preempted(
                        signal=preempt.signal_name, epoch=epoch,
                        step=steps_done,
                    )
                    # Step boundary after SIGTERM/SIGINT: one synchronous
                    # checkpoint recording the CURRENT (incomplete) epoch
                    # — restore() hands it back as the resume epoch — then
                    # a clean stop.
                    if checkpoint_dir:
                        if writer is not None:
                            writer.wait()
                        tree = {
                            "params": self.params, "opt_state": self.opt_state
                        }
                        with telemetry.goodput.measure("checkpoint") as ck:
                            if self._sharded_ckpt:
                                path = f"{checkpoint_dir}/lm_ckpt_preempt"
                                ckpt_mod.save_sharded(
                                    path, tree, step=epoch,
                                    partition=self._partition_meta,
                                )
                            else:
                                path = f"{checkpoint_dir}/lm_ckpt_preempt.npz"
                                ckpt_mod.save(path, tree, step=epoch)
                        telemetry.checkpoint_done(
                            path=path, epoch=epoch, seconds=ck.seconds,
                        )
                    cfg.log(
                        f"preemption ({preempt.signal_name}) at epoch "
                        f"{epoch} step {steps_done}: "
                        + (
                            "checkpoint written, stopping"
                            if checkpoint_dir
                            else "no checkpoint_dir, stopping"
                        )
                    )
                    break
                dt = time.perf_counter() - t0
                mean = total / steps_per_epoch
                tps = steps_per_epoch * gb * s / dt
                vloss = vppl = None
                if val_windows is not None:
                    with telemetry.goodput.measure("eval"):
                        host = jax.tree.map(np.asarray, self._full_params())
                        vloss, vppl = lm_perplexity(
                            self.lm, host, np.asarray(val_windows),
                            batch=min(64, len(val_windows)),
                        )
                bad = (
                    metrics_mod.bad_steps(self.opt_state)
                    if cfg.nan_guard
                    else None
                )
                cfg.log(
                    f"epoch {epoch}: loss {mean:.4f}  [{tps:,.0f} tok/s]"
                    + (f"  val loss {vloss:.4f} ppl {vppl:.1f}" if vppl else "")
                    + (f"  bad_steps {bad}" if bad else "")
                )
                history.append(
                    LMEpochStats(epoch, mean, dt, tps, vloss, vppl, bad)
                )
                telemetry.epoch_done(
                    epoch=epoch, mean_loss=mean, seconds=dt,
                    tokens_per_sec=round(tps, 3), val_loss=vloss,
                    val_perplexity=vppl, bad_steps=bad,
                )
                telemetry.compress_done(
                    error=compress_mod.ef_error(self.opt_state), epoch=epoch
                )
                if checkpoint_dir:
                    tree = {"params": self.params, "opt_state": self.opt_state}
                    with telemetry.goodput.measure("checkpoint") as ck:
                        if self._sharded_ckpt:
                            # sharded format = a DIRECTORY of shard files — no
                            # .npz suffix (ADVICE r2: a dir named .npz misleads)
                            path = f"{checkpoint_dir}/lm_ckpt_{epoch}"
                            writer.save_sharded(
                                path, tree, step=epoch + 1,
                                partition=self._partition_meta,
                            )
                        else:
                            path = f"{checkpoint_dir}/lm_ckpt_{epoch}.npz"
                            writer.save(path, tree, step=epoch + 1)
                    telemetry.checkpoint_done(
                        path=path, epoch=epoch, seconds=ck.seconds,
                    )
        return history

    def restore(self, path) -> int:
        from tpu_dist.comm import compress as compress_mod
        from tpu_dist.train import checkpoint

        like = {"params": self.params, "opt_state": self.opt_state}
        if self._sharded_ckpt:
            if self._ruleset is not None:
                # Engine mode: elastic resume.  Compatible provenance
                # restores directly; a different rule set or topology is
                # redistributed onto this run's shardings in
                # memory-bounded buckets (train.reshard).
                from tpu_dist.train import reshard as reshard_mod

                state, epoch, _ = reshard_mod.restore_or_redistribute(
                    path, like, self._partition_meta,
                    where=f"restore({path})",
                )
            else:
                # Rebuilt under the templates' shardings — replicated
                # leaves come back replicated, fsdp leaves row-sharded.
                state, epoch = checkpoint.restore_fsdp(path, like)
            self.params = state["params"]
            # A different-world-size checkpoint flat-copies fsdp rows
            # validly (zero padding) but would misdirect the dense
            # per-rank residual — zero it instead.
            self.opt_state = compress_mod.reset_resized_residual(
                state["opt_state"], checkpoint.read_meta(path),
                axis_name=parallel.DATA_AXIS,
            )
            return epoch
        state, epoch = checkpoint.restore(path, like)
        self.params = parallel.replicate(state["params"], self.mesh)
        self.opt_state = parallel.replicate(state["opt_state"], self.mesh)
        return epoch

    def generate(self, prompt, steps: int, **kw):
        """Decode with the current parameters (replicated device arrays
        feed the compiled decode directly; FSDP shards are reassembled
        first)."""
        return self.lm.generate(
            self._full_params(), jnp.asarray(np.asarray(prompt)), steps, **kw
        )
