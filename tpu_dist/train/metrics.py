"""Metrics / observability.

The reference's observability is per-rank ``print`` (SURVEY.md §5) plus a
hand-throttled benchmark loop (allreduce.py:41-42).  We keep that stdout
surface and add the counters the BASELINE targets need: step timing,
samples/sec/chip, and achieved collective GB/s, plus `jax.profiler` trace
hooks for perfetto inspection of ICI overlap.
"""

from __future__ import annotations

import contextlib
import math
import time
from dataclasses import dataclass, field

import jax


@dataclass
class StepTimer:
    """Wall-clock step timer with warmup discard (first steps include
    compilation).

    Two usage modes: as a context manager around a blocking step
    (enter/exit wall time), or via `tick` in a pipelined loop, where
    steps are dispatched without waiting and the meaningful per-step
    wall time is DISPATCH-TO-DISPATCH — the interval between successive
    `tick` calls (at steady state the device is the bottleneck, so the
    dispatch period equals the device step time)."""

    warmup: int = 2
    times: list = field(default_factory=list)
    _t0: float = 0.0
    _count: int = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._count += 1
        if self._count > self.warmup:
            self.times.append(time.perf_counter() - self._t0)

    def tick(self) -> None:
        """Record one dispatch boundary: the interval since the previous
        `tick` is a step time (the first call only arms the timer, and
        the first ``warmup`` intervals are discarded like the context
        manager's)."""
        now = time.perf_counter()
        if self._t0:
            self._count += 1
            if self._count > self.warmup:
                self.times.append(now - self._t0)
        self._t0 = now

    @property
    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)

    def samples_per_sec(self, batch: int) -> float:
        # NaN, not 0.0, when no steps were recorded: a silent zero reads
        # as "measured: infinitely slow" and poisons averages downstream.
        return batch / self.mean if self.times else float("nan")


def allreduce_gbps(nbytes: int, seconds: float, world: int) -> float:
    """Achieved ring-allreduce bus bandwidth: each rank moves
    2·(n-1)/n of the payload (reduce-scatter + all-gather lower bound)."""
    moved = 2 * (world - 1) / world * nbytes
    return moved / seconds / 1e9


@contextlib.contextmanager
def trace(dirname: str | None):
    """`jax.profiler` trace context — perfetto-viewable (SURVEY.md §5
    tracing equivalent).  No-op when dirname is None."""
    if dirname is None:
        yield
        return
    jax.profiler.start_trace(dirname)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def block_until_ready(tree):
    """Barrier for timing: wait for all device work in a pytree."""
    for leaf in jax.tree.leaves(tree):
        leaf.block_until_ready()
    return tree


def device_memory_stats(device=None) -> dict | None:
    """Live HBM statistics for one device (``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit``, ...) or None where the
    backend doesn't track them (CPU-sim).  The `watch nvidia-smi` analog
    (tuto.md:381), pulled from the runtime instead of a side tool.
    Telemetry consumers want `observe.memory.memory_snapshot` instead —
    it labels the source and falls back to host RSS on CPU-sim."""
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    return dict(stats) if stats else None


def bad_steps(opt_state) -> int | None:
    """Cumulative skipped-step count from a `resilience.nan_guard`
    optimizer state — the observable that says HOW OFTEN the run hit
    non-finite gradients (None when the state is unguarded).  Reading it
    syncs one device scalar; cheap next to the per-step loss readback."""
    from tpu_dist.resilience import guards

    return guards.bad_steps(opt_state)


def loss_scale(opt_state) -> float | None:
    """Live dynamic loss scale from a `resilience.nan_guard` optimizer
    state (None when unguarded)."""
    from tpu_dist.resilience import guards

    return guards.loss_scale(opt_state)


class TrainTelemetry:
    """Per-fit observability bundle shared by `Trainer` and `LMTrainer`:
    the JSONL event log, per-rank heartbeat, host-side span tracing,
    goodput accounting, and the process metrics registry
    (`tpu_dist.observe`) behind one call surface.

    Opt-in: with ``TPU_DIST_TELEMETRY`` unset, every call is a cheap
    no-op (registry updates excepted — those are in-memory and only
    exported when ``TPU_DIST_METRICS_PORT`` is set).  Constructing one
    emits the run manifest (config/mesh/platform provenance)."""

    # Consecutive bad (NaN-guard-skipped) steps that trigger ONE flight-
    # recorder dump: a single skipped step is routine, a streak means
    # the run is poisoned and the ring holds the steps that did it.
    NAN_STREAK_DUMP = 3

    def __init__(
        self, *, world: int, mesh, config, trainer: str, partition=None
    ):
        from tpu_dist import observe

        self.events = observe.events.from_env()
        self.enabled = self.events.enabled
        self.heartbeat = observe.heartbeat.from_env() if self.enabled else None
        self.spans = observe.spans.from_env()
        self.goodput = observe.heartbeat.GoodputMeter()
        # Always-on forensic ring (observe.flightrec): step/phase records
        # cost one deque append each, dumped only when something fires.
        self.flight = observe.flightrec.get()
        self.flight.record("mark", what="fit_start", trainer=trainer)
        # Live memory accounting (observe.memory): phase-bucketed
        # watermark sampler — HBM where tracked, host-RSS on CPU-sim.
        # Sampling is gated on telemetry; the OOM catch is always on.
        self.memory = observe.memory.WatermarkSampler(flight=self.flight)
        self._last_bad: int | None = None
        self._last_bad_sid = 0
        self._bad_streak = 0
        self._nan_dumped = False
        observe.registry.maybe_serve_from_env()
        reg = observe.registry.REGISTRY
        self._steps_c = reg.counter(
            "tpu_dist_steps_total", "optimizer steps taken"
        )
        self._loss_g = reg.gauge("tpu_dist_loss", "last training-step loss")
        self._step_h = reg.histogram(
            "tpu_dist_step_seconds", "train step wall time (seconds)"
        )
        self._bad_g = reg.gauge(
            "tpu_dist_bad_steps", "cumulative NaN-guard skipped steps"
        )
        self._wire_c = reg.counter(
            "tpu_dist_bytes_on_wire_total",
            "gradient-sync bytes shipped per rank (compressed wire)",
        )
        self._saved_c = reg.counter(
            "tpu_dist_bytes_saved_total",
            "gradient-sync bytes saved per rank vs exact fp32",
        )
        self._cerr_g = reg.gauge(
            "tpu_dist_compression_error",
            "relative quantization error of the last compressed sync",
        )
        self._compress_summary: dict | None = None
        self._pipe_summary: dict | None = None
        self._bubble_g = reg.gauge(
            "tpu_dist_bubble_fraction",
            "measured pipeline-schedule idle fraction (0 when not pipelined)",
        )
        self._every = observe.events.step_every()
        self.world = world
        self.global_step = 0
        self._dispatched = 0
        self._pending_tail = None
        self._compiled = False
        self._flops: float | None = None
        self._flops_captured = False
        # Mesh/rule-set provenance: the partition-engine summary when
        # one is active, otherwise the mesh axes alone (rules: null) —
        # every epoch event carries it, so an operator can tell WHAT
        # sharded a run without reading the config.
        self._partition_summary = partition or {
            "rules": None,
            "axes": observe.events.mesh_summary(mesh).get("shape", {}),
        }
        if self.enabled:
            self.events.manifest(
                world=world, config=config, mesh=mesh, trainer=trainer,
                partition=self._partition_summary,
            )

    @property
    def next_step_id(self) -> int:
        """Step id the NEXT dispatch will be assigned — the span
        correlation key for host phases that precede a dispatch (e.g.
        ``data_next``).  Under pipelining ``global_step`` (readbacks)
        lags dispatches by the ring depth, so spans must key off the
        dispatch counter, not the readback counter."""
        return self._dispatched + 1

    def capture_step_flops(self, step_fn, step_args: tuple) -> None:
        """XLA-measured FLOPs of one compiled step, for per-step MFU.
        Call BEFORE the first step executes (donated buffers are dead
        after it).  Only works when ``step_fn`` is a `jax.jit` object
        (has ``.lower``); costs one extra AOT compile, so it only runs
        when telemetry is on."""
        if self._flops_captured or not self.enabled:
            return
        self._flops_captured = True
        if not hasattr(step_fn, "lower"):
            return
        from tpu_dist.train import flops as flops_mod

        self._flops = flops_mod.xla_flops(step_fn, *step_args)

    def dispatch_step(
        self,
        step_fn,
        args: tuple,
        *,
        epoch: int,
        index: int = 0,
        batch_size: int,
        nan_guard: bool = False,
        extra=None,
    ):
        """Dispatch one training step WITHOUT waiting for its result —
        the pipelined half of the instrumentation choreography: FLOPs
        capture (first call), step-id assignment, the ``dispatch`` span,
        dispatch-phase goodput/heartbeat, and (when this step will emit
        an event) async device-side copies of the NaN-guard scalars,
        taken now because the opt-state leaves they live in are donated
        into the next dispatch.

        ``args`` is the step's ``(params, model_state, opt_state, batch,
        key)``.  Returns ``(outputs, pending)`` where ``outputs`` is the
        step's raw 5-tuple and ``pending`` is the
        `pipeline_driver.PendingStep` to hand to `complete_step` later
        (`PipelineDriver` does both)."""
        from tpu_dist.train.pipeline_driver import PendingStep

        self.capture_step_flops(step_fn, args)
        self._dispatched += 1
        sid = self._dispatched
        t0 = time.perf_counter()
        # dispatch-to-dispatch wall time: this dispatch closes the
        # previous step's interval (the pipelined loop's step time)
        prev = self._pending_tail
        if prev is not None and prev.d2d_seconds is None:
            prev.d2d_seconds = t0 - prev.t_dispatch
        try:
            with self.spans.span("dispatch", step=sid):
                out = step_fn(*args)
        except Exception as e:
            # RESOURCE_EXHAUSTED on the dispatch path: build the
            # plan-vs-live report and dump the flight ring BEFORE the
            # exception unwinds the fit (observe.memory OOM forensics).
            self._maybe_record_oom(e, phase="dispatch", step_args=args)
            raise
        dispatch_s = time.perf_counter() - t0
        self.sample_memory("dispatch")
        self.flight.record("step", step=sid, phase="dispatch", epoch=epoch)
        self.goodput.account_phase("dispatch", dispatch_s)
        if self.heartbeat is not None:
            # The ONE per-step beat (same file-write cadence as the
            # synchronous loop had): dispatch is the timely progress
            # signal under pipelining — a wedged device blocks the next
            # readback, which blocks the next dispatch, so the beat
            # still goes stale within K steps of a stall.
            self.heartbeat.beat(step=sid, phase="dispatch")
        emit = self.enabled and sid % self._every == 0
        bad_ref = scale_ref = None
        if emit and nan_guard:
            from tpu_dist.resilience.guards import _guard_state

            g = _guard_state(out[2])
            if g is not None:
                # `x + 0` is an async device-side copy: a NEW buffer the
                # next dispatch's donation cannot invalidate, and reading
                # it back later syncs only through THIS step.
                bad_ref = g["bad_steps"] + 0
                scale_ref = g["scale"] + 0
        pending = PendingStep(
            step_id=sid, epoch=epoch, index=index, loss=out[3],
            batch_size=batch_size, nan_guard=nan_guard, t_dispatch=t0,
            dispatch_seconds=dispatch_s, bad_ref=bad_ref,
            scale_ref=scale_ref, extra=extra, emit=emit,
        )
        self._pending_tail = pending
        return out, pending

    def sample_memory(self, phase: str) -> None:
        """One phase-bucketed watermark sample (no-op when telemetry is
        off — the snapshot read is cheap but not free on the hot path)."""
        if self.enabled:
            self.memory.sample(phase)

    def _resident_rows(self, step_args) -> list | None:
        """Per-class resident bytes from a step's args — best-effort:
        on the OOM path some buffers may already be donated/deleted, and
        the forensics must never mask the real exception."""
        try:
            from tpu_dist import parallel

            params = step_args[0] if len(step_args) > 0 else None
            opt = step_args[2] if len(step_args) > 2 else None
            batch = step_args[3] if len(step_args) > 3 else None
            return parallel.state_bytes_by_class(
                params, opt, batch=batch
            ) or None
        except Exception:
            return None

    def _maybe_record_oom(self, exc, *, phase: str, step_args=()) -> None:
        """RESOURCE_EXHAUSTED forensics on the step path: name the
        phase, the headroom, and the top resident class, then dump the
        flight ring (`observe.memory.record_oom`).  Any other exception
        passes through untouched."""
        from tpu_dist.observe import memory as memory_mod

        if not memory_mod.is_resource_exhausted(exc):
            return
        self.flight.record("mark", what="oom_detected", phase=phase)
        memory_mod.record_oom(
            exc,
            phase=phase,
            sampler=self.memory,
            resident=self._resident_rows(step_args),
            events_logger=self.events,
        )

    def complete_step(self, pending) -> float:
        """Read back one pending step's results and emit its telemetry —
        the ``readback`` span and the step event carry the step id
        assigned at DISPATCH time, so the event stream and the perfetto
        correlation recipe are unchanged by pipelining.  Returns the loss
        as a float."""
        sid = pending.step_id
        t0 = time.perf_counter()
        try:
            with self.spans.span("readback", step=sid):
                loss_f = float(pending.loss)
        except Exception as e:
            # a deferred allocation failure surfaces at readback — same
            # forensics, attributed to the readback phase
            self._maybe_record_oom(e, phase="readback")
            raise
        self.sample_memory("readback")
        self.flight.record(
            "step", step=sid, phase="readback", epoch=pending.epoch,
        )
        self.goodput.account_phase("readback", time.perf_counter() - t0)
        # Per-step wall time: dispatch-to-dispatch where a next dispatch
        # exists; dispatch-to-completion for the last steps of a drain.
        step_s = (
            pending.d2d_seconds
            if pending.d2d_seconds is not None
            else time.perf_counter() - pending.t_dispatch
        )
        bad = int(pending.bad_ref) if pending.bad_ref is not None else None
        scale = (
            float(pending.scale_ref) if pending.scale_ref is not None else None
        )
        self.step_done(
            epoch=pending.epoch,
            loss=loss_f,
            step_seconds=step_s,
            batch_size=pending.batch_size,
            nan_guard=pending.nan_guard,
            step=sid,
            bad=bad,
            scale=scale,
            **(pending.extra(step_s) if pending.extra is not None else {}),
        )
        return loss_f

    def run_step(
        self,
        step_fn,
        args: tuple,
        *,
        epoch: int,
        batch_size: int,
        nan_guard: bool = False,
        extra=None,
    ):
        """Execute one training step SYNCHRONOUSLY (dispatch + immediate
        readback) — the depth-0 composition of `dispatch_step` /
        `complete_step`, kept for callers that want the blocking
        contract.  Returns ``(params, model_state, opt_state,
        loss_float)``."""
        out, pending = self.dispatch_step(
            step_fn, args, epoch=epoch, batch_size=batch_size,
            nan_guard=nan_guard, extra=extra,
        )
        loss_f = self.complete_step(pending)
        return out[0], out[1], out[2], loss_f

    def step_done(
        self,
        *,
        epoch: int,
        loss: float,
        step_seconds: float,
        batch_size: int,
        opt_state=None,
        nan_guard: bool = False,
        step: int | None = None,
        bad: int | None = None,
        scale: float | None = None,
        **extra,
    ) -> None:
        """Record one completed optimizer step (the first one of a fit is
        accounted as compile time, not productive time).  ``step``
        defaults to the readback counter; pipelined callers pass the
        dispatch-assigned id.  ``bad``/``scale`` short-circuit the
        opt-state readback when the guard scalars were already captured
        at dispatch time."""
        self.goodput.account(
            "productive" if self._compiled else "compile", step_seconds
        )
        self._compiled = True
        self.global_step += 1
        sid = step if step is not None else self.global_step
        self._steps_c.inc()
        self._loss_g.set(loss)
        self._step_h.observe(step_seconds)
        cs = self._compress_summary
        if cs is not None:  # wire cost is static per step — count it here
            self._wire_c.inc(cs["bytes_on_wire"])
            self._saved_c.inc(cs["bytes_exact"] - cs["bytes_on_wire"])
        if not self.enabled or sid % self._every:
            return
        from tpu_dist.train import flops as flops_mod

        if nan_guard and bad is None:
            bad = bad_steps(opt_state)
            scale = loss_scale(opt_state)
        if bad is not None:
            self._bad_g.set(bad)
            # NaN-guard poison streak: NAN_STREAK_DUMP consecutive
            # skipped steps dump the flight ring once — the post-mortem
            # shows the exact steps that went bad, not just the count.
            # ``bad`` is cumulative and only observed at emitted steps
            # (TPU_DIST_TELEMETRY_EVERY sampling), so "consecutive" is
            # judged against the step delta: the streak only grows when
            # EVERY step since the last observation was bad.
            if self._last_bad is not None:
                d_bad = bad - self._last_bad
                d_steps = max(sid - self._last_bad_sid, 1)
                if d_bad >= d_steps:
                    self._bad_streak += d_steps
                elif d_bad > 0:
                    self._bad_streak = 1  # bad again, but not consecutive
                else:
                    self._bad_streak = 0
            self._last_bad = bad
            self._last_bad_sid = sid
            if self._bad_streak >= self.NAN_STREAK_DUMP and not self._nan_dumped:
                self._nan_dumped = True
                from tpu_dist.observe import flightrec as flightrec_mod

                self.flight.record(
                    "mark", what="nan_streak", bad_steps=bad, step=sid,
                )
                flightrec_mod.crash_dump("nan_streak")
        self.events.emit(
            "step",
            step=sid,
            epoch=epoch,
            loss=loss,
            step_time=round(step_seconds, 6),
            samples_per_sec_per_chip=round(
                batch_size / step_seconds / self.world, 3
            ),
            mfu=flops_mod.mfu(self._flops, step_seconds),
            bad_steps=bad,
            loss_scale=scale,
            # HBM where the backend tracks it, host-RSS fallback on
            # CPU-sim (labeled source: "rss") — non-null on every mesh
            hbm=self.memory.snapshot(),
            bubble_fraction=self.bubble_fraction,
            **extra,
        )

    def set_compress(self, summary: dict | None) -> None:
        """Arm per-step wire accounting: ``summary`` is a
        `comm.compress.FlatPlan.wire_summary` dict (None = sync is
        uncompressed; all compress telemetry stays silent)."""
        self._compress_summary = summary

    def set_pipeline(self, summary: dict | None) -> None:
        """Arm pipeline-schedule accounting: ``summary`` carries the
        executed schedule table's numbers (``kind``, ``ticks``,
        ``stash_depth``, and the MEASURED ``bubble_fraction`` — idle
        cells over all (tick, rank) cells).  None = the run is not
        pipeline-parallel; step/epoch events then carry
        ``bubble_fraction: null``."""
        self._pipe_summary = summary
        if summary is not None:
            self._bubble_g.set(summary["bubble_fraction"])
            self.goodput.set_bubble_fraction(summary["bubble_fraction"])

    @property
    def bubble_fraction(self) -> float | None:
        """Measured schedule bubble of the active pipeline run (None
        when not pipelined) — static per step, set once per fit."""
        if self._pipe_summary is None:
            return None
        return self._pipe_summary["bubble_fraction"]

    def compress_done(self, *, error: float | None, epoch: int) -> None:
        """Per-epoch compressed-sync record: the `compression_error`
        gauge plus a ``compress`` event carrying the wire accounting.
        No-op unless `set_compress` armed a summary."""
        cs = self._compress_summary
        if cs is None:
            return
        if error is not None and math.isfinite(error):
            self._cerr_g.set(error)
        if self.enabled:
            self.events.emit(
                "compress",
                epoch=epoch,
                wire=cs["wire"],
                mode=cs["mode"],
                buckets=cs["buckets"],
                bytes_on_wire=cs["bytes_on_wire"],
                bytes_saved=cs["bytes_exact"] - cs["bytes_on_wire"],
                compression_error=error,
            )

    def epoch_done(self, *, epoch: int, mean_loss: float, seconds: float,
                   **extra) -> None:
        if self.enabled:
            self.events.emit(
                "epoch",
                epoch=epoch,
                mean_loss=mean_loss,
                seconds=round(seconds, 4),
                goodput=self.goodput.summary(),
                bubble_fraction=self.bubble_fraction,
                pipeline=self._pipe_summary,
                mesh=self._partition_summary,
                **extra,
            )
            # the per-epoch memory event: latest watermark snapshot +
            # phase-bucketed deltas (observe.memory schema)
            self.memory.emit(self.events)

    def checkpoint_done(self, *, path, epoch: int, seconds: float) -> None:
        self.sample_memory("checkpoint")
        if self.enabled:
            self.events.emit(
                "checkpoint",
                path=str(path),
                epoch=epoch,
                seconds=round(seconds, 4),
            )

    def preempted(self, *, signal: str, epoch: int, step: int) -> None:
        # SIGTERM/SIGINT inside a fit is absorbed by PreemptionGuard (no
        # process-level handler fires), so the preempt flight dump
        # happens here, at the step boundary the guard drained to.
        from tpu_dist.observe import flightrec as flightrec_mod

        self.flight.record(
            "mark", what="preempt", signal=signal, epoch=epoch, step=step,
        )
        flightrec_mod.crash_dump(f"preempt:{signal}")
        if self.enabled:
            self.spans.instant("preempt", step=self.global_step)
            self.events.emit(
                "preempt", signal=signal, epoch=epoch, step=step
            )

    def finish(self, ok: bool = True) -> None:
        """Fit-exit (call from a finally): flush the span trace, close
        this rank's heartbeat — ``done`` on a clean exit (a finished rank
        must not read as stalled), ``crashed`` when the fit raised (a
        dead rank must STAY attributable to peers' watchdogs).  Never
        raises: telemetry teardown must not mask the fit's exception."""
        try:
            self.flight.record("mark", what="fit_end", ok=ok)
        except Exception:
            pass
        try:
            self.spans.save()
        except Exception:
            pass
        try:
            if self.heartbeat is not None:
                self.heartbeat.close(phase="done" if ok else "crashed")
        except Exception:
            pass


def compiled_memory_analysis(fn, *args) -> dict | None:
    """Compile ``fn`` for ``args`` and report XLA's memory plan:
    argument/output/temp/code sizes in bytes.  Works on every backend
    (it's a compile-time property), so HBM footprints are checkable on
    the CPU-sim mesh before a chip is ever involved — e.g. asserting
    that remat or accum_steps actually shrinks temp memory."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args).compile()
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
