"""Metrics / observability.

The reference's observability is per-rank ``print`` (SURVEY.md §5) plus a
hand-throttled benchmark loop (allreduce.py:41-42).  We keep that stdout
surface and add the counters the BASELINE targets need: step timing,
samples/sec/chip, and achieved collective GB/s, plus `jax.profiler` trace
hooks for perfetto inspection of ICI overlap.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@dataclass
class StepTimer:
    """Wall-clock step timer with warmup discard (first steps include
    compilation)."""

    warmup: int = 2
    times: list = field(default_factory=list)
    _t0: float = 0.0
    _count: int = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._count += 1
        if self._count > self.warmup:
            self.times.append(time.perf_counter() - self._t0)

    @property
    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)

    def samples_per_sec(self, batch: int) -> float:
        return batch / self.mean if self.times else 0.0


def allreduce_gbps(nbytes: int, seconds: float, world: int) -> float:
    """Achieved ring-allreduce bus bandwidth: each rank moves
    2·(n-1)/n of the payload (reduce-scatter + all-gather lower bound)."""
    moved = 2 * (world - 1) / world * nbytes
    return moved / seconds / 1e9


@contextlib.contextmanager
def trace(dirname: str | None):
    """`jax.profiler` trace context — perfetto-viewable (SURVEY.md §5
    tracing equivalent).  No-op when dirname is None."""
    if dirname is None:
        yield
        return
    jax.profiler.start_trace(dirname)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def block_until_ready(tree):
    """Barrier for timing: wait for all device work in a pytree."""
    for leaf in jax.tree.leaves(tree):
        leaf.block_until_ready()
    return tree


def device_memory_stats(device=None) -> dict | None:
    """Live HBM statistics for one device (``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit``, ...) or None where the
    backend doesn't track them (CPU-sim).  The `watch nvidia-smi` analog
    (tuto.md:381), pulled from the runtime instead of a side tool."""
    import jax

    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    return dict(stats) if stats else None


def bad_steps(opt_state) -> int | None:
    """Cumulative skipped-step count from a `resilience.nan_guard`
    optimizer state — the observable that says HOW OFTEN the run hit
    non-finite gradients (None when the state is unguarded).  Reading it
    syncs one device scalar; cheap next to the per-step loss readback."""
    from tpu_dist.resilience import guards

    return guards.bad_steps(opt_state)


def loss_scale(opt_state) -> float | None:
    """Live dynamic loss scale from a `resilience.nan_guard` optimizer
    state (None when unguarded)."""
    from tpu_dist.resilience import guards

    return guards.loss_scale(opt_state)


def compiled_memory_analysis(fn, *args) -> dict | None:
    """Compile ``fn`` for ``args`` and report XLA's memory plan:
    argument/output/temp/code sizes in bytes.  Works on every backend
    (it's a compile-time property), so HBM footprints are checkable on
    the CPU-sim mesh before a chip is ever involved — e.g. asserting
    that remat or accum_steps actually shrinks temp memory."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args).compile()
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
