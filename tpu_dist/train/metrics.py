"""Metrics / observability.

The reference's observability is per-rank ``print`` (SURVEY.md §5) plus a
hand-throttled benchmark loop (allreduce.py:41-42).  We keep that stdout
surface and add the counters the BASELINE targets need: step timing,
samples/sec/chip, and achieved collective GB/s, plus `jax.profiler` trace
hooks for perfetto inspection of ICI overlap.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@dataclass
class StepTimer:
    """Wall-clock step timer with warmup discard (first steps include
    compilation)."""

    warmup: int = 2
    times: list = field(default_factory=list)
    _t0: float = 0.0
    _count: int = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._count += 1
        if self._count > self.warmup:
            self.times.append(time.perf_counter() - self._t0)

    @property
    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)

    def samples_per_sec(self, batch: int) -> float:
        return batch / self.mean if self.times else 0.0


def allreduce_gbps(nbytes: int, seconds: float, world: int) -> float:
    """Achieved ring-allreduce bus bandwidth: each rank moves
    2·(n-1)/n of the payload (reduce-scatter + all-gather lower bound)."""
    moved = 2 * (world - 1) / world * nbytes
    return moved / seconds / 1e9


@contextlib.contextmanager
def trace(dirname: str | None):
    """`jax.profiler` trace context — perfetto-viewable (SURVEY.md §5
    tracing equivalent).  No-op when dirname is None."""
    if dirname is None:
        yield
        return
    jax.profiler.start_trace(dirname)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def block_until_ready(tree):
    """Barrier for timing: wait for all device work in a pytree."""
    for leaf in jax.tree.leaves(tree):
        leaf.block_until_ready()
    return tree
