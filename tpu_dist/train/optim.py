"""Optimizers — pytree-based, jit-native.

The reference uses ``optim.SGD(lr=0.01, momentum=0.5)``
(train_dist.py:110).  `sgd` here reproduces torch's momentum semantics
exactly (buf = m·buf + g; p -= lr·buf — no dampening, no Nesterov) so the
MNIST parity run matches the reference's training dynamics.  `adamw` backs
the extended configs (ViT).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Optimizer:
    """An (init, update) pair over parameter pytrees.

    ``update(params, grads, state) -> (new_params, new_state)`` is pure and
    traced into the train step, so the whole optimizer runs fused on
    device.

    ``elementwise``: True when the update of every parameter element
    depends only on that element's own history (sgd, adamw) — the
    property the FSDP/ZeRO step builders rely on to run the optimizer on
    flat-padded per-rank shards.  Optimizers with whole-tensor
    statistics (adafactor's factored moments / RMS clipping) must set
    False; the sharded builders refuse them loudly instead of silently
    computing per-shard statistics that vary with world size.

    ``shard_update``: optional ``(params, grads, state, axis_name) ->
    (new_params, new_state)`` — the sharded-execution form, called by
    the FSDP/ZeRO-1 builders INSIDE shard_map on per-rank gradient
    shards when present.  It may use collectives over ``axis_name`` to
    reconstruct whole-tree statistics (e.g. `clip_by_global_norm` psums
    squared shard norms so every rank clips by the TRUE global norm).
    An optimizer with ``elementwise=False`` but a ``shard_update`` is
    still accepted by the sharded builders."""

    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]
    elementwise: bool = True
    shard_update: Callable[[Any, Any, Any, str], tuple[Any, Any]] | None = None


def sgd(lr, momentum: float = 0.0) -> Optimizer:
    """torch-semantics SGD with momentum (train_dist.py:110).

    ``lr`` may be a float (the reference's fixed 0.01) or a schedule
    ``f(step) -> lr`` from `tpu_dist.train.schedule`; with a schedule the
    state carries a step counter.

    State format: ``{"buf": <momentum pytree>?, "step": <int32>?}`` (keys
    present only when used).  Checkpoints embed this structure; it is
    part of the checkpoint compatibility surface.
    """
    lr_fn = lr if callable(lr) else None

    def init(params):
        state = {}
        if momentum != 0.0:
            state["buf"] = jax.tree.map(jnp.zeros_like, params)
        if lr_fn is not None:
            state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(params, grads, state):
        new_state = dict(state)
        if lr_fn is not None:
            step = state["step"]
            cur_lr = lr_fn(step)
            new_state["step"] = step + 1
        else:
            cur_lr = lr
        if momentum == 0.0:
            direction = grads
        else:
            direction = jax.tree.map(
                lambda b, g: momentum * b + g, state["buf"], grads
            )
            new_state["buf"] = direction
        new_params = jax.tree.map(
            lambda p, d: p - cur_lr * d, params, direction
        )
        return new_params, new_state

    return Optimizer(init, update)


def adamw(
    lr,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    decay_mask=None,
) -> Optimizer:
    """AdamW.  ``lr`` may be a float or a schedule ``f(step) -> lr``
    (the state's step counter drives it, matching `sgd`).

    ``decay_mask``: optional ``fn(path_str, leaf) -> bool`` selecting
    which parameters weight decay applies to (standard practice: skip
    biases and norm scales).  ``decay_mask_default`` implements that
    convention; None decays everything (backward compatible)."""
    lr_fn = lr if callable(lr) else (lambda _step: lr)

    def init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
        }

    def update(params, grads, state):
        step = state["step"] + 1
        cur_lr = lr_fn(state["step"])
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree.map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"], grads
        )
        bc1 = 1 - b1**step.astype(jnp.float32)
        bc2 = 1 - b2**step.astype(jnp.float32)

        def upd(p, m_, v_, decay_on=True):
            mh = m_ / bc1
            vh = v_ / bc2
            wd = weight_decay if decay_on else 0.0
            return p - cur_lr * (mh / (jnp.sqrt(vh) + eps) + wd * p)

        if decay_mask is None:
            new_params = jax.tree.map(upd, params, m, v)
        else:
            flat_p = jax.tree_util.tree_flatten_with_path(params)
            paths = [jax.tree_util.keystr(pth) for pth, _ in flat_p[0]]
            leaves_p = [leaf for _, leaf in flat_p[0]]
            leaves_m = jax.tree.leaves(m)
            leaves_v = jax.tree.leaves(v)
            new_leaves = [
                upd(p_, m_, v_, decay_mask(path, p_))
                for path, p_, m_, v_ in zip(
                    paths, leaves_p, leaves_m, leaves_v
                )
            ]
            new_params = jax.tree_util.tree_unflatten(flat_p[1], new_leaves)
        return new_params, {"step": step, "m": m, "v": v}

    return Optimizer(init, update)


def adafactor(
    lr=None,
    *,
    decay_rate: float = 0.8,
    eps1: float = 1e-30,
    eps2: float = 1e-3,
    clip_threshold: float = 1.0,
    weight_decay: float = 0.0,
    decay_mask=None,
    min_dim_size_to_factor: int = 128,
) -> Optimizer:
    """Adafactor (Shazeer & Stern 2018) — the TPU-era memory-efficient
    optimizer: the second moment of an (m, n) weight is stored FACTORED
    as a row vector (m,) and a column vector (n,) — ``m + n`` floats
    instead of ``m·n`` — and there is no first moment at all, so
    optimizer HBM drops from 2x params (Adam) to ~zero.  Matrices whose
    trailing dims are both >= ``min_dim_size_to_factor`` factor; biases
    and small leaves keep a full accumulator.

    ``lr=None`` (default) uses the paper's relative step size:
    ``alpha_t = max(eps2, RMS(param)) * min(1e-2, 1/sqrt(t))`` — no
    tuning needed.  An explicit float/schedule ``lr`` overrides it.
    Updates are RMS-clipped at ``clip_threshold`` (the paper's update
    clipping), and the second-moment decay anneals as
    ``beta2_t = 1 - t^-decay_rate``.

    ``decay_mask``: same contract as `adamw`'s (``fn(path, leaf) ->
    bool``; `decay_mask_default` skips biases/norm scales); None decays
    everything.

    State: ``{"step", "v": <per-leaf {"r","c"} or {"v"}>}`` — a pytree,
    so npz/orbax checkpointing works unchanged.  NOT usable with the
    FSDP/ZeRO step builders (``elementwise=False``): the factoring
    decision, RMS clipping, and relative step size are whole-tensor
    statistics, which per-rank shards would compute differently at
    every world size — the builders raise instead.
    """
    lr_fn = lr if callable(lr) else (None if lr is None else (lambda _s: lr))

    def _factored(p) -> bool:
        return (
            p.ndim >= 2
            and p.shape[-1] >= min_dim_size_to_factor
            and p.shape[-2] >= min_dim_size_to_factor
        )

    def init(params):
        def leaf_state(p):
            if _factored(p):
                return {
                    "r": jnp.zeros(p.shape[:-1], jnp.float32),
                    "c": jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32),
                }
            return {"v": jnp.zeros(p.shape, jnp.float32)}

        return {
            "step": jnp.zeros((), jnp.int32),
            "v": jax.tree.map(leaf_state, params),
        }

    def update(params, grads, state):
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        beta2 = 1.0 - sf ** (-decay_rate)

        def leaf(p, g, s, decay_on=True):
            g32 = g.astype(jnp.float32)
            g2 = jnp.square(g32) + eps1
            if "v" in s:
                v = beta2 * s["v"] + (1 - beta2) * g2
                u = g32 * jax.lax.rsqrt(v)
                new_s = {"v": v}
            else:
                r = beta2 * s["r"] + (1 - beta2) * g2.mean(axis=-1)
                c = beta2 * s["c"] + (1 - beta2) * g2.mean(axis=-2)
                # vhat ~= (r ⊗ c) / mean(r): rank-1 reconstruction of
                # the second moment (paper eq. 4)
                r_f = jax.lax.rsqrt(r / r.mean(axis=-1, keepdims=True))
                c_f = jax.lax.rsqrt(c)
                u = g32 * r_f[..., None] * c_f[..., None, :]
                new_s = {"r": r, "c": c}
            rms_u = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms_u / clip_threshold)
            if lr_fn is None:  # relative step size (paper alg. 4-6)
                rms_p = jnp.sqrt(jnp.mean(jnp.square(p.astype(jnp.float32))))
                alpha = jnp.maximum(eps2, rms_p) * jnp.minimum(
                    1e-2, 1.0 / jnp.sqrt(sf)
                )
            else:
                alpha = lr_fn(state["step"])
            wd = weight_decay if decay_on else 0.0
            new_p = p - (alpha * u + alpha * wd * p).astype(p.dtype)
            return new_p, new_s

        with_paths, treedef = jax.tree_util.tree_flatten_with_path(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_s = treedef.flatten_up_to(state["v"])
        res = [
            leaf(
                p, g, s,
                decay_mask(jax.tree_util.keystr(pth), p)
                if decay_mask is not None
                else True,
            )
            for (pth, p), g, s in zip(with_paths, leaves_g, leaves_s)
        ]
        return (
            treedef.unflatten([r_[0] for r_ in res]),
            {
                "step": step,
                "v": treedef.unflatten([r_[1] for r_ in res]),
            },
        )

    return Optimizer(init, update, elementwise=False)


def decay_mask_default(path: str, leaf) -> bool:
    """The standard AdamW decay convention: decay matrices, skip biases,
    norm scales, and any 1-D parameter."""
    lowered = path.lower()
    if any(tag in lowered for tag in ("bias", "scale", "'b'", "[b]")):
        return False
    return getattr(leaf, "ndim", 0) >= 2


def global_norm(tree: Any) -> jax.Array:
    """L2 norm over every leaf of a pytree (f32 accumulation).
    Canonical implementation lives in `tpu_dist.utils.tree`; re-exported
    here because it's the clipping companion."""
    from tpu_dist.utils.tree import global_norm as _gn

    if not jax.tree.leaves(tree):
        return jnp.zeros((), jnp.float32)
    return _gn(tree)


def _inner_sharded(optimizer: Optimizer):
    """The sharded-execution form of ``optimizer`` for wrapper
    composition: its own ``shard_update`` when present, a pass-through
    adapter when it is elementwise (per-rank rows are valid as-is), else
    None — the wrapper then has no sharded form either and the FSDP/
    ZeRO-1 builders refuse it."""
    if optimizer.shard_update is not None:
        return optimizer.shard_update
    if optimizer.elementwise:
        return lambda p, g, s, _ax: optimizer.update(p, g, s)
    return None


def clip_by_global_norm(optimizer: Optimizer, max_norm: float) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping: when the
    gradient pytree's L2 norm exceeds ``max_norm``, every leaf is scaled
    by ``max_norm / norm`` before the wrapped update (the standard
    recipe for stabilizing LM training).  State is the wrapped
    optimizer's, unchanged — checkpoints stay compatible.

    Runs inside the compiled train step; under data parallelism it
    composes after the gradient ``pmean``, so every replica clips the
    same averaged gradient and replicas stay bit-identical.

    Global-norm clipping is a WHOLE-TREE statistic, so the result is
    ``elementwise=False``: on per-rank gradient shards a local norm
    would differ per rank and per world size (silent divergence).  The
    FSDP/ZeRO-1 builders instead use the provided ``shard_update``,
    which psums the squared shard norms over the data axis — every rank
    clips by the true global norm and the trajectory matches dense.
    """
    if max_norm <= 0:
        raise ValueError(f"max_norm must be > 0, got {max_norm}")

    def _clip(grads, norm):
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)

    def update(params, grads, state):
        return optimizer.update(params, _clip(grads, global_norm(grads)), state)

    # Sharded form: shard rows partition the full gradient over the data
    # axis (zero padding contributes nothing), so psum of squared local
    # norms == squared global norm.  Delegate to the inner optimizer's
    # own sharded/elementwise update on the clipped shards.
    inner_sharded = _inner_sharded(optimizer)
    if inner_sharded is not None:
        def shard_update(params, grads, state, axis_name):
            from jax import lax

            # sum of squares directly (no sqrt-then-square round trip)
            sq = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)
            )
            norm = jnp.sqrt(lax.psum(sq, axis_name))
            return inner_sharded(params, _clip(grads, norm), state, axis_name)
    else:
        shard_update = None

    return Optimizer(optimizer.init, update, elementwise=False,
                     shard_update=shard_update)


def from_optax(tx, *, elementwise: bool = False) -> Optimizer:
    """Adapt an optax ``GradientTransformation`` to this framework's
    `Optimizer` (init/update) contract, so the whole optax catalog drops
    into `make_train_step` / `Trainer` unchanged.  State is the optax
    state pytree — checkpointable like any other.

    ``elementwise`` defaults to **False**: an arbitrary optax chain may
    carry whole-tensor statistics (``optax.adafactor``,
    ``optax.clip_by_global_norm``) that per-rank shards would compute
    differently at every world size, so the FSDP/ZeRO-1 builders refuse
    the result by default.  Pass ``elementwise=True`` only when every
    transform in the chain is per-element (e.g. plain ``optax.adamw``)
    and you want it on the sharded step builders."""

    def init(params):
        return tx.init(params)

    def update(params, grads, state):
        updates, new_state = tx.update(grads, state, params)
        import optax

        return optax.apply_updates(params, updates), new_state

    return Optimizer(init, update, elementwise=elementwise)


def with_ema(optimizer: Optimizer, decay: float = 0.999) -> Optimizer:
    """Track an exponential moving average of the parameters alongside
    any optimizer: ``ema = decay*ema + (1-decay)*params`` after each
    update, inside the same compiled step.  The shadow copy lives in the
    optimizer state under ``"ema"`` (checkpointed with everything else);
    read it back with `ema_params`.  Evaluating/serving with EMA weights
    is the standard trick for a final accuracy bump.
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"decay must be in (0, 1), got {decay}")

    def init(params):
        # A REAL copy, not jnp.asarray: the shadow tree must not share
        # buffers with the live params — under a donating train step a
        # shared buffer reaches the step through two donated arguments
        # at once (observed as an XLA:CPU collective-rendezvous crash).
        return {
            "base": optimizer.init(params),
            "ema": jax.tree.map(lambda a: jnp.array(a, copy=True), params),
        }

    def _track(new_params, ema):
        return jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p, ema, new_params
        )

    def update(params, grads, state):
        new_params, base = optimizer.update(params, grads, state["base"])
        return new_params, {"base": base, "ema": _track(new_params, state["ema"])}

    # EMA itself is per-element, so the sharded form exists iff the
    # inner optimizer is shardable (elementwise or shard_update-capable).
    inner_sharded = _inner_sharded(optimizer)
    if inner_sharded is not None:
        def shard_update(params, grads, state, axis_name):
            new_params, base = inner_sharded(params, grads, state["base"], axis_name)
            return new_params, {"base": base, "ema": _track(new_params, state["ema"])}
    else:
        shard_update = None

    return Optimizer(init, update, optimizer.elementwise,
                     shard_update=shard_update)


def ema_params(opt_state):
    """The EMA shadow parameters from a `with_ema` optimizer state."""
    return opt_state["ema"]
