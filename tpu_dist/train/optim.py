"""Optimizers — pytree-based, jit-native.

The reference uses ``optim.SGD(lr=0.01, momentum=0.5)``
(train_dist.py:110).  `sgd` here reproduces torch's momentum semantics
exactly (buf = m·buf + g; p -= lr·buf — no dampening, no Nesterov) so the
MNIST parity run matches the reference's training dynamics.  `adamw` backs
the extended configs (ViT).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Optimizer:
    """An (init, update) pair over parameter pytrees.

    ``update(params, grads, state) -> (new_params, new_state)`` is pure and
    traced into the train step, so the whole optimizer runs fused on
    device."""

    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]


def sgd(lr, momentum: float = 0.0) -> Optimizer:
    """torch-semantics SGD with momentum (train_dist.py:110).

    ``lr`` may be a float (the reference's fixed 0.01) or a schedule
    ``f(step) -> lr`` from `tpu_dist.train.schedule`; with a schedule the
    state carries a step counter.

    State format: ``{"buf": <momentum pytree>?, "step": <int32>?}`` (keys
    present only when used).  Checkpoints embed this structure; it is
    part of the checkpoint compatibility surface.
    """
    lr_fn = lr if callable(lr) else None

    def init(params):
        state = {}
        if momentum != 0.0:
            state["buf"] = jax.tree.map(jnp.zeros_like, params)
        if lr_fn is not None:
            state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(params, grads, state):
        new_state = dict(state)
        if lr_fn is not None:
            step = state["step"]
            cur_lr = lr_fn(step)
            new_state["step"] = step + 1
        else:
            cur_lr = lr
        if momentum == 0.0:
            direction = grads
        else:
            direction = jax.tree.map(
                lambda b, g: momentum * b + g, state["buf"], grads
            )
            new_state["buf"] = direction
        new_params = jax.tree.map(
            lambda p, d: p - cur_lr * d, params, direction
        )
        return new_params, new_state

    return Optimizer(init, update)


def adamw(
    lr,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> Optimizer:
    """AdamW.  ``lr`` may be a float or a schedule ``f(step) -> lr``
    (the state's step counter drives it, matching `sgd`)."""
    lr_fn = lr if callable(lr) else (lambda _step: lr)

    def init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
        }

    def update(params, grads, state):
        step = state["step"] + 1
        cur_lr = lr_fn(state["step"])
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree.map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state["v"], grads
        )
        bc1 = 1 - b1**step.astype(jnp.float32)
        bc2 = 1 - b2**step.astype(jnp.float32)

        def upd(p, m_, v_):
            mh = m_ / bc1
            vh = v_ / bc2
            return p - cur_lr * (mh / (jnp.sqrt(vh) + eps) + weight_decay * p)

        new_params = jax.tree.map(upd, params, m, v)
        return new_params, {"step": step, "m": m, "v": v}

    return Optimizer(init, update)
