"""Step pipelining — K-deep dispatch with deferred metrics readback.

The fused train step made the DEVICE side of a step one program, but the
host loop around it re-introduced a serializer: reading the loss back
(``float(loss)``) after every dispatch makes the host wait for step N's
device result before dispatching step N+1, so XLA's async dispatch and
the input-pipeline prefetch buy nothing — on the latency-bound parity
workload the host round-trip IS the step time.

`PipelineDriver` decouples dispatch from result consumption with a
bounded in-flight ring of depth K (``TrainConfig.inflight_steps``): the
trainer dispatches step N immediately and only reads back loss/metrics
for step N−K.  Correctness needs no per-step host decision — the NaN
guard skips non-finite steps *on device* (`resilience.guards.nan_guard`)
— so the only places the host must resynchronize are the observable
boundaries: epoch end, eval, checkpoint, preemption.  `drain` is that
explicit barrier, and because readbacks happen in FIFO dispatch order,
the drained loop produces bit-identical observable results (epoch mean
loss, bad_steps, checkpointed state) to the synchronous loop.

Depth semantics: ``depth=K`` keeps up to K dispatched-but-unread steps
in flight (the readback of step N−K happens right after dispatch of
step N).  ``depth=0`` is the synchronous loop — dispatch then immediate
readback — so both trainers run ONE code path and the sync/async choice
is pure config.

The driver is telemetry-aware but telemetry-optional: with a
`train.metrics.TrainTelemetry` it runs the full instrumentation
choreography (``dispatch`` spans at dispatch time, ``readback`` spans +
step events at readback time, with the step ids assigned at dispatch);
with ``telemetry=None`` (benchmarks) it only moves losses.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class PendingStep:
    """One dispatched-but-unread step in the ring.

    ``loss`` is the step's device scalar (a step OUTPUT — never donated
    into the next dispatch, so holding it is safe); ``bad_ref`` /
    ``scale_ref`` are async device-side COPIES of the NaN-guard scalars
    (the originals are opt-state leaves, dead the moment the next
    dispatch donates them).  ``d2d_seconds`` — dispatch-to-dispatch wall
    time, the pipelined loop's per-step time — is filled in by the NEXT
    dispatch; it stays None for the last steps of a drain, where
    dispatch-to-completion is reported instead."""

    step_id: int
    epoch: int
    index: int  # 0-based dispatch index, fit-global
    loss: Any
    batch_size: int
    nan_guard: bool = False
    t_dispatch: float = 0.0
    dispatch_seconds: float = 0.0
    d2d_seconds: float | None = None
    bad_ref: Any = None
    scale_ref: Any = None
    extra: Callable[[float], dict] | None = None
    emit: bool = False


@dataclass(frozen=True)
class CompletedStep:
    """A read-back step: what the training loop accumulates."""

    step_id: int
    epoch: int
    index: int  # 0-based dispatch index, fit-global
    loss: float


class PipelineDriver:
    """Bounded in-flight ring between a training loop and its compiled
    step.  See the module docstring for semantics; the step function
    contract is the trainers' 5-tuple ``step(params, model_state,
    opt_state, batch, key) -> (params, model_state, opt_state, loss,
    aux)``."""

    def __init__(self, telemetry=None, *, depth: int = 2):
        if depth < 0:
            raise ValueError(
                f"inflight depth must be >= 0 (0 = synchronous), got {depth}"
            )
        self.telemetry = telemetry
        self.depth = int(depth)
        self._ring: collections.deque[PendingStep] = collections.deque()
        self._dispatched = 0

    @property
    def in_flight(self) -> int:
        return len(self._ring)

    def step(
        self,
        step_fn: Callable,
        args: tuple,
        *,
        epoch: int = 0,
        batch_size: int = 0,
        nan_guard: bool = False,
        extra: Callable[[float], dict] | None = None,
    ) -> tuple[Any, Any, Any, list[CompletedStep]]:
        """Dispatch one step and read back whatever the depth bound
        evicts.  Returns ``(params, model_state, opt_state, completed)``
        — ``completed`` holds 0 or more `CompletedStep` in dispatch
        order (older steps whose results are now consumed)."""
        index = self._dispatched
        self._dispatched += 1
        if self.telemetry is not None:
            out, pending = self.telemetry.dispatch_step(
                step_fn, args,
                epoch=epoch, index=index, batch_size=batch_size,
                nan_guard=nan_guard, extra=extra,
            )
        else:
            t0 = time.perf_counter()
            out = step_fn(*args)
            pending = PendingStep(
                step_id=self._dispatched, epoch=epoch, index=index,
                loss=out[3], batch_size=batch_size, t_dispatch=t0,
                dispatch_seconds=time.perf_counter() - t0,
            )
        params, model_state, opt_state = out[0], out[1], out[2]
        self._ring.append(pending)
        completed = []
        while len(self._ring) > self.depth:
            completed.append(self._complete(self._ring.popleft()))
        return params, model_state, opt_state, completed

    def drain(self) -> list[CompletedStep]:
        """Read back EVERYTHING in flight — the explicit host/device
        barrier for observable boundaries (epoch end, eval, checkpoint,
        preemption).  After `drain` the host has every dispatched step's
        loss and the device queue is empty."""
        completed = []
        while self._ring:
            completed.append(self._complete(self._ring.popleft()))
        return completed

    def _complete(self, pending: PendingStep) -> CompletedStep:
        if self.telemetry is not None:
            loss_f = self.telemetry.complete_step(pending)
        else:
            loss_f = float(pending.loss)
        return CompletedStep(
            pending.step_id, pending.epoch, pending.index, loss_f
        )

    # drain-on-exit so a raising fit never leaves device work unobserved
    def __enter__(self) -> "PipelineDriver":
        return self

    def __exit__(self, *exc) -> None:
        try:
            self.drain()
        except Exception:
            # the primary exception (if any) must win; a failed readback
            # of an abandoned step is secondary
            if exc == (None, None, None):
                raise
