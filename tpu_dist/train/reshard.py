"""Elastic resume: memory-bounded checkpoint redistribution.

`checkpoint.restore_sharded` already reads arbitrary REGIONS of saved
leaves, so a same-rules world resize restores in place.  What it cannot
do is change the PARTITIONING itself — resume a dp=8 checkpoint on a
dp=2,fsdp=4 mesh, or a dp×tp run on dp×fsdp after a preemption returned
a different slice.  This module closes that gap with the redistribution
scheme of "Memory-efficient array redistribution" (arxiv 2112.01075)
adapted to the resume path: instead of all-to-all slice exchange between
live ranks, the saved shard files ARE the source layout, and each rank
streams exactly the regions its own target shards need, in bounded
buckets, never materializing a full replica of any leaf.

The phases (each start is a flight-ring mark, so a redistribution that
dies is post-mortem-debuggable like any collective):

  plan    map the template's target shardings onto the saved leaf
          domains: one transfer UNIT per unique target region (replicas
          of a region share the unit), greedy-packed into buckets of at
          most ``bucket_bytes``
  verify  integrity before any byte moves: every shard blob intersecting
          a needed region must pass `checkpoint._verify_blob` (embedded
          sha256); npz sources re-hash against the tree digest
  stream  per bucket, per unit: read the region from the intersecting
          shard files (`checkpoint._read_region`), place it on every
          device that needs it, release the staging buffer.  Transient
          host bytes are accounted EXACTLY by `observe.memory.
          TransientMeter` with the bound ``2 × largest bucket`` —
          crossing it raises instead of silently ballooning
  commit  assemble `jax.Array` leaves from the placed per-device shards
          (`jax.make_array_from_single_device_arrays`), unflatten, emit
          the validated ``reshard`` telemetry event

Shape-mismatched leaves (per-rank state like the error-feedback
residual, whose physical shape is a function of the rule set) cannot be
redistributed meaningfully; with ``on_shape_mismatch="reset"`` (the
default, matching `compress.reset_resized_residual` semantics) they are
zero-initialized under the target sharding and reported in the event.

Entry points: `redistribute` (the engine), `restore_or_redistribute`
(the trainers' resume route: direct restore when
`checkpoint.partition_mismatch` is empty, redistribution otherwise),
`target_templates` (build the target-sharding template tree from
partition rules + mesh for standalone use).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import numpy as np

from tpu_dist.train import checkpoint

# Default streaming granularity.  64 MiB mirrors the bucket sizing of
# the gradient-bucketing path (comm.bucketing): large enough that file
# IO amortizes, small enough that 2× the bucket is far below any leaf
# of interest at scale.
DEFAULT_BUCKET_BYTES = 64 << 20


class ReshardError(RuntimeError):
    """A redistribution failed.  ``phase`` names the phase that died
    ("plan" / "verify" / "stream" / "commit") — the same phase the
    flight-ring trail ends with."""

    def __init__(self, message: str, *, phase: str = "plan"):
        super().__init__(message)
        self.phase = phase


@dataclass(frozen=True)
class _Unit:
    """One transfer unit: a half-open region of one leaf, destined for
    one or more devices (replicas of the region share the unit — the
    region is read once and placed per device)."""

    leaf: int
    keypath: str
    bounds: tuple[tuple[int, int], ...]
    nbytes: int
    devices: tuple  # target devices; empty = host (numpy) leaf


@dataclass
class ReshardPlan:
    """The full redistribution plan for one (checkpoint, template) pair
    — inspectable before any byte moves (`plan_reshard`)."""

    path: Path
    step: int
    source: dict | None  # saved partition provenance (may be None)
    npz: bool
    units: list[_Unit]
    buckets: list[list[int]]  # indices into units
    reset_leaves: dict[int, str]  # leaf index -> keypath (zero-init)
    bytes_to_move: int
    bucket_bytes: int
    largest_bucket_bytes: int

    @property
    def bound_bytes(self) -> int:
        """The asserted transient-host-bytes ceiling: 2× the largest
        bucket (read-ahead of one bucket plus the buffers mid-handoff;
        for npz sources the one-leaf decompression cache is folded into
        the largest-bucket figure)."""
        return 2 * max(self.largest_bucket_bytes, 1)

    def summary(self) -> dict:
        return {
            "step": self.step,
            "units": len(self.units),
            "buckets": len(self.buckets),
            "bytes_to_move": self.bytes_to_move,
            "bucket_bytes": self.bucket_bytes,
            "largest_bucket_bytes": self.largest_bucket_bytes,
            "bound_bytes": self.bound_bytes,
            "leaves_reset": sorted(self.reset_leaves.values()),
        }


def _leaf_shape_dtype(tmpl: Any) -> tuple[tuple[int, ...], np.dtype]:
    if hasattr(tmpl, "shape") and hasattr(tmpl, "dtype"):
        return tuple(int(d) for d in tmpl.shape), np.dtype(tmpl.dtype)
    arr = np.asarray(tmpl)
    return tuple(arr.shape), arr.dtype


def _leaf_sharding(tmpl: Any):
    """The target sharding of a template leaf, or None for host leaves
    (numpy / python scalars, restored as fully-assembled numpy)."""
    import jax

    if isinstance(tmpl, jax.Array):
        return tmpl.sharding
    return getattr(tmpl, "sharding", None)  # ShapeDtypeStruct carries it


def _npz_leaf_headers(path: Path, n: int) -> list[tuple[tuple, np.dtype]]:
    """(shape, dtype) per npz leaf WITHOUT decompressing the data — the
    npy header inside the zip member carries both.  Falls back to full
    decompression (one leaf at a time) if the private header reader
    moves in a future numpy."""
    import zipfile

    from numpy.lib import format as npfmt

    out = []
    try:
        with zipfile.ZipFile(path) as zf:
            for i in range(n):
                with zf.open(f"leaf_{i}.npy") as fh:
                    version = npfmt.read_magic(fh)
                    shape, _fortran, dtype = npfmt._read_array_header(
                        fh, version
                    )
                out.append((tuple(int(d) for d in shape), np.dtype(dtype)))
        return out
    except (AttributeError, TypeError):
        out = []
        with np.load(path, allow_pickle=False) as z:
            for i in range(n):
                arr = z[f"leaf_{i}"]
                out.append((tuple(arr.shape), arr.dtype))
                del arr
        return out


def _load_source_meta(path: Path) -> tuple[dict, bool]:
    """Normalize either checkpoint format to sharded-dir meta shape:
    ``{"step", "partition"?, "leaves": [{"path","shape","dtype"}...]}``
    (npz leaves carry no shard table — the whole leaf is one region)."""
    if path.is_dir():
        return checkpoint.read_meta(path), False
    with np.load(path, allow_pickle=False) as z:
        raw = json.loads(str(z["__meta__"]))
    headers = _npz_leaf_headers(path, len(raw["paths"]))
    leaves = [
        {"path": keypath, "shape": list(shape), "dtype": dtype.name}
        for keypath, (shape, dtype) in zip(raw["paths"], headers, strict=True)
    ]
    meta = {"step": raw["step"], "leaves": leaves, "digest": raw.get("digest")}
    if "partition" in raw:
        meta["partition"] = raw["partition"]
    return meta, True


def _npz_dtype_view(arr: np.ndarray, want: np.dtype) -> np.ndarray:
    """npz round-trips extension dtypes (bfloat16/fp8) as raw void with
    the same bytes — re-view them as the template dtype.  A genuine
    dtype mismatch still raises upstream (plan phase compares names)."""
    if arr.dtype != want and arr.dtype.kind == "V" \
            and arr.dtype.itemsize == want.itemsize:
        return arr.view(want)
    return arr


def plan_reshard(
    path: str | Path,
    like: Any,
    *,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    on_shape_mismatch: str = "reset",
) -> ReshardPlan:
    """Build the redistribution plan: per-unique-target-region transfer
    units, greedy-packed into buckets, plus the leaves that must be
    zero-reset (template shape differs from the saved shape — per-rank
    state whose physical layout is a function of the rule set)."""
    path = Path(path)
    meta, npz = _load_source_meta(path)
    return _plan_from_meta(
        path, meta, npz, like,
        bucket_bytes=bucket_bytes, on_shape_mismatch=on_shape_mismatch,
    )


def _plan_from_meta(
    path: Path,
    meta: dict,
    npz: bool,
    like: Any,
    *,
    bucket_bytes: int,
    on_shape_mismatch: str,
) -> ReshardPlan:
    leaves_like, _ = checkpoint._flatten_with_paths(like)
    saved_paths = [rec["path"] for rec in meta["leaves"]]
    if [k for k, _ in leaves_like] != saved_paths:
        raise ValueError(
            f"reshard source {path} structure mismatch: "
            f"{saved_paths[:3]}... vs {[k for k, _ in leaves_like][:3]}..."
        )
    units: list[_Unit] = []
    reset_leaves: dict[int, str] = {}
    largest_leaf = 0
    for i, ((keypath, tmpl), rec) in enumerate(
        zip(leaves_like, meta["leaves"], strict=True)
    ):
        t_shape, t_dtype = _leaf_shape_dtype(tmpl)
        s_shape, s_dtype = tuple(rec["shape"]), np.dtype(rec["dtype"])
        if t_shape != tuple(s_shape):
            if on_shape_mismatch != "reset":
                raise ValueError(
                    f"leaf {keypath}: saved shape {tuple(s_shape)} vs "
                    f"template shape {t_shape} (on_shape_mismatch="
                    f"{on_shape_mismatch!r})"
                )
            reset_leaves[i] = keypath
            continue
        if s_dtype != t_dtype:
            raise ValueError(
                f"leaf {keypath}: saved dtype {s_dtype} vs template "
                f"dtype {t_dtype} — redistribution never casts"
            )
        sharding = _leaf_sharding(tmpl)
        if sharding is None:
            nbytes = int(np.prod(t_shape, dtype=np.int64)) * t_dtype.itemsize
            units.append(
                _Unit(i, keypath, tuple((0, d) for d in t_shape),
                      int(nbytes), ())
            )
            largest_leaf = max(largest_leaf, int(nbytes))
            continue
        # One unit per unique target region on THIS process's devices;
        # replicas (several devices, same region) share the unit.
        addressable = set(sharding.addressable_devices)
        indices = sharding.devices_indices_map(t_shape)
        regions: dict[tuple, list] = {}
        for dev in sorted(addressable, key=lambda d: d.id):
            bounds = checkpoint._norm_index(indices[dev], t_shape)
            regions.setdefault(bounds, []).append(dev)
        for bounds, devs in regions.items():
            n = int(np.prod([hi - lo for lo, hi in bounds], dtype=np.int64)
                    ) if bounds else 1
            units.append(
                _Unit(i, keypath, bounds, n * t_dtype.itemsize, tuple(devs))
            )
        nbytes = int(np.prod(t_shape, dtype=np.int64)) * t_dtype.itemsize
        largest_leaf = max(largest_leaf, int(nbytes))
    # Greedy packing in leaf order (units of one leaf stay adjacent —
    # the npz reader's one-leaf cache relies on it).
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    largest = 0
    for j, u in enumerate(units):
        if cur and cur_bytes + u.nbytes > bucket_bytes:
            buckets.append(cur)
            largest = max(largest, cur_bytes)
            cur, cur_bytes = [], 0
        cur.append(j)
        cur_bytes += u.nbytes
    if cur:
        buckets.append(cur)
        largest = max(largest, cur_bytes)
    if npz:
        # The decompression cache holds one full leaf at a time.
        largest = max(largest, largest_leaf)
    return ReshardPlan(
        path=path,
        step=int(meta["step"]),
        source=meta.get("partition"),
        npz=npz,
        units=units,
        buckets=buckets,
        reset_leaves=reset_leaves,
        bytes_to_move=sum(u.nbytes for u in units),
        bucket_bytes=bucket_bytes,
        largest_bucket_bytes=largest,
    )


def _intersects(shard: dict, bounds: tuple[tuple[int, int], ...]) -> bool:
    return all(
        max(int(o), lo) < min(int(o) + int(s), hi)
        for (lo, hi), o, s in zip(bounds, shard["offset"], shard["shape"])
    )


def _verify_source(path: Path, plan: ReshardPlan, meta: dict) -> int:
    """Integrity pass before any byte moves.  Sharded dirs: every blob
    file intersecting a needed region must pass `_verify_blob` (size +
    embedded sha256).  npz: re-hash the stored leaves against the tree
    digest.  Returns the number of artifacts checked."""
    if plan.npz:
        digest = meta.get("digest")
        if digest is None:
            return 0  # digest-less legacy snapshot: nothing to check
        with np.load(path, allow_pickle=False) as z:
            paths = [rec["path"] for rec in meta["leaves"]]
            leaves = [z[f"leaf_{i}"] for i in range(len(paths))]
            if checkpoint._tree_digest(paths, leaves) != digest:
                raise ValueError(
                    f"{path} failed checksum validation (truncated or "
                    "corrupt)"
                )
        return len(paths)
    files: dict[tuple[int, str], tuple[Path, np.dtype]] = {}
    for u in plan.units:
        rec = meta["leaves"][u.leaf]
        dtype = np.dtype(rec["dtype"])
        for shard in rec["shards"]:
            if _intersects(shard, u.bounds):
                files[(u.leaf, shard["file"])] = (
                    path / f"leaf_{u.leaf}" / shard["file"], dtype
                )
    for (leaf_i, name), (f, dtype) in sorted(files.items()):
        if not checkpoint._verify_blob(f, dtype):
            raise ValueError(
                f"shard blob {f} failed integrity verification "
                "(missing, truncated, or embedded-digest mismatch)"
            )
    return len(files)


class _DirReader:
    """Region reads from a sharded-dir source.  Holds exactly the bytes
    of the in-flight region on the meter."""

    def __init__(self, path: Path, meta: dict, meter):
        self.path = path
        self.meta = meta
        self.meter = meter

    def read(self, u: _Unit) -> np.ndarray:
        rec = self.meta["leaves"][u.leaf]
        self.meter.hold(u.nbytes)
        return checkpoint._read_region(
            self.path / f"leaf_{u.leaf}", rec, u.bounds,
            np.dtype(rec["dtype"]),
        )

    def done(self, u: _Unit) -> None:
        self.meter.release(u.nbytes)

    def close(self) -> None:
        pass


class _NpzReader:
    """Region reads from a monolithic npz source via a one-leaf
    decompression cache (units arrive in leaf order, so each leaf is
    decompressed exactly once; the cache bytes sit on the meter for the
    leaf's lifetime and regions are served as views)."""

    def __init__(self, path: Path, meta: dict, like_dtypes: list, meter):
        self.z = np.load(path, allow_pickle=False)
        self.meta = meta
        self.like_dtypes = like_dtypes
        self.meter = meter
        self.cache_leaf: int | None = None
        self.cache: np.ndarray | None = None

    def _evict(self) -> None:
        if self.cache is not None:
            self.meter.release(self.cache.nbytes)
            self.cache, self.cache_leaf = None, None

    def read(self, u: _Unit) -> np.ndarray:
        if self.cache_leaf != u.leaf:
            self._evict()
            arr = np.asarray(self.z[f"leaf_{u.leaf}"])
            arr = _npz_dtype_view(arr, self.like_dtypes[u.leaf])
            self.meter.hold(arr.nbytes)
            self.cache, self.cache_leaf = arr, u.leaf
        sel = tuple(slice(lo, hi) for lo, hi in u.bounds)
        return self.cache[sel]

    def done(self, u: _Unit) -> None:
        pass  # cache-owned; released on evict/close

    def close(self) -> None:
        self._evict()
        self.z.close()


def _zero_leaf(tmpl: Any):
    """Zero-initialized replacement for a shape-mismatched leaf, under
    the template's target sharding (device leaves) or as numpy (host)."""
    import jax

    shape, dtype = _leaf_shape_dtype(tmpl)
    sharding = _leaf_sharding(tmpl)
    if sharding is None:
        return np.zeros(shape, dtype)

    def cb(index):
        b = checkpoint._norm_index(index, shape)
        return np.zeros(tuple(hi - lo for lo, hi in b), dtype)

    return jax.make_array_from_callback(shape, sharding, cb)


def redistribute(
    path: str | Path,
    like: Any,
    *,
    target_partition: dict | None = None,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    verify: bool = True,
    on_shape_mismatch: str = "reset",
    logger=None,
    sampler=None,
) -> tuple[Any, int]:
    """Redistribute a saved checkpoint (sharded dir or npz, any source
    mesh / rule set) onto the shardings of ``like`` — the elastic-resume
    engine.  Returns ``(tree, step)`` like the restore functions.

    ``like`` supplies structure, shapes, dtypes, AND target shardings
    (live ``jax.Array`` state or `target_templates` output).  Peak
    transient host bytes are hard-bounded at ``2 × largest bucket``
    (`ReshardPlan.bound_bytes`) — exceeded is an error, not a warning.
    ``target_partition`` (a `parallel.partition_summary`) is recorded in
    the emitted ``reshard`` event next to the saved provenance.  A
    failure in any phase raises `ReshardError` whose ``phase`` names the
    dying phase, mirrored by the flight-ring trail."""
    import jax

    from tpu_dist.observe import events as ev_mod
    from tpu_dist.observe import flightrec
    from tpu_dist.observe import memory as mem_mod

    path = Path(path)
    ring = flightrec.get()
    log = logger if logger is not None else ev_mod.from_env()
    t0 = time.monotonic()
    phase = "plan"
    meter = None
    plan = None

    def _mark(p: str, **fields) -> None:
        ring.record("mark", what="reshard", phase=p, path=str(path), **fields)

    try:
        _mark("plan")
        meta, npz = _load_source_meta(path)
        plan = _plan_from_meta(
            path, meta, npz, like,
            bucket_bytes=bucket_bytes, on_shape_mismatch=on_shape_mismatch,
        )
        if verify:
            phase = "verify"
            _mark("verify", units=len(plan.units))
            _verify_source(path, plan, meta)
        phase = "stream"
        meter = mem_mod.TransientMeter(limit_bytes=plan.bound_bytes)
        if sampler is None:
            sampler = mem_mod.WatermarkSampler(flight=ring)
        leaves_like, treedef = checkpoint._flatten_with_paths(like)
        if plan.npz:
            reader = _NpzReader(
                path, meta,
                [_leaf_shape_dtype(t)[1] for _, t in leaves_like], meter,
            )
        else:
            reader = _DirReader(path, meta, meter)
        out: dict[int, Any] = {
            i: _zero_leaf(leaves_like[i][1]) for i in plan.reset_leaves
        }
        pending: dict[int, int] = {}
        for u in plan.units:
            pending[u.leaf] = pending.get(u.leaf, 0) + 1
        placements: dict[int, list] = {}
        try:
            for b, bucket in enumerate(plan.buckets):
                _mark("stream", bucket=b, units=len(bucket),
                      bytes=sum(plan.units[j].nbytes for j in bucket))
                for j in bucket:
                    u = plan.units[j]
                    region = reader.read(u)
                    if u.devices:
                        parts = placements.setdefault(u.leaf, [])
                        for dev in u.devices:
                            parts.append(jax.device_put(region, dev))
                    else:
                        # Host leaf: the assembled region IS the output
                        # (copy out of the npz cache — views die on
                        # evict), committed, no longer transient.
                        out[u.leaf] = (
                            np.array(region) if plan.npz else region
                        )
                    reader.done(u)
                    pending[u.leaf] -= 1
                    if pending[u.leaf] == 0 and u.leaf in placements:
                        tmpl = leaves_like[u.leaf][1]
                        shape, _ = _leaf_shape_dtype(tmpl)
                        out[u.leaf] = (
                            jax.make_array_from_single_device_arrays(
                                shape, _leaf_sharding(tmpl),
                                placements.pop(u.leaf),
                            )
                        )
                sampler.sample("reshard")
        finally:
            reader.close()
        phase = "commit"
        _mark("commit", leaves=len(leaves_like))
        if len(out) != len(leaves_like):
            missing = [
                kp for i, (kp, _) in enumerate(leaves_like) if i not in out
            ]
            raise ValueError(
                f"redistribution left {len(missing)} leaf/leaves "
                f"unassembled (e.g. {missing[0]})"
            )
        tree = jax.tree_util.tree_unflatten(
            treedef, [out[i] for i in range(len(leaves_like))]
        )
        seconds = time.monotonic() - t0
        log.emit(
            "reshard",
            source=plan.source,
            target=target_partition,
            bytes_moved=plan.bytes_to_move,
            peak_bytes=meter.peak,
            seconds=seconds,
            status="ok",
            step=plan.step,
            path=str(path),
            units=len(plan.units),
            buckets=len(plan.buckets),
            bound_bytes=plan.bound_bytes,
            leaves_reset=sorted(plan.reset_leaves.values()),
            watermark=sampler.summary(),
        )
        _mark("done", seconds=seconds, bytes_moved=plan.bytes_to_move,
              peak_bytes=meter.peak)
        return tree, plan.step
    except ReshardError:
        raise
    except Exception as e:
        _mark("failed", failed_phase=phase, error=f"{type(e).__name__}: {e}")
        try:
            log.emit(
                "reshard",
                source=plan.source if plan is not None else None,
                target=target_partition,
                bytes_moved=plan.bytes_to_move if plan is not None else 0,
                peak_bytes=meter.peak if meter is not None else 0,
                seconds=time.monotonic() - t0,
                status="failed",
                failed_phase=phase,
                error=f"{type(e).__name__}: {e}",
                path=str(path),
            )
        except Exception:
            pass  # telemetry must not mask the real failure
        raise ReshardError(
            f"redistribution of {path} failed in phase {phase!r}: {e}",
            phase=phase,
        ) from e


def target_templates(like: Any, rules, mesh) -> Any:
    """Template tree for `redistribute`: shapes/dtypes from ``like``
    (live arrays, numpy, or `jax.ShapeDtypeStruct`s), target shardings
    from matching ``rules`` (a rule iterable or a `parallel.RuleSet`,
    whose param rules are used) on the TARGET mesh."""
    import jax
    from jax.sharding import NamedSharding

    rules = getattr(rules, "param_rules", rules)
    from tpu_dist.parallel.partition import match_partition_rules

    specs = match_partition_rules(rules, like, mesh)

    def to_tmpl(leaf, spec):
        shape, dtype = _leaf_shape_dtype(leaf)
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, spec)
        )

    return jax.tree_util.tree_map(to_tmpl, like, specs)


def restore_or_redistribute(
    path: str | Path,
    like: Any,
    expected_partition: dict,
    *,
    where: str = "restore",
    logger=None,
) -> tuple[Any, int, bool]:
    """The engine trainers' resume route.  Compatible provenance
    (identical, or a same-rules/same-axes world resize) takes the direct
    `checkpoint.restore_fsdp` path; any rule-set or topology change is
    redistributed onto ``like``'s shardings.  Returns
    ``(tree, step, resharded)``."""
    path = Path(path)
    meta = checkpoint.read_meta(path) if path.is_dir() else \
        _load_source_meta(path)[0]
    if checkpoint.partition_mismatch(meta, expected_partition, where=where):
        tree, step = redistribute(
            path, like, target_partition=expected_partition, logger=logger
        )
        return tree, step, True
    tree, step = checkpoint.restore_fsdp(path, like)
    return tree, step, False
