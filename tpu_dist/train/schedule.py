"""Learning-rate schedules.

Not in the reference (fixed lr=0.01, train_dist.py:110 — the default here
remains a constant schedule so parity runs are untouched), but the
extended configs (ViT especially) need warmup + decay.  A schedule is just
``f(step) -> lr`` evaluated inside the compiled update, so it costs
nothing at runtime.
"""

from __future__ import annotations

import jax.numpy as jnp


def constant(lr: float):
    return lambda step: jnp.float32(lr)


def cosine(base_lr: float, total_steps: int, *, warmup_steps: int = 0):
    """Linear warmup to ``base_lr`` then cosine decay to zero."""
    if total_steps <= warmup_steps:
        raise ValueError(
            f"total_steps {total_steps} must exceed warmup_steps {warmup_steps}"
        )

    def f(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base_lr * step / jnp.maximum(warmup_steps, 1)
        progress = (step - warmup_steps) / (total_steps - warmup_steps)
        progress = jnp.clip(progress, 0.0, 1.0)
        decayed = base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, decayed)

    return f


def step_decay(base_lr: float, *, gamma: float = 0.1, every: int = 30):
    """Multiply by ``gamma`` every ``every`` steps (epoch-style decay)."""

    def f(step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / every)
        return base_lr * gamma**k

    return f
